// C-ABI compatibility shim: a subset of the reference's `LGBM_*` surface
// (ref: include/LightGBM/c_api.h, 98 exported functions; this shim covers 97
// covering dataset/booster lifecycle, streaming push (ChunkedArray flow),
// fast single-row predict configs, and model surgery — backed by the lightgbm_tpu Python framework
// through an embedded CPython interpreter.
//
// Design: every entry point forwards to lightgbm_tpu.capi with raw
// pointers passed as integers; that module wraps them with ctypes/NumPy
// and drives the ordinary Python API. Handles returned to C callers are
// small registry integers cast to opaque pointers — the same contract as
// the reference's DatasetHandle/BoosterHandle (c_api.h:28-34).
//
// Concurrency contract: the reference guards its Booster with
// shared/unique locks (c_api.cpp:170), which lets concurrent predicts
// proceed in parallel with each other (shared) while train iterations
// take the unique lock. Here the GIL serves the lock role: every entry
// point takes PyGILState_Ensure, so concurrent callers are SAFE but
// FULLY SERIALIZED — including predict-during-train, which the
// reference would run concurrently. The practical difference is small:
// the heavy compute runs inside XLA executables that hold the GIL for
// their (host-side) duration anyway, and TPU deployments get
// parallelism from batching rather than caller threads. Multi-threaded
// C consumers needing overlapped predict should batch rows per call or
// run separate processes.

#include <Python.h>

#include <cstdint>
#include <functional>
#include <utility>
#include <vector>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

#define LGBM_API extern "C" __attribute__((visibility("default")))

typedef void* DatasetHandle;
typedef void* BoosterHandle;

static thread_local std::string g_last_error = "everything is fine";
static PyObject* g_capi_module = nullptr;
static std::once_flag g_py_once;

LGBM_API const char* LGBM_GetLastError() { return g_last_error.c_str(); }

namespace {

void EnsureInterpreter() {
  std::call_once(g_py_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // Release the GIL taken by Py_Initialize so PyGILState_Ensure
      // works uniformly from every (including this) thread.
      PyEval_SaveThread();
    }
  });
}

// RAII GIL + lazy import of lightgbm_tpu.capi.
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }
  Gil(const Gil&) = delete;
  Gil& operator=(const Gil&) = delete;

 private:
  PyGILState_STATE state_;
};

PyObject* CapiModule() {
  if (g_capi_module == nullptr) {
    g_capi_module = PyImport_ImportModule("lightgbm_tpu.capi");
  }
  return g_capi_module;
}

std::string FetchPyError() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

// Call lightgbm_tpu.capi.<fn>(args...) and return the result (new ref),
// or nullptr with g_last_error set.
PyObject* Call(const char* fn, const char* fmt, ...) {
  PyObject* mod = CapiModule();
  if (mod == nullptr) {
    g_last_error = "failed to import lightgbm_tpu.capi: " + FetchPyError();
    return nullptr;
  }
  PyObject* func = PyObject_GetAttrString(mod, fn);
  if (func == nullptr) {
    PyErr_Clear();  // a pending exception would poison later calls
    g_last_error = std::string("missing capi function ") + fn;
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  PyObject* result = nullptr;
  if (args != nullptr) {
    result = PyObject_CallObject(func, args);
    Py_DECREF(args);
  }
  Py_DECREF(func);
  if (result == nullptr) {
    g_last_error = std::string(fn) + ": " + FetchPyError();
    return nullptr;
  }
  return result;
}

int HandleResult(PyObject* r) {
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int64_t AsHandleInt(void* h) { return reinterpret_cast<intptr_t>(h); }

}  // namespace

// -- dataset ---------------------------------------------------------------

LGBM_API int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                                       int32_t nrow, int32_t ncol,
                                       int is_row_major,
                                       const char* parameters,
                                       const DatasetHandle reference,
                                       DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_mat", "(LiiiisL)",
                     (long long)(intptr_t)data, data_type, (int)nrow,
                     (int)ncol, is_row_major, parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col,
                                       const char* parameters,
                                       const DatasetHandle reference,
                                       DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_csr", "(LiLLiLLLsL)",
                     (long long)(intptr_t)indptr, indptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)nindptr, (long long)nelem,
                     (long long)num_col, parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetCreateFromFile(const char* filename,
                                        const char* parameters,
                                        const DatasetHandle reference,
                                        DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_file", "(ssL)", filename,
                     parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetSetField(DatasetHandle handle,
                                  const char* field_name,
                                  const void* field_data, int num_element,
                                  int type) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_set_field", "(LsLii)",
                           (long long)AsHandleInt(handle), field_name,
                           (long long)(intptr_t)field_data, num_element,
                           type));
}

LGBM_API int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_num_data", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_num_feature", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetFree(DatasetHandle handle) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("handle_free", "(L)",
                           (long long)AsHandleInt(handle)));
}

// -- booster ---------------------------------------------------------------

LGBM_API int LGBM_BoosterCreate(const DatasetHandle train_data,
                                const char* parameters,
                                BoosterHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_create", "(Ls)",
                     (long long)AsHandleInt(train_data),
                     parameters ? parameters : "");
  if (r == nullptr) return -1;
  *out = reinterpret_cast<BoosterHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterCreateFromModelfile(const char* filename,
                                             int* out_num_iterations,
                                             BoosterHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_create_from_modelfile", "(s)", filename);
  if (r == nullptr) return -1;
  long long handle = 0;
  int iters = 0;
  if (!PyArg_ParseTuple(r, "Li", &handle, &iters)) {
    PyErr_Clear();  // a pending exception would poison later calls
    Py_DECREF(r);
    g_last_error = "bad tuple from booster_create_from_modelfile";
    return -1;
  }
  Py_DECREF(r);
  *out = reinterpret_cast<BoosterHandle>((intptr_t)handle);
  *out_num_iterations = iters;
  return 0;
}

LGBM_API int LGBM_BoosterAddValidData(BoosterHandle handle,
                                      const DatasetHandle valid_data) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_add_valid_data", "(LL)",
                           (long long)AsHandleInt(handle),
                           (long long)AsHandleInt(valid_data)));
}

LGBM_API int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                       int* is_finished) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_update_one_iter", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *is_finished = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                             int* out_iteration) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_current_iteration", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out_iteration = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_eval_counts", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out_len = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx,
                                 int* out_len, double* out_results) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_eval", "(LiL)",
                     (long long)AsHandleInt(handle), data_idx,
                     (long long)(intptr_t)out_results);
  if (r == nullptr) return -1;
  *out_len = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForMat(BoosterHandle handle,
                                       const void* data, int data_type,
                                       int32_t nrow, int32_t ncol,
                                       int is_row_major, int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_mat", "(LLiiiiiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)data, data_type, (int)nrow,
                     (int)ncol, is_row_major, predict_type,
                     start_iteration, num_iteration,
                     (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForCSR(BoosterHandle handle,
                                       const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col, int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_csr", "(LLiLLiLLLiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)indptr, indptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)nindptr, (long long)nelem,
                     (long long)num_col, predict_type, start_iteration,
                     num_iteration, (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterSaveModel(BoosterHandle handle,
                                   int start_iteration, int num_iteration,
                                   int feature_importance_type,
                                   const char* filename) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_save_model", "(Liiis)",
                           (long long)AsHandleInt(handle), start_iteration,
                           num_iteration, feature_importance_type,
                           filename));
}

LGBM_API int LGBM_BoosterSaveModelToString(BoosterHandle handle,
                                           int start_iteration,
                                           int num_iteration,
                                           int feature_importance_type,
                                           int64_t buffer_len,
                                           int64_t* out_len,
                                           char* out_str) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_save_model_to_string", "(Liii)",
                     (long long)AsHandleInt(handle), start_iteration,
                     num_iteration, feature_importance_type);
  if (r == nullptr) return -1;
  Py_ssize_t size = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &size);
  if (s == nullptr) {
    Py_DECREF(r);
    g_last_error = "model string encode failed";
    return -1;
  }
  *out_len = (int64_t)size + 1;  // including trailing '\0', like the ref
  if (buffer_len >= size + 1) {
    std::memcpy(out_str, s, size + 1);
  }
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_num_feature", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterFree(BoosterHandle handle) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("handle_free", "(L)",
                           (long long)AsHandleInt(handle)));
}

// -- streaming dataset construction (ref: c_api.cpp:1330 PushRows family,
// test scenarios: tests/cpp_tests/test_stream.cpp:253,304) ----------------

LGBM_API int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                           int64_t num_total_row,
                                           DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_by_reference", "(LL)",
                     (long long)AsHandleInt(reference),
                     (long long)num_total_row);
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetCreateFromSampledColumn(
    double** sample_data, int** sample_indices, int32_t ncol,
    const int32_t* num_per_col, int32_t num_sample_row,
    int32_t num_local_row, int64_t num_dist_row, const char* parameters,
    DatasetHandle* out) {
  (void)num_dist_row;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_sampled_column", "(LLiLiis)",
                     (long long)(intptr_t)sample_data,
                     (long long)(intptr_t)sample_indices, (int)ncol,
                     (long long)(intptr_t)num_per_col, (int)num_sample_row,
                     (int)num_local_row, parameters ? parameters : "");
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetInitStreaming(DatasetHandle dataset,
                                       int32_t has_weights,
                                       int32_t has_init_scores,
                                       int32_t has_queries,
                                       int32_t nclasses, int32_t nthreads,
                                       int32_t omp_max_threads) {
  (void)nthreads;
  (void)omp_max_threads;
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_init_streaming", "(Liiii)",
                           (long long)AsHandleInt(dataset),
                           (int)has_weights, (int)has_init_scores,
                           (int)has_queries, (int)nclasses));
}

LGBM_API int LGBM_DatasetPushRows(DatasetHandle dataset, const void* data,
                                  int data_type, int32_t nrow,
                                  int32_t ncol, int32_t start_row) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_push_rows", "(LLiiii)",
                           (long long)AsHandleInt(dataset),
                           (long long)(intptr_t)data, data_type, (int)nrow,
                           (int)ncol, (int)start_row));
}

LGBM_API int LGBM_DatasetPushRowsWithMetadata(
    DatasetHandle dataset, const void* data, int data_type, int32_t nrow,
    int32_t ncol, int32_t start_row, const float* label,
    const float* weight, const double* init_score, const int32_t* query,
    int32_t tid) {
  (void)tid;
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_push_rows_with_metadata", "(LLiiiiLLLL)",
                           (long long)AsHandleInt(dataset),
                           (long long)(intptr_t)data, data_type, (int)nrow,
                           (int)ncol, (int)start_row,
                           (long long)(intptr_t)label,
                           (long long)(intptr_t)weight,
                           (long long)(intptr_t)init_score,
                           (long long)(intptr_t)query));
}

LGBM_API int LGBM_DatasetPushRowsByCSR(DatasetHandle dataset,
                                       const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col, int64_t start_row) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_push_rows_by_csr", "(LLiLLiLLLL)",
                           (long long)AsHandleInt(dataset),
                           (long long)(intptr_t)indptr, indptr_type,
                           (long long)(intptr_t)indices,
                           (long long)(intptr_t)data, data_type,
                           (long long)nindptr, (long long)nelem,
                           (long long)num_col, (long long)start_row));
}

LGBM_API int LGBM_DatasetPushRowsByCSRWithMetadata(
    DatasetHandle dataset, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t start_row, const float* label,
    const float* weight, const double* init_score, const int32_t* query,
    int32_t tid) {
  (void)tid;
  EnsureInterpreter();
  Gil gil;
  return HandleResult(
      Call("dataset_push_rows_by_csr_with_metadata", "(LLiLLiLLLLLLL)",
           (long long)AsHandleInt(dataset), (long long)(intptr_t)indptr,
           indptr_type, (long long)(intptr_t)indices,
           (long long)(intptr_t)data, data_type, (long long)nindptr,
           (long long)nelem, (long long)start_row,
           (long long)(intptr_t)label, (long long)(intptr_t)weight,
           (long long)(intptr_t)init_score, (long long)(intptr_t)query));
}

LGBM_API int LGBM_DatasetSetWaitForManualFinish(DatasetHandle dataset,
                                                int wait) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_set_wait_for_manual_finish", "(Li)",
                           (long long)AsHandleInt(dataset), wait));
}

LGBM_API int LGBM_DatasetMarkFinished(DatasetHandle dataset) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_mark_finished", "(L)",
                           (long long)AsHandleInt(dataset)));
}

LGBM_API int LGBM_GetSampleCount(int32_t num_total_row,
                                 const char* parameters, int* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("get_sample_count", "(is)", (int)num_total_row,
                     parameters ? parameters : "");
  if (r == nullptr) return -1;
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_SampleIndices(int32_t num_total_row,
                                const char* parameters, void* out,
                                int32_t* out_len) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("sample_indices", "(isL)", (int)num_total_row,
                     parameters ? parameters : "",
                     (long long)(intptr_t)out);
  if (r == nullptr) return -1;
  *out_len = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

// -- dataset field access / utilities --------------------------------------

LGBM_API int LGBM_DatasetGetField(DatasetHandle handle,
                                  const char* field_name, int* out_len,
                                  const void** out_ptr, int* out_type) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_get_field", "(Ls)",
                     (long long)AsHandleInt(handle), field_name);
  if (r == nullptr) return -1;
  long long ptr = 0;
  int len = 0, code = 0;
  if (!PyArg_ParseTuple(r, "Lii", &ptr, &len, &code)) {
    PyErr_Clear();
    Py_DECREF(r);
    g_last_error = "bad tuple from dataset_get_field";
    return -1;
  }
  Py_DECREF(r);
  *out_ptr = reinterpret_cast<const void*>((intptr_t)ptr);
  *out_len = len;
  *out_type = code;
  return 0;
}

namespace {
// Copy a Python list of str into the (len, out_len, buffer_len,
// out_buffer_len, out_strs) contract shared by the *GetFeatureNames /
// GetEvalNames entry points (ref: c_api.cpp:2308).
int CopyStringList(PyObject* list, const int len, int* out_len,
                   const size_t buffer_len, size_t* out_buffer_len,
                   char** out_strs) {
  if (list == nullptr) return -1;
  Py_ssize_t n = PyList_Size(list);
  *out_len = (int)n;
  size_t need = 1;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* s = PyList_GetItem(list, i);  // borrowed
    Py_ssize_t sz = 0;
    const char* c = PyUnicode_AsUTF8AndSize(s, &sz);
    if (c == nullptr) {
      Py_DECREF(list);
      g_last_error = "string encode failed";
      return -1;
    }
    if ((size_t)(sz + 1) > need) need = (size_t)(sz + 1);
    if (i < len && out_strs != nullptr) {
      size_t ncopy = (size_t)sz + 1 <= buffer_len ? (size_t)sz + 1
                                                  : buffer_len;
      if (ncopy > 0 && out_strs[i] != nullptr) {
        std::memcpy(out_strs[i], c, ncopy);
        out_strs[i][ncopy - 1] = '\0';
      }
    }
  }
  *out_buffer_len = need;
  Py_DECREF(list);
  return 0;
}
}  // namespace

LGBM_API int LGBM_DatasetGetFeatureNames(DatasetHandle handle,
                                         const int len,
                                         int* num_feature_names,
                                         const size_t buffer_len,
                                         size_t* out_buffer_len,
                                         char** feature_names) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_get_feature_names", "(L)",
                     (long long)AsHandleInt(handle));
  return CopyStringList(r, len, num_feature_names, buffer_len,
                        out_buffer_len, feature_names);
}

LGBM_API int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                         const char** feature_names,
                                         int num_feature_names) {
  EnsureInterpreter();
  Gil gil;
  PyObject* list = PyList_New(num_feature_names);
  for (int i = 0; i < num_feature_names; ++i) {
    PyList_SetItem(list, i, PyUnicode_FromString(feature_names[i]));
  }
  PyObject* r = Call("dataset_set_feature_names", "(LO)",
                     (long long)AsHandleInt(handle), list);
  Py_DECREF(list);
  return HandleResult(r);
}

LGBM_API int LGBM_DatasetGetFeatureNumBin(DatasetHandle handle, int feature,
                                          int* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_get_feature_num_bin", "(Li)",
                     (long long)AsHandleInt(handle), feature);
  if (r == nullptr) return -1;
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetSaveBinary(DatasetHandle handle,
                                    const char* filename) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_save_binary", "(Ls)",
                           (long long)AsHandleInt(handle), filename));
}

LGBM_API int LGBM_DatasetDumpText(DatasetHandle handle,
                                  const char* filename) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_dump_text", "(Ls)",
                           (long long)AsHandleInt(handle), filename));
}

LGBM_API int LGBM_DatasetGetSubset(const DatasetHandle handle,
                                   const int32_t* used_row_indices,
                                   int32_t num_used_row_indices,
                                   const char* parameters,
                                   DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_get_subset", "(LLis)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)used_row_indices,
                     (int)num_used_row_indices,
                     parameters ? parameters : "");
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetUpdateParamChecking(const char* old_parameters,
                                             const char* new_parameters) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_update_param_checking", "(ss)",
                           old_parameters ? old_parameters : "",
                           new_parameters ? new_parameters : ""));
}

// -- booster extras --------------------------------------------------------

LGBM_API int LGBM_BoosterLoadModelFromString(const char* model_str,
                                             int* out_num_iterations,
                                             BoosterHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_load_model_from_string", "(s)", model_str);
  if (r == nullptr) return -1;
  long long handle = 0;
  int iters = 0;
  if (!PyArg_ParseTuple(r, "Li", &handle, &iters)) {
    PyErr_Clear();
    Py_DECREF(r);
    g_last_error = "bad tuple from booster_load_model_from_string";
    return -1;
  }
  Py_DECREF(r);
  *out = reinterpret_cast<BoosterHandle>((intptr_t)handle);
  *out_num_iterations = iters;
  return 0;
}

LGBM_API int LGBM_BoosterResetParameter(BoosterHandle handle,
                                        const char* parameters) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_reset_parameter", "(Ls)",
                           (long long)AsHandleInt(handle),
                           parameters ? parameters : ""));
}

LGBM_API int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                           const DatasetHandle train_data) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_reset_training_data", "(LL)",
                           (long long)AsHandleInt(handle),
                           (long long)AsHandleInt(train_data)));
}

LGBM_API int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_rollback_one_iter", "(L)",
                           (long long)AsHandleInt(handle)));
}

namespace {
int IntGetter(const char* fn, BoosterHandle handle, int* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call(fn, "(L)", (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int DoubleGetter(const char* fn, BoosterHandle handle, double* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call(fn, "(L)", (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}
}  // namespace

LGBM_API int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len) {
  return IntGetter("booster_get_num_classes", handle, out_len);
}

LGBM_API int LGBM_BoosterNumModelPerIteration(BoosterHandle handle,
                                              int* out_tree_per_iteration) {
  return IntGetter("booster_num_model_per_iteration", handle,
                   out_tree_per_iteration);
}

LGBM_API int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle,
                                            int* out_models) {
  return IntGetter("booster_number_of_total_model", handle, out_models);
}

LGBM_API int LGBM_BoosterGetLinear(BoosterHandle handle, int* out) {
  return IntGetter("booster_get_linear", handle, out);
}

LGBM_API int LGBM_BoosterGetEvalNames(BoosterHandle handle, const int len,
                                      int* out_len, const size_t buffer_len,
                                      size_t* out_buffer_len,
                                      char** out_strs) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_eval_names", "(L)",
                     (long long)AsHandleInt(handle));
  return CopyStringList(r, len, out_len, buffer_len, out_buffer_len,
                        out_strs);
}

LGBM_API int LGBM_BoosterGetFeatureNames(BoosterHandle handle, const int len,
                                         int* out_len,
                                         const size_t buffer_len,
                                         size_t* out_buffer_len,
                                         char** out_strs) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_feature_names", "(L)",
                     (long long)AsHandleInt(handle));
  return CopyStringList(r, len, out_len, buffer_len, out_buffer_len,
                        out_strs);
}

LGBM_API int LGBM_BoosterValidateFeatureNames(BoosterHandle handle,
                                              const char** data_names,
                                              int data_num_features) {
  EnsureInterpreter();
  Gil gil;
  PyObject* list = PyList_New(data_num_features);
  for (int i = 0; i < data_num_features; ++i) {
    PyList_SetItem(list, i, PyUnicode_FromString(data_names[i]));
  }
  PyObject* r = Call("booster_validate_feature_names", "(LO)",
                     (long long)AsHandleInt(handle), list);
  Py_DECREF(list);
  return HandleResult(r);
}

LGBM_API int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                                        int predict_type,
                                        int start_iteration,
                                        int num_iteration,
                                        int64_t* out_len) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_calc_num_predict", "(Liiii)",
                     (long long)AsHandleInt(handle), num_row, predict_type,
                     start_iteration, num_iteration);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                                       int64_t* out_len) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_num_predict", "(Li)",
                     (long long)AsHandleInt(handle), data_idx);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                                    int64_t* out_len, double* out_result) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_predict", "(LiL)",
                     (long long)AsHandleInt(handle), data_idx,
                     (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForFile(BoosterHandle handle,
                                        const char* data_filename,
                                        int data_has_header,
                                        int predict_type,
                                        int start_iteration,
                                        int num_iteration,
                                        const char* parameter,
                                        const char* result_filename) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_predict_for_file", "(Lsiiiiss)",
                           (long long)AsHandleInt(handle), data_filename,
                           data_has_header, predict_type, start_iteration,
                           num_iteration, parameter ? parameter : "",
                           result_filename));
}

LGBM_API int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                                   int num_iteration,
                                   int feature_importance_type,
                                   int64_t buffer_len, int64_t* out_len,
                                   char* out_str) {
  (void)feature_importance_type;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_dump_model", "(Lii)",
                     (long long)AsHandleInt(handle), start_iteration,
                     num_iteration);
  if (r == nullptr) return -1;
  Py_ssize_t size = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &size);
  if (s == nullptr) {
    Py_DECREF(r);
    g_last_error = "model dump encode failed";
    return -1;
  }
  *out_len = (int64_t)size + 1;
  if (buffer_len >= size + 1) {
    std::memcpy(out_str, s, size + 1);
  }
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterFeatureImportance(BoosterHandle handle,
                                           int num_iteration,
                                           int importance_type,
                                           double* out_results) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_feature_importance", "(LiiL)",
                           (long long)AsHandleInt(handle), num_iteration,
                           importance_type,
                           (long long)(intptr_t)out_results));
}

LGBM_API int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                                      int leaf_idx, double* out_val) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_leaf_value", "(Lii)",
                     (long long)AsHandleInt(handle), tree_idx, leaf_idx);
  if (r == nullptr) return -1;
  *out_val = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                                      int leaf_idx, double val) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_set_leaf_value", "(Liid)",
                           (long long)AsHandleInt(handle), tree_idx,
                           leaf_idx, val));
}

LGBM_API int LGBM_BoosterGetUpperBoundValue(BoosterHandle handle,
                                            double* out_results) {
  return DoubleGetter("booster_get_upper_bound_value", handle, out_results);
}

LGBM_API int LGBM_BoosterGetLowerBoundValue(BoosterHandle handle,
                                            double* out_results) {
  return DoubleGetter("booster_get_lower_bound_value", handle, out_results);
}

LGBM_API int LGBM_BoosterShuffleModels(BoosterHandle handle, int start_iter,
                                       int end_iter) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_shuffle_models", "(Lii)",
                           (long long)AsHandleInt(handle), start_iter,
                           end_iter));
}

LGBM_API int LGBM_BoosterMerge(BoosterHandle handle,
                               BoosterHandle other_handle) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_merge", "(LL)",
                           (long long)AsHandleInt(handle),
                           (long long)AsHandleInt(other_handle)));
}

LGBM_API int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle,
                                             const float* grad,
                                             const float* hess,
                                             int* is_finished) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_update_one_iter_custom", "(LLL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)grad, (long long)(intptr_t)hess);
  if (r == nullptr) return -1;
  *is_finished = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterRefit(BoosterHandle handle, const int32_t* leaf_preds,
                               int32_t nrow, int32_t ncol) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_refit", "(LLii)",
                           (long long)AsHandleInt(handle),
                           (long long)(intptr_t)leaf_preds, (int)nrow,
                           (int)ncol));
}

// -- single-row / fast-path prediction (ref: c_api.cpp:2605-2625) ----------

typedef void* FastConfigHandle;

LGBM_API int LGBM_BoosterPredictForMatSingleRow(
    BoosterHandle handle, const void* data, int data_type, int ncol,
    int is_row_major, int predict_type, int start_iteration,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  (void)is_row_major;
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_mat_single_row", "(LLiiiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)data, data_type, ncol,
                     predict_type, start_iteration, num_iteration,
                     (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForMatSingleRowFastInit(
    BoosterHandle handle, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int32_t ncol,
    const char* parameter, FastConfigHandle* out_fastConfig) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("fast_config_init", "(Liiiii)",
                     (long long)AsHandleInt(handle), predict_type,
                     start_iteration, num_iteration, data_type, (int)ncol);
  if (r == nullptr) return -1;
  *out_fastConfig =
      reinterpret_cast<FastConfigHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForMatSingleRowFast(
    FastConfigHandle fastConfig_handle, const void* data, int64_t* out_len,
    double* out_result) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_single_row_fast", "(LLL)",
                     (long long)AsHandleInt(fastConfig_handle),
                     (long long)(intptr_t)data,
                     (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForCSRSingleRow(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_csr_single_row", "(LLiLLiLLLiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)indptr, indptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)nindptr, (long long)nelem,
                     (long long)num_col, predict_type, start_iteration,
                     num_iteration, (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForCSRSingleRowFastInit(
    BoosterHandle handle, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int64_t num_col,
    const char* parameter, FastConfigHandle* out_fastConfig) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("fast_config_init", "(Liiiii)",
                     (long long)AsHandleInt(handle), predict_type,
                     start_iteration, num_iteration, data_type,
                     (int)num_col);
  if (r == nullptr) return -1;
  *out_fastConfig =
      reinterpret_cast<FastConfigHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForCSRSingleRowFast(
    FastConfigHandle fastConfig_handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int64_t nindptr, int64_t nelem,
    int64_t* out_len, double* out_result) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_csr_single_row_fast", "(LLiLLLLL)",
                     (long long)AsHandleInt(fastConfig_handle),
                     (long long)(intptr_t)indptr, indptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, (long long)nindptr,
                     (long long)nelem, (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_FastConfigFree(FastConfigHandle fastConfig) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("handle_free", "(L)",
                           (long long)AsHandleInt(fastConfig)));
}

LGBM_API int LGBM_BoosterPredictForMats(BoosterHandle handle,
                                        const void** data, int data_type,
                                        int32_t nrow, int32_t ncol,
                                        int predict_type,
                                        int start_iteration,
                                        int num_iteration,
                                        const char* parameter,
                                        int64_t* out_len,
                                        double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_mats", "(LLiiiiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)data, data_type, (int)nrow,
                     (int)ncol, predict_type, start_iteration,
                     num_iteration, (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

// -- global utilities ------------------------------------------------------

LGBM_API int LGBM_SetMaxThreads(int num_threads) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("set_max_threads", "(i)", num_threads));
}

LGBM_API int LGBM_GetMaxThreads(int* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("get_max_threads", "()");
  if (r == nullptr) return -1;
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DumpParamAliases(int64_t buffer_len, int64_t* out_len,
                                   char* out_str) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dump_param_aliases", "()");
  if (r == nullptr) return -1;
  Py_ssize_t size = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &size);
  if (s == nullptr) {
    Py_DECREF(r);
    g_last_error = "alias dump encode failed";
    return -1;
  }
  *out_len = (int64_t)size + 1;
  if (buffer_len >= size + 1) {
    std::memcpy(out_str, s, size + 1);
  }
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_RegisterLogCallback(void (*callback)(const char*)) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("register_log_callback", "(L)",
                           (long long)(intptr_t)callback));
}

LGBM_API int LGBM_NetworkInit(const char* machines, int local_listen_port,
                              int listen_time_out, int num_machines) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("network_init", "(siii)",
                           machines ? machines : "", local_listen_port,
                           listen_time_out, num_machines));
}

LGBM_API int LGBM_NetworkFree() {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("network_free", "()"));
}

// -- serialized dataset reference + ByteBuffer (ref: c_api.h:117,545) ------

typedef void* ByteBufferHandle;

LGBM_API int LGBM_DatasetSerializeReferenceToBinary(DatasetHandle handle,
                                                    ByteBufferHandle* out,
                                                    int32_t* out_len) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_serialize_reference", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  long long buf = PyLong_AsLongLong(r);
  Py_DECREF(r);
  PyObject* sz = Call("byte_buffer_size", "(L)", buf);
  if (sz == nullptr) return -1;
  *out_len = (int32_t)PyLong_AsLong(sz);
  Py_DECREF(sz);
  *out = reinterpret_cast<ByteBufferHandle>((intptr_t)buf);
  return 0;
}

LGBM_API int LGBM_ByteBufferGetAt(ByteBufferHandle handle, int32_t index,
                                  uint8_t* out_val) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("byte_buffer_get_at", "(Li)",
                     (long long)AsHandleInt(handle), (int)index);
  if (r == nullptr) return -1;
  *out_val = (uint8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_ByteBufferFree(ByteBufferHandle handle) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("handle_free", "(L)",
                           (long long)AsHandleInt(handle)));
}

LGBM_API int LGBM_DatasetCreateFromSerializedReference(
    const void* ref_buffer, int32_t ref_buffer_size, int64_t num_row,
    int32_t num_classes, const char* parameters, DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_serialized_reference", "(LiLis)",
                     (long long)(intptr_t)ref_buffer, (int)ref_buffer_size,
                     (long long)num_row, (int)num_classes,
                     parameters ? parameters : "");
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetLoadedParam(BoosterHandle handle,
                                        int64_t buffer_len,
                                        int64_t* out_len, char* out_str) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_loaded_param", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  Py_ssize_t size = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &size);
  if (s == nullptr) {
    Py_DECREF(r);
    g_last_error = "param dump encode failed";
    return -1;
  }
  *out_len = (int64_t)size + 1;
  if (buffer_len >= size + 1) {
    std::memcpy(out_str, s, size + 1);
  }
  Py_DECREF(r);
  return 0;
}

// -- sparse (CSR) prediction output (ref: c_api.h:1117) --------------------

LGBM_API int LGBM_BoosterPredictSparseOutput(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col_or_row,
    int predict_type, int start_iteration, int num_iteration,
    const char* parameter, int matrix_type, int64_t* out_len,
    void** out_indptr, int32_t** out_indices, void** out_data) {
  (void)parameter;
  if (matrix_type != 0 /* C_API_MATRIX_TYPE_CSR */) {
    g_last_error = "only CSR matrix_type is supported";
    return -1;
  }
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_sparse_output", "(LLiLLiLLLiii)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)indptr, indptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)nindptr, (long long)nelem,
                     (long long)num_col_or_row, predict_type,
                     start_iteration, num_iteration);
  if (r == nullptr) return -1;
  PyObject *b_indptr = nullptr, *b_indices = nullptr, *b_data = nullptr;
  int out_nindptr = 0;
  long long out_nelem = 0;
  if (!PyArg_ParseTuple(r, "SSSiL", &b_indptr, &b_indices, &b_data,
                        &out_nindptr, &out_nelem)) {
    PyErr_Clear();
    Py_DECREF(r);
    g_last_error = "bad tuple from booster_predict_sparse_output";
    return -1;
  }
  // caller frees with LGBM_BoosterFreePredictSparse (plain free())
  void* p_indptr = std::malloc(PyBytes_GET_SIZE(b_indptr));
  int32_t* p_indices =
      static_cast<int32_t*>(std::malloc(PyBytes_GET_SIZE(b_indices)));
  void* p_data = std::malloc(PyBytes_GET_SIZE(b_data));
  if (p_indptr == nullptr || p_indices == nullptr || p_data == nullptr) {
    std::free(p_indptr);
    std::free(p_indices);
    std::free(p_data);
    Py_DECREF(r);
    g_last_error = "sparse output allocation failed";
    return -1;
  }
  std::memcpy(p_indptr, PyBytes_AS_STRING(b_indptr),
              PyBytes_GET_SIZE(b_indptr));
  std::memcpy(p_indices, PyBytes_AS_STRING(b_indices),
              PyBytes_GET_SIZE(b_indices));
  std::memcpy(p_data, PyBytes_AS_STRING(b_data),
              PyBytes_GET_SIZE(b_data));
  Py_DECREF(r);
  *out_indptr = p_indptr;
  *out_indices = p_indices;
  *out_data = p_data;
  // the reference contract: out_len is a 2-entry array — [0] = element
  // count (indices/data length), [1] = indptr length (c_api.h:1117)
  out_len[0] = out_nelem;
  out_len[1] = (int64_t)out_nindptr;
  return 0;
}

LGBM_API int LGBM_BoosterFreePredictSparse(void* indptr, int32_t* indices,
                                           void* data, int indptr_type,
                                           int data_type) {
  (void)indptr_type;
  (void)data_type;
  std::free(indptr);
  std::free(indices);
  std::free(data);
  return 0;
}

// -- Arrow C-data entry points (ref: c_api.h:461-1534; the Python side
// consumes the raw structs through the dependency-free PyCapsule
// ingestion in io/arrow_ingest.py) -----------------------------------------

struct ArrowSchema;
struct ArrowArray;
struct ArrowArrayStream;

LGBM_API int LGBM_DatasetCreateFromArrow(int64_t n_chunks,
                                         struct ArrowArray* chunks,
                                         struct ArrowSchema* schema,
                                         const char* parameters,
                                         const DatasetHandle reference,
                                         DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_arrow", "(LLLsL)",
                     (long long)n_chunks, (long long)(intptr_t)chunks,
                     (long long)(intptr_t)schema,
                     parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetCreateFromArrowStream(
    struct ArrowArrayStream* stream, const char* parameters,
    const DatasetHandle reference, DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_arrow_stream", "(LsL)",
                     (long long)(intptr_t)stream,
                     parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetSetFieldFromArrow(DatasetHandle handle,
                                           const char* field_name,
                                           int64_t n_chunks,
                                           struct ArrowArray* chunks,
                                           struct ArrowSchema* schema) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_set_field_from_arrow", "(LsLLL)",
                           (long long)AsHandleInt(handle), field_name,
                           (long long)n_chunks,
                           (long long)(intptr_t)chunks,
                           (long long)(intptr_t)schema));
}

LGBM_API int LGBM_DatasetSetFieldFromArrowStream(
    DatasetHandle handle, const char* field_name,
    struct ArrowArrayStream* stream) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_set_field_from_arrow_stream", "(LsL)",
                           (long long)AsHandleInt(handle), field_name,
                           (long long)(intptr_t)stream));
}

LGBM_API int LGBM_BoosterPredictForArrow(BoosterHandle handle,
                                         int64_t n_chunks,
                                         struct ArrowArray* chunks,
                                         struct ArrowSchema* schema,
                                         int predict_type,
                                         int start_iteration,
                                         int num_iteration,
                                         const char* parameter,
                                         int64_t* out_len,
                                         double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_arrow", "(LLLLiiiL)",
                     (long long)AsHandleInt(handle), (long long)n_chunks,
                     (long long)(intptr_t)chunks,
                     (long long)(intptr_t)schema, predict_type,
                     start_iteration, num_iteration,
                     (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForArrowStream(BoosterHandle handle,
                                               struct ArrowArrayStream* stream,
                                               int predict_type,
                                               int start_iteration,
                                               int num_iteration,
                                               const char* parameter,
                                               int64_t* out_len,
                                               double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_arrow_stream", "(LLiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)stream, predict_type,
                     start_iteration, num_iteration,
                     (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

// -- CSC / multi-matrix / merge (ref: c_api.h:394,440,677) -----------------

LGBM_API int LGBM_DatasetCreateFromCSC(const void* col_ptr,
                                       int col_ptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t ncol_ptr, int64_t nelem,
                                       int64_t num_row,
                                       const char* parameters,
                                       const DatasetHandle reference,
                                       DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_csc", "(LiLLiLLLsL)",
                     (long long)(intptr_t)col_ptr, col_ptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)ncol_ptr, (long long)nelem,
                     (long long)num_row, parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForCSC(BoosterHandle handle,
                                       const void* col_ptr,
                                       int col_ptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t ncol_ptr, int64_t nelem,
                                       int64_t num_row, int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_csc", "(LLiLLiLLLiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)col_ptr, col_ptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)ncol_ptr, (long long)nelem,
                     (long long)num_row, predict_type, start_iteration,
                     num_iteration, (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetCreateFromMats(int32_t nmat, const void** data,
                                        int data_type, int32_t* nrow,
                                        int32_t ncol, int* is_row_major,
                                        const char* parameters,
                                        const DatasetHandle reference,
                                        DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_mats", "(iLiLiLsL)",
                     (int)nmat, (long long)(intptr_t)data, data_type,
                     (long long)(intptr_t)nrow, (int)ncol,
                     (long long)(intptr_t)is_row_major,
                     parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetAddFeaturesFrom(DatasetHandle target,
                                         DatasetHandle source) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_add_features_from", "(LL)",
                           (long long)AsHandleInt(target),
                           (long long)AsHandleInt(source)));
}

// LGBM_DatasetCreateFromCSRFunc: the funptr is a C++
// std::function<void(int, std::vector<std::pair<int, double>>&)>* (the
// reference casts it the same way, c_api.cpp:1362) — invoke it here to
// collect the CSR triple, then reuse the plain CSR path.
LGBM_API int LGBM_DatasetCreateFromCSRFunc(void* get_row_funptr,
                                           int num_rows, int64_t num_col,
                                           const char* parameters,
                                           const DatasetHandle reference,
                                           DatasetHandle* out) {
  using RowFunc = std::function<void(int, std::vector<std::pair<int, double>>&)>;
  auto* fn = reinterpret_cast<RowFunc*>(get_row_funptr);
  std::vector<int32_t> indptr{0};
  std::vector<int32_t> indices;
  std::vector<double> values;
  std::vector<std::pair<int, double>> row;
  for (int i = 0; i < num_rows; ++i) {
    row.clear();
    (*fn)(i, row);
    for (const auto& kv : row) {
      indices.push_back(kv.first);
      values.push_back(kv.second);
    }
    indptr.push_back((int32_t)indices.size());
  }
  return LGBM_DatasetCreateFromCSR(
      indptr.data(), 2 /* int32 */, indices.data(), values.data(),
      1 /* float64 */, (int64_t)indptr.size(), (int64_t)values.size(),
      num_col, parameters, reference, out);
}

LGBM_API int LGBM_NetworkInitWithFunctions(int num_machines, int rank,
                                           void* reduce_scatter_ext_fun,
                                           void* allgather_ext_fun) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("network_init_with_functions", "(iiLL)",
                           num_machines, rank,
                           (long long)(intptr_t)reduce_scatter_ext_fun,
                           (long long)(intptr_t)allgather_ext_fun));
}
