"""Advanced learner features: forced splits, interaction constraints,
path smoothing, CEGB (ref: serial_tree_learner.cpp:628 ForceSplits,
col_sampler.hpp, feature_histogram.hpp USE_SMOOTHING,
cost_effective_gradient_boosting.hpp)."""

import json

import numpy as np
import pytest

from conftest import make_binary, make_regression

import lightgbm_tpu as lgb


def _train(X, y, params, rounds=10):
    p = {"objective": "regression", "verbosity": -1, "num_leaves": 15,
         "min_data_in_leaf": 5, **params}
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


class TestForcedSplits:
    def test_root_split_is_forced(self, tmp_path):
        X, y = make_regression(800, 6)
        fs = tmp_path / "forced.json"
        # feature 3 is noise — the learner would never choose it first
        fs.write_text(json.dumps({"feature": 3, "threshold": 0.0}))
        bst = _train(X, y, {"forcedsplits_filename": str(fs)}, rounds=3)
        for it in bst._gbdt.models:
            for tree in it:
                assert tree.split_feature[0] == 3
                assert abs(tree.threshold[0] - 0.0) < 0.5

    def test_nested_forced_splits(self, tmp_path):
        X, y = make_regression(800, 6)
        fs = tmp_path / "forced.json"
        fs.write_text(json.dumps({
            "feature": 3, "threshold": 0.0,
            "left": {"feature": 4, "threshold": 0.5},
            "right": {"feature": 5, "threshold": -0.5}}))
        bst = _train(X, y, {"forcedsplits_filename": str(fs)}, rounds=2)
        tree = bst._gbdt.models[0][0]
        assert tree.split_feature[0] == 3
        # splits 1 and 2 are the forced children (BFS order)
        assert {tree.split_feature[1], tree.split_feature[2]} == {4, 5}

    def test_forced_categorical_split(self, tmp_path):
        """A forced split on a categorical feature becomes the
        one-vs-rest bitset split on that category (VERDICT r3 #9; ref:
        ForceSplits serial_tree_learner.cpp:628 + tree.h:375)."""
        rng = np.random.RandomState(3)
        n = 800
        cat = rng.randint(0, 5, n)
        X = np.column_stack([rng.randn(n), cat.astype(np.float64),
                             rng.randn(n)])
        y = (0.3 * X[:, 0] + (cat == 2) * 2.0
             + 0.1 * rng.randn(n)).astype(np.float32)
        fs = tmp_path / "forced_cat.json"
        fs.write_text(json.dumps({"feature": 1, "threshold": 4}))
        bst = lgb.train(
            {"objective": "regression", "verbosity": -1, "num_leaves": 15,
             "min_data_in_leaf": 5, "forcedsplits_filename": str(fs)},
            lgb.Dataset(X, label=y, categorical_feature=[1]),
            num_boost_round=3)
        for it in bst._gbdt.models:
            for tree in it:
                # root must be a categorical decision on feature 1
                # sending exactly the forced category 4 left
                assert tree.split_feature[0] == 1
                assert tree.decision_type[0] & 1  # categorical bit
                ci = int(tree.threshold[0])
                words = tree.cat_threshold[tree.cat_boundaries[ci]:
                                           tree.cat_boundaries[ci + 1]]
                vals = [w * 32 + b for w, word in enumerate(words)
                        for b in range(32) if word >> b & 1]
                assert vals == [4]
        # model round-trips through the text format with the forced
        # categorical node intact
        from lightgbm_tpu.model_io import load_model_from_string
        loaded = load_model_from_string(bst.model_to_string())
        np.testing.assert_allclose(
            np.asarray(loaded.predict_raw(X)).reshape(-1),
            bst.predict(X), rtol=1e-5, atol=1e-6)

    def test_forced_split_still_learns(self, tmp_path):
        X, y = make_binary(1000, 6)
        fs = tmp_path / "forced.json"
        fs.write_text(json.dumps({"feature": 5, "threshold": 0.0}))
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "forcedsplits_filename": str(fs)},
                        lgb.Dataset(X, label=y), num_boost_round=20)
        preds = bst.predict(X)
        assert preds[y == 1].mean() > preds[y == 0].mean() + 0.2

    def test_reference_example_forced_splits(self):
        import os
        path = ("/root/reference/examples/binary_classification/"
                "forced_splits.json")
        if not os.path.exists(path):
            pytest.skip("reference examples not mounted")
        X, y = make_binary(500, 30)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "forcedsplits_filename": path},
                        lgb.Dataset(X, label=y), num_boost_round=2)
        spec = json.load(open(path))
        tree = bst._gbdt.models[0][0]
        assert tree.split_feature[0] == spec["feature"]


class TestInteractionConstraints:
    def test_constrained_features_never_mix(self):
        X, y = make_regression(1000, 6)
        bst = _train(X, y, {"interaction_constraints": [[0, 1], [2, 3, 4, 5]]},
                     rounds=10)
        groups = [{0, 1}, {2, 3, 4, 5}]
        for it in bst._gbdt.models:
            for tree in it:
                # every root->leaf path must stay inside one group
                def walk(node, used):
                    if node < 0:
                        assert any(used <= g for g in groups), used
                        return
                    used = used | {int(tree.split_feature[node])}
                    walk(tree.left_child[node], used)
                    walk(tree.right_child[node], used)
                if tree.num_internal:
                    walk(0, set())

    def test_single_group_restricts_features(self):
        X, y = make_regression(800, 6)
        bst = _train(X, y, {"interaction_constraints": [[1, 2]]}, rounds=5)
        for it in bst._gbdt.models:
            for tree in it:
                for s in range(tree.num_internal):
                    assert int(tree.split_feature[s]) in (1, 2)

    def test_accuracy_unconstrained_vs_full_group(self):
        X, y = make_regression(800, 6)
        b1 = _train(X, y, {}, rounds=10)
        b2 = _train(X, y, {"interaction_constraints": [[0, 1, 2, 3, 4, 5]]},
                    rounds=10)
        np.testing.assert_allclose(b1.predict(X), b2.predict(X), rtol=1e-5)


class TestPathSmoothing:
    @pytest.mark.slow
    def test_smoothing_shrinks_leaf_values(self):
        X, y = make_regression(500, 6)
        b0 = _train(X, y, {}, rounds=5)
        b1 = _train(X, y, {"path_smooth": 100.0}, rounds=5)
        # smoothed leaves are pulled toward parents -> smaller extremes
        lv0 = np.concatenate([t.leaf_value for it in b0._gbdt.models
                              for t in it])
        lv1 = np.concatenate([t.leaf_value for it in b1._gbdt.models
                              for t in it])
        assert np.abs(lv1).max() < np.abs(lv0).max()

    def test_smoothing_zero_is_identity(self):
        X, y = make_regression(500, 6)
        b0 = _train(X, y, {}, rounds=5)
        b1 = _train(X, y, {"path_smooth": 0.0}, rounds=5)
        np.testing.assert_allclose(b0.predict(X), b1.predict(X), rtol=1e-6)

    def test_smoothing_still_learns(self):
        X, y = make_regression(800, 6)
        bst = _train(X, y, {"path_smooth": 10.0}, rounds=30)
        pred = bst.predict(X)
        ss_res = ((y - pred) ** 2).sum()
        ss_tot = ((y - y.mean()) ** 2).sum()
        assert 1 - ss_res / ss_tot > 0.7


class TestCEGB:
    def test_split_penalty_reduces_tree_size(self):
        X, y = make_regression(500, 6)
        b0 = _train(X, y, {}, rounds=5)
        b1 = _train(X, y, {"cegb_penalty_split": 1.0,
                           "cegb_tradeoff": 1.0}, rounds=5)
        n0 = sum(t.num_leaves for it in b0._gbdt.models for t in it)
        n1 = sum(t.num_leaves for it in b1._gbdt.models for t in it)
        assert n1 < n0

    def test_coupled_penalty_concentrates_features(self):
        X, y = make_regression(1000, 6, seed=3)
        pen = [10.0] * 6
        b = _train(X, y, {"cegb_penalty_feature_coupled": pen,
                          "cegb_tradeoff": 1.0}, rounds=10)
        used = set()
        for it in b._gbdt.models:
            for t in it:
                used |= set(t.split_feature[:t.num_internal].tolist())
        b0 = _train(X, y, {}, rounds=10)
        used0 = set()
        for it in b0._gbdt.models:
            for t in it:
                used0 |= set(t.split_feature[:t.num_internal].tolist())
        assert len(used) <= len(used0)

    def test_lazy_penalty_trains(self):
        X, y = make_regression(500, 6)
        b = _train(X, y, {"cegb_penalty_feature_lazy": [1e-4] * 6,
                          "cegb_tradeoff": 1.0}, rounds=10)
        pred = b.predict(X)
        assert np.corrcoef(pred, y)[0, 1] > 0.7


class TestMaxDeltaStep:
    def test_leaf_values_clipped(self):
        X, y = make_regression(500, 6)
        y = y * 100.0  # large outputs
        bst = _train(X, y, {"max_delta_step": 0.5, "learning_rate": 1.0,
                            "boost_from_average": False}, rounds=2)
        for it in bst._gbdt.models:
            for t in it:
                assert np.abs(t.leaf_value).max() <= 0.5 + 1e-5


class TestPredictionEarlyStop:
    def test_binary_early_stop_close_to_full(self):
        """pred_early_stop trades exactness for speed: rows whose margin
        is already decisive stop traversing (ref:
        prediction_early_stop.cpp). With a small margin, hard rows keep
        the same sign; with a huge margin, results are identical."""
        from conftest import make_binary
        import lightgbm_tpu as lgb
        X, y = make_binary(800, 6)
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
        ds = lgb.Dataset(X, label=y, params=dict(params))
        bst = lgb.train(dict(params), ds, num_boost_round=30)
        full = bst.predict(X, raw_score=True)

        bst._gbdt.config.pred_early_stop = True
        bst._gbdt.config.pred_early_stop_freq = 5
        bst._gbdt.config.pred_early_stop_margin = 1e9
        exact = bst.predict(X, raw_score=True)
        # early-stop path sums trees in f64 on host; the default path is
        # the f32 device ensemble — agreement at f32 resolution
        np.testing.assert_allclose(exact, full, rtol=1e-4, atol=1e-6)

        bst._gbdt.config.pred_early_stop_margin = 1.0  # stops at 2|raw|>1
        approx = bst.predict(X, raw_score=True)
        # decisions agree even where magnitudes were truncated
        assert np.mean((approx > 0) == (full > 0)) > 0.97
        # margin-exceeding rows really did stop early
        assert np.any(np.abs(approx) < np.abs(full) - 1e-12)
        bst._gbdt.config.pred_early_stop = False

    def test_multiclass_early_stop(self):
        from conftest import make_multiclass
        import lightgbm_tpu as lgb
        X, y = make_multiclass(900, 6, 3)
        params = {"objective": "multiclass", "num_class": 3,
                  "num_leaves": 7, "verbosity": -1}
        ds = lgb.Dataset(X, label=y, params=dict(params))
        bst = lgb.train(dict(params), ds, num_boost_round=12)
        full = np.argmax(bst.predict(X), axis=1)
        bst._gbdt.config.pred_early_stop = True
        bst._gbdt.config.pred_early_stop_freq = 3
        bst._gbdt.config.pred_early_stop_margin = 0.5
        approx = np.argmax(bst.predict(X), axis=1)
        assert np.mean(approx == full) > 0.97


def test_feature_contri_penalty_steers_splits():
    """feature_contri multiplies per-feature split gains (ref:
    feature_contri config.h / FeatureHistogram penalty): strongly
    penalizing the dominant feature must reduce its split count."""
    r = np.random.RandomState(11)
    n = 2000
    X = r.randn(n, 4)
    # feature 0 dominant, feature 1 slightly informative
    y = (X[:, 0] + 0.3 * X[:, 1] + 0.2 * r.randn(n) > 0).astype(np.float32)
    b_plain = _train(X, y, {"objective": "binary"})
    b_pen = _train(X, y, {"objective": "binary",
                          "feature_contri": [0.01, 1, 1, 1]})
    splits_plain = b_plain.feature_importance("split")
    splits_pen = b_pen.feature_importance("split")
    assert splits_plain[0] > 0
    assert splits_pen[0] < splits_plain[0]
    # the penalized model leans on other features instead
    assert splits_pen[1:].sum() > splits_plain[1:].sum()


def test_monotone_penalty_delays_constrained_splits():
    """monotone_penalty discounts gains of monotone-constrained features
    near the root (ref: feature_histogram.hpp monotone_penalty factor):
    with a large penalty the dominant constrained feature loses the
    root split."""
    r = np.random.RandomState(12)
    n = 2000
    X = r.randn(n, 3)
    y = (2.0 * X[:, 0] + 0.5 * X[:, 1] + 0.1 * r.randn(n)).astype(
        np.float32)
    common = {"monotone_constraints": [1, 0, 0], "num_leaves": 15}

    def root_feature(bst):
        tree0 = bst._gbdt.models[0][0]
        return int(tree0.split_feature_inner[0])

    b_plain = _train(X, y, dict(common))
    b_pen = _train(X, y, {**common, "monotone_penalty": 4.0})
    assert root_feature(b_plain) == 0  # dominant constrained feature
    assert root_feature(b_pen) != 0   # penalty pushed it off the root


def test_refit_decay_rate_blends_leaf_values():
    """refit leaf values are decay*old + (1-decay)*new (ref:
    GBDT::RefitTree refit_decay_rate): decay 1.0 reproduces the original
    model, decay 0.0 moves furthest from it."""
    r = np.random.RandomState(13)
    X = r.randn(1200, 4)
    y = (X[:, 0] + 0.2 * r.randn(1200)).astype(np.float32)
    bst = _train(X[:800], y[:800], {"objective": "regression"})
    base = bst.predict(X[800:])
    X2, y2 = X[800:], y[800:] + 1.0  # shifted target
    keep = bst.refit(X2, y2, decay_rate=1.0).predict(X2)
    mid = bst.refit(X2, y2, decay_rate=0.5).predict(X2)
    full = bst.refit(X2, y2, decay_rate=0.0).predict(X2)
    np.testing.assert_allclose(keep, base, rtol=1e-6, atol=1e-6)
    # decay 0 adapts most to the shifted target
    assert np.abs(full - (y2)).mean() < np.abs(mid - (y2)).mean() \
        < np.abs(keep - (y2)).mean()
