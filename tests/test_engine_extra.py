"""Engine semantics the reference pins in its 4.9k-LoC test_engine.py
that weren't yet covered here: prediction iteration slicing, early
stopping min_delta, unseen categoricals, importance types, init_score
continuation (ref: tests/python_package_test/test_engine.py)."""

import numpy as np
import pytest

from conftest import make_binary, make_multiclass, make_regression

import lightgbm_tpu as lgb


def _booster(params=None, rounds=12, n=600):
    X, y = make_binary(n)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1, **(params or {})}
    return lgb.train(p, lgb.Dataset(X, label=y),
                     num_boost_round=rounds), X, y


class TestPredictSlicing:
    def test_num_iteration_prefix(self):
        """predict(num_iteration=k) equals the raw-score sum of the
        first k trees (ref: LGBM_BoosterPredictForMat num_iteration)."""
        bst, X, _y = _booster()
        full = bst.predict(X, raw_score=True)
        half = bst.predict(X, raw_score=True, num_iteration=6)
        assert not np.allclose(full, half)
        # rebuild the prefix sum from the model dump
        from lightgbm_tpu.model_io import load_model_from_string
        prefix = load_model_from_string(
            bst.model_to_string(num_iteration=6))
        np.testing.assert_allclose(
            half, np.asarray(prefix.predict_raw(X)).reshape(-1),
            rtol=1e-5, atol=1e-6)

    def test_start_iteration_suffix(self):
        bst, X, _y = _booster()
        full = bst.predict(X, raw_score=True)
        head = bst.predict(X, raw_score=True, num_iteration=4)
        tail = bst.predict(X, raw_score=True, start_iteration=4,
                           num_iteration=-1)
        np.testing.assert_allclose(head + tail, full, rtol=1e-5,
                                   atol=1e-5)


class TestEarlyStoppingMinDelta:
    def _run(self, min_delta):
        X, y = make_binary(900, seed=3)
        Xt, yt = X[:600], y[:600]
        Xv, yv = X[600:], y[600:]
        ds = lgb.Dataset(Xt, label=yt)
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 31, "learning_rate":
             0.02, "min_data_in_leaf": 5, "metric": "binary_logloss",
             "verbosity": -1},
            ds, num_boost_round=60,
            valid_sets=[lgb.Dataset(Xv, label=yv, reference=ds)],
            callbacks=[lgb.early_stopping(5, min_delta=min_delta,
                                          verbose=False)])
        return bst.best_iteration

    @pytest.mark.slow
    def test_min_delta_stops_earlier(self):
        """A large min_delta must stop no later than min_delta=0
        (ref: callback.py early_stopping min_delta)."""
        loose = self._run(0.0)
        strict = self._run(0.05)
        assert strict <= loose
        assert strict < 60


class TestCategoricalEdge:
    def test_unseen_category_predicts(self):
        rng = np.random.RandomState(0)
        n = 600
        cat = rng.randint(0, 4, n).astype(np.float64)
        X = np.column_stack([cat, rng.randn(n)])
        y = (cat == 2).astype(np.float64) * 2 + 0.1 * rng.randn(n)
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "min_data_in_leaf": 5, "verbosity": -1},
                        lgb.Dataset(X, label=y,
                                    categorical_feature=[0]),
                        num_boost_round=10)
        Xq = np.array([[99.0, 0.0], [2.0, 0.0]])  # 99 never seen
        pred = bst.predict(Xq)
        assert np.isfinite(pred).all()
        # the unseen category must not land in category 2's leaf
        assert abs(pred[0] - pred[1]) > 0.5


class TestImportanceTypes:
    def test_split_and_gain(self):
        bst, X, _y = _booster()
        split = bst.feature_importance("split")
        gain = bst.feature_importance("gain")
        assert split.shape == gain.shape == (X.shape[1],)
        assert split.sum() > 0 and gain.sum() > 0
        assert np.all(split == split.astype(int))  # counts
        assert np.all(gain >= 0)
        # features never split have zero gain and zero count together
        assert np.array_equal(split == 0, gain == 0)


class TestInitScore:
    def test_training_continues_from_init_score(self):
        """A strong init_score should change the learned residual model
        (ref: Dataset.set_init_score / boost_from_average interplay)."""
        X, y = make_regression(600)
        base = np.full(len(y), y.mean(), np.float64)
        ds = lgb.Dataset(X, label=y)
        ds.set_init_score(base)
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "verbosity": -1, "boost_from_average": False},
                        ds, num_boost_round=20)
        # predictions EXCLUDE the dataset init_score (reference
        # semantics): adding it back should fit y well
        pred = bst.predict(X) + base
        assert np.mean((pred - y) ** 2) < np.var(y) * 0.2


class TestMulticlassPredictShape:
    def test_proba_rows_sum_to_one(self):
        X, y = make_multiclass(600)
        bst = lgb.train({"objective": "multiclass", "num_class": 4,
                         "num_leaves": 7, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=5)
        proba = bst.predict(X)
        assert proba.shape == (600, 4)
        np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-5)
        raw = bst.predict(X, raw_score=True)
        assert raw.shape == (600, 4)


def test_is_unbalance_shifts_probabilities():
    """is_unbalance upweights the minority class (ref:
    binary_objective.hpp label weight setup): predicted probabilities
    on an imbalanced set shift up vs the plain objective."""
    r = np.random.RandomState(0)
    n = 2000
    X = r.randn(n, 5)
    y = ((X[:, 0] + 0.5 * r.randn(n)) > 1.1).astype(np.float32)  # ~13% pos
    assert 0.05 < y.mean() < 0.25
    p_plain = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 7},
                        lgb.Dataset(X, label=y),
                        num_boost_round=10).predict(X)
    p_unbal = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 7, "is_unbalance": True},
                        lgb.Dataset(X, label=y),
                        num_boost_round=10).predict(X)
    assert p_unbal.mean() > p_plain.mean() + 0.05


def test_scale_pos_weight_shifts_probabilities():
    r = np.random.RandomState(1)
    n = 2000
    X = r.randn(n, 5)
    y = ((X[:, 0] + 0.5 * r.randn(n)) > 1.1).astype(np.float32)
    p1 = lgb.train({"objective": "binary", "verbosity": -1,
                    "num_leaves": 7},
                   lgb.Dataset(X, label=y), num_boost_round=10).predict(X)
    p5 = lgb.train({"objective": "binary", "verbosity": -1,
                    "num_leaves": 7, "scale_pos_weight": 5.0},
                   lgb.Dataset(X, label=y), num_boost_round=10).predict(X)
    assert p5.mean() > p1.mean() + 0.05


def test_first_metric_only_early_stopping():
    """With first_metric_only, a deteriorating SECOND metric must not
    stop training while the first keeps improving (ref: python-package
    early_stopping(first_metric_only=True)). A custom feval that gets
    strictly worse every round makes the discrimination deterministic:
    without the flag it stops after stopping_rounds; with it, training
    runs on the (improving) first metric."""
    r = np.random.RandomState(2)
    X = r.randn(1200, 5)
    y = (X[:, 0] + 0.3 * r.randn(1200) > 0).astype(np.float32)
    Xv, yv = X[800:], y[800:]
    Xt, yt = X[:800], y[:800]

    def make_worsening():
        state = {"v": 0.0}

        def worsening(_preds, _dataset):
            state["v"] += 1.0
            return "worsening", state["v"], False  # lower is better

        return worsening

    common = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "metric": "binary_logloss", "early_stopping_round": 3}
    rounds = 15
    b_all = lgb.train(dict(common), lgb.Dataset(Xt, label=yt),
                      num_boost_round=rounds,
                      valid_sets=[lgb.Dataset(Xv, label=yv)],
                      feval=make_worsening())
    # the always-worsening metric must have stopped this run early
    assert b_all.current_iteration() < rounds
    b_first = lgb.train({**common, "first_metric_only": True},
                        lgb.Dataset(Xt, label=yt), num_boost_round=rounds,
                        valid_sets=[lgb.Dataset(Xv, label=yv)],
                        feval=make_worsening())
    # with first_metric_only the worsening metric is ignored
    assert b_first.current_iteration() > b_all.current_iteration()


def test_forcedbins_filename(tmp_path):
    """forcedbins_filename pins bin upper bounds for chosen features
    (ref: Dataset forced bins JSON, dataset_loader.cpp)."""
    import json
    r = np.random.RandomState(3)
    X = r.rand(800, 3) * 10
    y = (X[:, 0] > 5).astype(np.float32)
    fb = tmp_path / "forced.json"
    fb.write_text(json.dumps(
        [{"feature": 0, "bin_upper_bound": [2.5, 5.0, 7.5]}]))
    ds = lgb.Dataset(X, label=y, params={
        "forcedbins_filename": str(fb), "max_bin": 15,
        "verbosity": -1}).construct()
    m = ds._binned.mappers[0]
    ubs = np.asarray(m.bin_upper_bound, np.float64)
    for b in (2.5, 5.0, 7.5):
        assert np.any(np.isclose(ubs, b)), (b, ubs)


def test_max_cat_to_onehot_switches_split_style():
    """Categories <= max_cat_to_onehot use one-vs-rest splits; above it
    the sorted-subset scan can send MULTIPLE categories left (ref:
    feature_histogram.hpp one-hot vs sorted categorical paths)."""
    r = np.random.RandomState(21)
    n = 3000
    cat = r.randint(0, 12, n)
    y = (np.isin(cat, [1, 4, 7, 9]) * 2.0 - 1.0
         + 0.3 * r.randn(n)).astype(np.float32)
    X = cat.astype(np.float64)[:, None]

    def root_left_cats(bst):
        tree = bst._gbdt.models[0][0]
        assert tree.decision_type[0] & 1
        ci = int(tree.threshold[0])
        words = tree.cat_threshold[tree.cat_boundaries[ci]:
                                   tree.cat_boundaries[ci + 1]]
        return [w * 32 + b for w, word in enumerate(words)
                for b in range(32) if word >> b & 1]

    params = {"objective": "regression", "verbosity": -1,
              "num_leaves": 7, "min_data_in_leaf": 5}
    # sorted-subset mode (threshold below the 12 categories): the root
    # split groups several of the 4 positive categories at once
    b_sub = lgb.train({**params, "max_cat_to_onehot": 2},
                      lgb.Dataset(X, label=y, categorical_feature=[0]),
                      num_boost_round=2)
    assert len(root_left_cats(b_sub)) > 1
    # one-hot mode: exactly one category per split
    b_hot = lgb.train({**params, "max_cat_to_onehot": 32},
                      lgb.Dataset(X, label=y, categorical_feature=[0]),
                      num_boost_round=2)
    assert len(root_left_cats(b_hot)) == 1


def test_cat_l2_regularizes_categorical_gain():
    """cat_l2 adds extra L2 to categorical splits (ref:
    feature_histogram.hpp cat_l2): a huge value suppresses categorical
    splits in favor of numerical ones."""
    r = np.random.RandomState(22)
    n = 2000
    cat = r.randint(0, 10, n)
    num = r.randn(n)
    y = (np.isin(cat, [2, 5]) * 1.5 + 0.7 * num
         + 0.2 * r.randn(n)).astype(np.float32)
    X = np.column_stack([cat.astype(np.float64), num])
    params = {"objective": "regression", "verbosity": -1,
              "num_leaves": 7, "min_data_in_leaf": 5}
    b0 = lgb.train(dict(params),
                   lgb.Dataset(X, label=y, categorical_feature=[0]),
                   num_boost_round=3)
    b1 = lgb.train({**params, "cat_l2": 1e6},
                   lgb.Dataset(X, label=y, categorical_feature=[0]),
                   num_boost_round=3)
    cat_splits0 = b0.feature_importance("split")[0]
    cat_splits1 = b1.feature_importance("split")[0]
    assert cat_splits0 > 0
    assert cat_splits1 < cat_splits0


@pytest.mark.slow
def test_min_sum_hessian_in_leaf_limits_leaves():
    """min_sum_hessian_in_leaf blocks low-mass leaves (ref:
    feature_histogram.hpp min_sum_hessian check)."""
    X, y = make_regression(600)
    b0 = lgb.train({"objective": "regression", "verbosity": -1,
                    "num_leaves": 63, "min_data_in_leaf": 1},
                   lgb.Dataset(X, label=y), num_boost_round=3)
    b1 = lgb.train({"objective": "regression", "verbosity": -1,
                    "num_leaves": 63, "min_data_in_leaf": 1,
                    "min_sum_hessian_in_leaf": 100.0},
                   lgb.Dataset(X, label=y), num_boost_round=3)
    n0 = sum(t.num_leaves for it in b0._gbdt.models for t in it)
    n1 = sum(t.num_leaves for it in b1._gbdt.models for t in it)
    assert n1 < n0
