"""Best-split search over histograms (device).

TPU-native replacement for the reference split kernels
(ref: src/treelearner/feature_histogram.hpp:166 FindBestThreshold,
src/treelearner/cuda/cuda_best_split_finder.cu:776). The per-feature
sequential threshold scan becomes a fully vectorized prefix-sum + gain
evaluation over ``[F, B]`` with a global argmax, evaluated for both
missing-value directions (the reference's two-direction scan).

Split semantics (numerical): rows with ``bin <= threshold`` go left; the
NaN bin (when missing_type == NAN) is the feature's last bin and goes to
the side indicated by ``default_left``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import GRAD, HESS, COUNT
from ..obs.metrics import global_metrics

MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2
K_MIN_SCORE = -1e30
# Tie-rejection band for the net-gain acceptance, relative to the
# parent-gain shift. L1-family gradients are lattice-valued (e.g.
# quantile: every grad is 1-alpha or -alpha), so candidate splits with
# EXACTLY zero net improvement are structural, not rare — and f32
# accumulation noise between two compilations of the same math (the
# fused one-program iteration vs the standalone grower; XLA contracts
# them differently) lands on either side of a strict `> 0` cut,
# flipping whether a worthless split is made. Requiring the net gain to
# clear a noise-sized band keeps both programs' verdicts identical on
# structural ties while rejecting nothing a f32 pipeline could
# meaningfully resolve (tests/test_engine.py::TestFusedRenewal).
K_GAIN_TIE_RTOL = 1e-5
K_EPSILON = 1e-15


class SplitHyperParams(NamedTuple):
    """Dynamic (traced) regularization scalars (ref: config.h)."""
    lambda_l1: jax.Array
    lambda_l2: jax.Array
    min_data_in_leaf: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    min_gain_to_split: jax.Array
    max_delta_step: jax.Array
    path_smooth: jax.Array     # (ref: config.h path_smooth)
    cegb_split_pen: jax.Array  # cegb_tradeoff * cegb_penalty_split
    cat_l2: jax.Array          # extra L2 for categorical subset splits
    cat_smooth: jax.Array      # grad/hess ratio smoothing + count filter
    max_cat_threshold: jax.Array   # max categories sent left
    max_cat_to_onehot: jax.Array   # one-hot below this many bins
    min_data_per_group: jax.Array  # min data per categorical group
    monotone_penalty: jax.Array    # gain penalty on monotone splits

    @classmethod
    def from_config(cls, cfg) -> "SplitHyperParams":
        f = jnp.float32
        return cls(
            lambda_l1=jnp.asarray(cfg.lambda_l1, f),
            lambda_l2=jnp.asarray(cfg.lambda_l2, f),
            min_data_in_leaf=jnp.asarray(cfg.min_data_in_leaf, f),
            min_sum_hessian_in_leaf=jnp.asarray(
                max(cfg.min_sum_hessian_in_leaf, K_EPSILON), f),
            min_gain_to_split=jnp.asarray(cfg.min_gain_to_split, f),
            max_delta_step=jnp.asarray(cfg.max_delta_step, f),
            path_smooth=jnp.asarray(cfg.path_smooth, f),
            cegb_split_pen=jnp.asarray(
                cfg.cegb_tradeoff * cfg.cegb_penalty_split, f),
            cat_l2=jnp.asarray(cfg.cat_l2, f),
            cat_smooth=jnp.asarray(cfg.cat_smooth, f),
            max_cat_threshold=jnp.asarray(cfg.max_cat_threshold, jnp.int32),
            max_cat_to_onehot=jnp.asarray(cfg.max_cat_to_onehot, jnp.int32),
            min_data_per_group=jnp.asarray(cfg.min_data_per_group, f),
            monotone_penalty=jnp.asarray(cfg.monotone_penalty, f),
        )


class FeatureMeta(NamedTuple):
    """Static per-feature binning metadata, as device arrays.

    num_bins: [F] actual bin count per feature (<= B).
    missing_type: [F] MISSING_* code.
    default_bin: [F] bin that value 0.0 maps to.
    is_categorical: [F] bool.
    monotone: [F] int8 in {-1, 0, +1}.
    penalty: [F] multiplicative gain penalty (feature_contri; 1.0 = none).
    """
    num_bins: jax.Array
    missing_type: jax.Array
    default_bin: jax.Array
    is_categorical: jax.Array
    monotone: jax.Array
    penalty: jax.Array
    cegb_feat: jax.Array  # [F] additive gain penalty (CEGB coupled, pre-scaled)
    cegb_lazy: jax.Array  # [F] per-row additive penalty (CEGB lazy, pre-scaled)


class SplitInfo(NamedTuple):
    """Best split for one leaf — scalar fields (ref: split_info.hpp:22).

    cat_mask: [B] bool — for categorical splits, the set of bins sent left
    (the device analog of the reference's cat_threshold bitset,
    split_info.hpp cat_threshold / tree.h:375). All-False for numerical.
    """
    gain: jax.Array          # gain above (parent_gain + min_gain_to_split); <=0 => no split
    feature: jax.Array       # int32 feature index
    threshold: jax.Array     # int32 bin threshold (bin <= threshold -> left)
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    cat_mask: jax.Array      # [B] bool, bins going left (categorical)


def split_info_nbytes(max_bins: int) -> int:
    """Wire size of ONE SplitInfo record: 11 four-byte scalar fields
    (gain, feature, threshold, 6 child sums, 2 outputs) + the
    default_left bool + the [max_bins] bool cat_mask. This is the
    all_gather payload unit of the reduce-scatter learner's winner
    sync (ref: SyncUpGlobalBestSplit ships sizeof(SplitInfo) per
    machine, data_parallel_tree_learner.cpp:297) — O(bytes) per split,
    vs O(F * B) for a full histogram row."""
    return 11 * 4 + 1 + max_bins


def threshold_l1(s: jax.Array, l1: jax.Array) -> jax.Array:
    """Soft-threshold by lambda_l1 (ref: feature_histogram.hpp ThresholdL1)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_grad, sum_hess, hp: SplitHyperParams):
    """Optimal leaf value -TL1(G)/(H+l2), clipped by max_delta_step
    (ref: feature_histogram.hpp CalculateSplittedLeafOutput)."""
    raw = -threshold_l1(sum_grad, hp.lambda_l1) / (sum_hess + hp.lambda_l2)
    return jnp.where(hp.max_delta_step > 0,
                     jnp.clip(raw, -hp.max_delta_step, hp.max_delta_step), raw)


def leaf_gain_given_output(sum_grad, sum_hess, output, hp: SplitHyperParams):
    """-(2*TL1(G)*w + (H+l2)*w^2) — equals TL1(G)^2/(H+l2) at the optimum
    (ref: feature_histogram.hpp GetLeafGainGivenOutput)."""
    g = threshold_l1(sum_grad, hp.lambda_l1)
    return -(2.0 * g * output + (sum_hess + hp.lambda_l2) * output * output)


def leaf_gain(sum_grad, sum_hess, hp: SplitHyperParams):
    return leaf_gain_given_output(sum_grad, sum_hess,
                                  leaf_output(sum_grad, sum_hess, hp), hp)


def smooth_output(raw, count, parent_output, hp: SplitHyperParams):
    """Path smoothing: pull a leaf's output toward its parent's,
    weighted by leaf size (ref: feature_histogram.hpp
    CalculateSplittedLeafOutput USE_SMOOTHING branch:
    w' = w * (n/a)/(n/a+1) + parent/(n/a+1), a = path_smooth)."""
    ratio = count / jnp.maximum(hp.path_smooth, K_EPSILON)
    smoothed = (raw * ratio + parent_output) / (ratio + 1.0)
    return jnp.where(hp.path_smooth > 0, smoothed, raw)


def leaf_output_smooth(sum_grad, sum_hess, count, parent_output,
                       hp: SplitHyperParams):
    return smooth_output(leaf_output(sum_grad, sum_hess, hp), count,
                         parent_output, hp)


def propagate_monotone_bounds(out_l, out_r, mono_t, is_cat_split,
                              p_minb, p_maxb):
    """Children's output bounds after a split (basic method,
    ref: monotone_constraints.hpp:466 Update): a numerical split on a
    monotone feature pins the children's shared boundary at the midpoint
    of their outputs. Returns (l_min, l_max, r_min, r_max)."""
    upd = ~is_cat_split & (mono_t != 0)
    mid = (out_l + out_r) * 0.5
    l_max = jnp.where(upd & (mono_t > 0), jnp.minimum(p_maxb, mid), p_maxb)
    l_min = jnp.where(upd & (mono_t < 0), jnp.maximum(p_minb, mid), p_minb)
    r_min = jnp.where(upd & (mono_t > 0), jnp.maximum(p_minb, mid), p_minb)
    r_max = jnp.where(upd & (mono_t < 0), jnp.minimum(p_maxb, mid), p_maxb)
    return l_min, l_max, r_min, r_max


def compute_box_bounds(box_lo, box_hi, outputs, leaf_valid, monotone):
    """Exact pairwise leaf-output bounds for the `intermediate` and
    `advanced` monotone methods — the TPU-native re-architecture of
    IntermediateLeafConstraints / AdvancedLeafConstraints (ref:
    monotone_constraints.hpp:517,859).

    The reference refines its basic midpoint constraints by recursively
    walking the tree (GoUpToFindLeavesToUpdate / GoDown…, hpp:625,707)
    to find leaves whose feature ranges are contiguous to a changed
    leaf, with per-threshold cumulative extremum arrays in the advanced
    mode. Here the same information lives in flat per-leaf FEATURE-RANGE
    BOXES, and the true constraint set is computed exactly in one
    vectorized pass: monotonicity along feature f relates leaves a, b
    iff their boxes overlap in every other feature and a's f-range lies
    strictly below b's (leaf boxes partition the space, so overlapping
    everywhere else forces disjoint f-ranges). `out_a <= out_b` over
    exactly those pairs is the minimal sound constraint set — it
    subsumes both reference methods (their ancestor-based sets are
    supersets of these pairs), so one mechanism serves both modes.

    box_lo/box_hi: [L, F] int32 inclusive bin ranges; outputs: [L];
    leaf_valid: [L] bool (slots in use); monotone: [F] in {-1, 0, +1}.
    Returns (min_bound, max_bound): [L] f32.
    """
    f32 = outputs.dtype
    num_l, num_f = box_lo.shape

    # Never materialize [L, L, F]: at F=10k (the wide-sparse regime)
    # that is ~650M elements per scan step. Everything stays [L, L] via
    # a rolled loop over features.
    def _ov(f):
        return ((box_lo[:, None, f] <= box_hi[None, :, f])
                & (box_lo[None, :, f] <= box_hi[:, None, f]))

    ov_cnt = lax.fori_loop(
        0, num_f,
        lambda f, acc: acc + _ov(f).astype(jnp.int32),
        jnp.zeros((num_l, num_l), jnp.int32))

    def _accum(f, p_rel):
        # overlap in all features except f <=> ov_cnt - ov_f == F-1
        rel = ((box_hi[:, None, f] < box_lo[None, :, f])
               & ((ov_cnt - _ov(f).astype(jnp.int32)) == (num_f - 1)))
        m = monotone[f]
        return p_rel | (rel & (m > 0)) | (rel.T & (m < 0))

    # P[a, b] = "out_a <= out_b required"
    p_rel = lax.fori_loop(0, num_f, _accum,
                          jnp.zeros((num_l, num_l), jnp.bool_))
    p_rel = p_rel & leaf_valid[:, None] & leaf_valid[None, :]
    inf = jnp.asarray(jnp.inf, f32)
    max_bound = jnp.min(jnp.where(p_rel, outputs[None, :], inf), axis=1)
    min_bound = jnp.max(jnp.where(p_rel, outputs[:, None], -inf), axis=0)
    return min_bound, max_bound


def split_child_boxes(box_lo, box_hi, leaf, new_leaf, feat, thr,
                      is_cat_split, valid):
    """Update leaf boxes after applying a split: left keeps `leaf`'s id
    with f-range capped at thr, right (`new_leaf`) starts at thr+1.
    Categorical splits leave both ranges untouched (no order semantics;
    the reference likewise descends categorical children conservatively,
    monotone_constraints.hpp:598-601)."""
    p_lo, p_hi = box_lo[leaf], box_hi[leaf]
    l_hi = jnp.where(is_cat_split, p_hi, p_hi.at[feat].set(
        jnp.minimum(p_hi[feat], thr)))
    r_lo = jnp.where(is_cat_split, p_lo, p_lo.at[feat].set(
        jnp.maximum(p_lo[feat], thr + 1)))
    box_lo = box_lo.at[new_leaf].set(jnp.where(valid, r_lo,
                                               box_lo[new_leaf]))
    box_hi = box_hi.at[leaf].set(jnp.where(valid, l_hi, box_hi[leaf]))
    box_hi = box_hi.at[new_leaf].set(jnp.where(valid, p_hi,
                                               box_hi[new_leaf]))
    return box_lo, box_hi


def _monotone_penalty_factor(depth, hp: SplitHyperParams):
    """Multiplicative gain penalty for splits on monotone-constrained
    features (ref: monotone_constraints.hpp:358
    ComputeMonotoneSplitGainPenalty)."""
    pen = hp.monotone_penalty
    dep = jnp.maximum(depth, 0).astype(jnp.float32)
    factor = jnp.where(
        pen >= dep + 1.0, K_EPSILON,
        jnp.where(pen <= 1.0, 1.0 - pen / (2.0 ** dep) + K_EPSILON,
                  1.0 - 2.0 ** (pen - 1.0 - dep) + K_EPSILON))
    return jnp.where(pen > 0, factor, 1.0)


def _gain_tensors(hist: jax.Array,
                  parent_sum_grad: jax.Array,
                  parent_sum_hess: jax.Array,
                  parent_count: jax.Array,
                  meta: FeatureMeta,
                  hp: SplitHyperParams,
                  feature_mask: jax.Array,
                  parent_output,
                  min_bound,
                  max_bound,
                  depth,
                  has_categorical: bool,
                  rand_bins=None):
    """NET candidate gains for every (feature, threshold, variant).

    Variants: A numerical/missing-right, B numerical/missing-left,
    C categorical one-hot, and (when has_categorical) D/E categorical
    sorted-subset scans in ascending/descending grad-ratio order
    (ref: feature_histogram.cpp:243-344 categorical branch).

    rand_bins: optional [F] int32 — extra-trees mode: only this bin is a
    numerical split candidate per feature (ref: feature_histogram.hpp:205
    rand_threshold in BeforeNumerical, checked at :897,:995).

    Gains are net of (parent_gain + min_gain_to_split) with the monotone
    split penalty applied, so a positive entry is a strictly improving
    split. Returns (gains [F, B, V], aux dict).
    """
    num_features, num_bin_slots, _ = hist.shape
    prefix = jnp.cumsum(hist, axis=1)  # [F, B, 3]
    t_idx = jnp.arange(num_bin_slots, dtype=jnp.int32)[None, :]  # [1, B]
    nb = meta.num_bins[:, None]  # [F, 1]

    # --- variant A: missing (NaN bin = last) goes RIGHT; left = prefix[t]
    left_a = prefix  # [F, B, 3]
    # --- variant B: missing goes LEFT. right = (non-NaN rows above t)
    #     = prefix[nb-2] - prefix[t]; left = parent - right.
    last_non_nan = jnp.take_along_axis(
        prefix, jnp.maximum(meta.num_bins - 2, 0)[:, None, None], axis=1)  # [F,1,3]
    right_b = jnp.maximum(last_non_nan - prefix, 0.0)

    parent = jnp.stack([parent_sum_grad, parent_sum_hess, parent_count])

    # net-gain shift (ref: FindBestThresholdFromHistogram min_gain_shift;
    # with smoothing the parent's gain is evaluated at its actual output)
    parent_gain = jnp.where(
        hp.path_smooth > 0,
        leaf_gain_given_output(parent_sum_grad, parent_sum_hess,
                               parent_output, hp),
        leaf_gain(parent_sum_grad, parent_sum_hess, hp))
    shift = parent_gain + hp.min_gain_to_split

    # monotone split penalty (multiplies the net gain of candidates on
    # monotone features; ref: serial_tree_learner.cpp:1001-1005)
    mono_factor = _monotone_penalty_factor(depth, hp)
    mono_feat = (meta.monotone != 0)[:, None]

    # CEGB delta per feature (ref: cost_effective_gradient_boosting.hpp
    # DeltaGain: tradeoff*penalty_split*n_leaf + coupled-first-use +
    # lazy per-row costs; coupled/lazy are pre-scaled by tradeoff on host)
    cegb_delta = (meta.cegb_feat
                  + (hp.cegb_split_pen + meta.cegb_lazy) * parent_count)

    def eval_variant(left, right, valid_extra, hp_eff):
        gl, hl, cl = left[..., GRAD], left[..., HESS], left[..., COUNT]
        gr, hr, cr = right[..., GRAD], right[..., HESS], right[..., COUNT]
        out_l = smooth_output(leaf_output(gl, hl, hp_eff), cl, parent_output,
                              hp_eff)
        out_r = smooth_output(leaf_output(gr, hr, hp_eff), cr, parent_output,
                              hp_eff)
        # per-leaf output bounds from ancestors' monotone splits
        # (ref: monotone_constraints.hpp:466 BasicLeafConstraints)
        out_l = jnp.clip(out_l, min_bound, max_bound)
        out_r = jnp.clip(out_r, min_bound, max_bound)
        gain = (leaf_gain_given_output(gl, hl, out_l, hp_eff)
                + leaf_gain_given_output(gr, hr, out_r, hp_eff))
        # monotone split check: increasing (+1) needs out_l <= out_r
        mono = meta.monotone[:, None]
        mono_ok = jnp.where(
            mono == 0, True,
            jnp.where(mono > 0, out_l <= out_r, out_l >= out_r))
        valid = (
            valid_extra
            & mono_ok
            & (cl >= jnp.maximum(hp.min_data_in_leaf, 1.0))
            & (cr >= jnp.maximum(hp.min_data_in_leaf, 1.0))
            & (hl >= hp.min_sum_hessian_in_leaf)
            & (hr >= hp.min_sum_hessian_in_leaf)
            & feature_mask[:, None]
        )
        net = (gain * meta.penalty[:, None] - cegb_delta[:, None] - shift)
        net = jnp.where(mono_feat, net * mono_factor, net)
        # structural-tie rejection (see K_GAIN_TIE_RTOL): a candidate
        # must clear the f32 noise band of the gain arithmetic to count
        # as an improvement at all
        tie = K_GAIN_TIE_RTOL * jnp.maximum(jnp.abs(shift), 1.0)
        return jnp.where(valid & (net > tie), net, K_MIN_SCORE)

    is_cat = meta.is_categorical[:, None]
    base_valid_a = (t_idx < nb - 1) & ~is_cat
    has_nan = meta.missing_type[:, None] == MISSING_NAN
    base_valid_b = has_nan & (t_idx < nb - 2) & ~is_cat
    if rand_bins is not None:
        rand_ok = t_idx == rand_bins[:, None]
        base_valid_a = base_valid_a & rand_ok
        base_valid_b = base_valid_b & rand_ok
    gains_a = eval_variant(left_a, parent[None, None, :] - left_a,
                           base_valid_a, hp)
    gains_b = eval_variant(parent[None, None, :] - right_b, right_b,
                           base_valid_b, hp)

    # --- variant C: categorical one-hot split, bin == t goes LEFT
    # (ref: feature_histogram.cpp:188-242 one-hot branch when
    # num_bins <= max_cat_to_onehot; bin 0 = "other/unseen" never splits
    # left so binned and raw-value prediction stay consistent)
    left_c = hist
    onehot_ok = nb <= hp.max_cat_to_onehot
    base_valid_c = is_cat & onehot_ok & (t_idx >= 1) & (t_idx < nb)
    gains_c = eval_variant(left_c, parent[None, None, :] - left_c,
                           base_valid_c, hp)

    aux = dict(left_a=left_a, right_b=right_b, left_c=left_c, parent=parent,
               parent_gain=parent_gain)

    if not has_categorical:
        gains = jnp.stack([gains_a, gains_b, gains_c], axis=-1)  # [F, B, 3]
        return gains, aux

    # --- variants D/E: categorical sorted-subset scan
    # (ref: feature_histogram.cpp:243-344): bins with enough estimated
    # count enter, sorted ascending by g/(h + cat_smooth); prefixes of the
    # sorted order (D) and of the reversed order (E) go left, with
    # l2 += cat_l2 and a min_data_per_group thinning of candidates.
    hp_cat = hp._replace(lambda_l2=hp.lambda_l2 + hp.cat_l2)
    g_b, h_b, c_b = hist[..., GRAD], hist[..., HESS], hist[..., COUNT]
    eligible = (t_idx >= 1) & (t_idx < nb) & (c_b >= hp.cat_smooth) & is_cat
    ratio = g_b / (h_b + hp.cat_smooth)
    sort_key = jnp.where(eligible, ratio, jnp.inf)
    order = jnp.argsort(sort_key, axis=1)                    # [F, B]
    rank = jnp.argsort(order, axis=1).astype(jnp.int32)       # [F, B]
    used = jnp.sum(eligible, axis=1).astype(jnp.int32)        # [F]
    sorted_hist = jnp.take_along_axis(hist, order[:, :, None], axis=1)
    pos_ok = t_idx < used[:, None]
    sorted_hist = jnp.where(pos_ok[:, :, None], sorted_hist, 0.0)
    sortP = jnp.cumsum(sorted_hist, axis=1)                   # [F, B, 3]
    totalP = jnp.take_along_axis(
        sortP, jnp.maximum(used - 1, 0)[:, None, None], axis=1)  # [F,1,3]
    totalP = jnp.where((used > 0)[:, None, None], totalP, 0.0)

    # descending-direction prefix: last i+1 eligible bins
    idx_rev = used[:, None] - 2 - t_idx                       # [F, B]
    take_rev = jnp.take_along_axis(
        sortP, jnp.clip(idx_rev, 0, num_bin_slots - 1)[:, :, None], axis=1)
    left_e = totalP - jnp.where((idx_rev >= 0)[:, :, None], take_rev, 0.0)

    # candidate validity: position in range, bounded subset size
    # (max_num_cat = min(max_cat_threshold, (used+1)/2),
    #  feature_histogram.cpp:267-269)
    max_num_cat = jnp.minimum(hp.max_cat_threshold, (used[:, None] + 1) // 2)
    cat_pos_ok = pos_ok & (t_idx < max_num_cat) & is_cat & ~onehot_ok
    # min_data_per_group thinning: emit a candidate only when the data
    # accumulated since the previous candidate reaches the group minimum.
    # The reference resets a running counter at each emission
    # (feature_histogram.cpp:280-317); the crossing-of-multiples form
    # below is its vectorized equivalent up to overshoot at boundaries.
    G = jnp.maximum(hp.min_data_per_group, 1.0)

    def group_ok(P):
        cum_c = P[..., COUNT]
        prev_c = jnp.concatenate(
            [jnp.zeros_like(cum_c[:, :1]), cum_c[:, :-1]], axis=1)
        return jnp.floor(cum_c / G) > jnp.floor(prev_c / G)

    # right side must also keep min_data_per_group
    # (feature_histogram.cpp:302-305)
    right_big_d = (parent[COUNT] - sortP[..., COUNT]) >= G
    right_big_e = (parent[COUNT] - left_e[..., COUNT]) >= G
    gains_d = eval_variant(sortP, parent[None, None, :] - sortP,
                           cat_pos_ok & group_ok(sortP) & right_big_d, hp_cat)
    gains_e = eval_variant(left_e, parent[None, None, :] - left_e,
                           cat_pos_ok & group_ok(left_e) & right_big_e,
                           hp_cat)

    gains = jnp.stack([gains_a, gains_b, gains_c, gains_d, gains_e],
                      axis=-1)  # [F, B, 5]
    aux.update(sortP=sortP, left_e=left_e, rank=rank, used=used,
               eligible=eligible)
    return gains, aux


def per_feature_best_gain(hist, parent_sum_grad, parent_sum_hess,
                          parent_count, meta: FeatureMeta,
                          hp: SplitHyperParams, feature_mask,
                          parent_output=None, min_bound=None, max_bound=None,
                          depth=None, has_categorical: bool = True
                          ) -> jax.Array:
    """Best candidate net gain per feature ([F]) — the voting statistic
    each worker computes from its local histograms (ref:
    voting_parallel_tree_learner.cpp:353 local FindBestThreshold + MaxK)."""
    if parent_output is None:
        parent_output = jnp.float32(0.0)
    if min_bound is None:
        min_bound = jnp.float32(-jnp.inf)
    if max_bound is None:
        max_bound = jnp.float32(jnp.inf)
    if depth is None:
        depth = jnp.int32(1)
    gains, _ = _gain_tensors(hist, parent_sum_grad, parent_sum_hess,
                             parent_count, meta, hp, feature_mask,
                             parent_output, min_bound, max_bound, depth,
                             has_categorical)
    return jnp.max(gains, axis=(1, 2))


def find_best_split(hist: jax.Array,
                    parent_sum_grad: jax.Array,
                    parent_sum_hess: jax.Array,
                    parent_count: jax.Array,
                    meta: FeatureMeta,
                    hp: SplitHyperParams,
                    feature_mask: jax.Array,
                    parent_output=None,
                    min_bound=None,
                    max_bound=None,
                    depth=None,
                    has_categorical: bool = True,
                    rand_bins=None) -> SplitInfo:
    """Find the best split across all features for one leaf.

    hist: [F, B, 3]; parent_*: scalars; feature_mask: [F] bool (feature
    fraction / interaction constraints); parent_output: scalar output of
    the leaf being split (path smoothing); min_bound/max_bound: the
    leaf's output bounds from ancestor monotone splits; depth: the
    leaf's depth (monotone penalty); rand_bins: optional [F] extra-trees
    random thresholds. Returns scalar SplitInfo.
    """
    # trace-time only: counts split-search (re)compilations
    global_metrics.note_trace("ops/split_search")
    if parent_output is None:
        parent_output = jnp.float32(0.0)
    if min_bound is None:
        min_bound = jnp.float32(-jnp.inf)
    if max_bound is None:
        max_bound = jnp.float32(jnp.inf)
    if depth is None:
        depth = jnp.int32(1)
    num_bin_slots = hist.shape[1]
    gains, aux = _gain_tensors(
        hist, parent_sum_grad, parent_sum_hess, parent_count, meta, hp,
        feature_mask, parent_output, min_bound, max_bound, depth,
        has_categorical, rand_bins)
    parent = aux["parent"]
    num_variants = gains.shape[-1]
    flat = gains.reshape(-1)
    best = jnp.argmax(flat)
    gain = flat[best]  # already net of parent gain + min_gain_to_split

    feature = (best // (num_bin_slots * num_variants)).astype(jnp.int32)
    threshold = ((best // num_variants) % num_bin_slots).astype(jnp.int32)
    variant = (best % num_variants).astype(jnp.int32)
    variant_b = variant == 1
    variant_c = variant == 2

    la = aux["left_a"][feature, threshold]
    rb = aux["right_b"][feature, threshold]
    lc_ = aux["left_c"][feature, threshold]
    left = jnp.where(variant_b, parent - rb, jnp.where(variant_c, lc_, la))
    bidx = jnp.arange(num_bin_slots, dtype=jnp.int32)
    cat_mask = variant_c & (bidx == threshold)

    if num_variants == 5:
        variant_d = variant == 3
        variant_e = variant == 4
        ld = aux["sortP"][feature, threshold]
        le = aux["left_e"][feature, threshold]
        left = jnp.where(variant_d, ld, jnp.where(variant_e, le, left))
        rank_f = aux["rank"][feature]
        used_f = aux["used"][feature]
        elig_f = aux["eligible"][feature]
        mask_d = (rank_f <= threshold) & elig_f
        mask_e = (rank_f >= used_f - 1 - threshold) & elig_f
        cat_mask = jnp.where(variant_d, mask_d,
                             jnp.where(variant_e, mask_e, cat_mask))
    right = parent - left

    is_cat_split = variant >= 2
    l2_eff = hp.lambda_l2 + jnp.where(variant >= 3, hp.cat_l2, 0.0)
    hp_out = hp._replace(lambda_l2=l2_eff)

    mt = meta.missing_type[feature]
    default_left = jnp.where(
        is_cat_split, False,
        jnp.where(mt == MISSING_NAN, variant_b,
                  jnp.where(mt == MISSING_ZERO,
                            meta.default_bin[feature] <= threshold, False)))

    out_l = jnp.clip(
        leaf_output_smooth(left[GRAD], left[HESS], left[COUNT],
                           parent_output, hp_out), min_bound, max_bound)
    out_r = jnp.clip(
        leaf_output_smooth(right[GRAD], right[HESS], right[COUNT],
                           parent_output, hp_out), min_bound, max_bound)

    return SplitInfo(
        gain=gain,
        feature=feature,
        threshold=threshold,
        default_left=default_left,
        left_sum_grad=left[GRAD], left_sum_hess=left[HESS], left_count=left[COUNT],
        right_sum_grad=right[GRAD], right_sum_hess=right[HESS], right_count=right[COUNT],
        left_output=out_l,
        right_output=out_r,
        cat_mask=cat_mask,
    )
