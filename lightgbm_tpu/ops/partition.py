"""Row partition op (device).

TPU-native replacement for the reference DataPartition
(ref: src/treelearner/data_partition.hpp:22, cuda_data_partition.cu:291).
Rather than physically permuting row indices per leaf, we keep a full-length
``row_leaf: [N] int32`` map (row -> leaf id) and update it with masked
`where` — the mask-over-permutation idiom that XLA/TPU prefers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .split import MISSING_NAN


def feature_bins(bins_fm: jax.Array, feature: jax.Array,
                 bundle=None) -> jax.Array:
    """Logical [N] bin column of `feature` — a plain row slice for a
    dense matrix, or an on-the-fly decode of the EFB-bundled matrix
    (bundle = (group_of, offset_of, num_bins) device arrays; ref:
    feature_group.h bin_offsets_ decoding)."""
    if bundle is None:
        return jnp.take(bins_fm, feature, axis=0).astype(jnp.int32)
    group_of, offset_of, nb = bundle
    col = jnp.take(bins_fm, group_of[feature], axis=0).astype(jnp.int32)
    off = offset_of[feature]
    in_range = (col >= off) & (col < off + nb[feature] - 1)
    return jnp.where(in_range, col - off + 1, 0)


def apply_split(row_leaf: jax.Array, bins_fm: jax.Array,
                leaf_id: jax.Array, new_leaf_id: jax.Array,
                feature: jax.Array, threshold: jax.Array,
                default_left: jax.Array, cat_mask: jax.Array,
                num_bins: jax.Array, missing_type: jax.Array,
                is_categorical: jax.Array, valid: jax.Array,
                bundle=None) -> jax.Array:
    """Send rows of `leaf_id` that fail the decision to `new_leaf_id`.

    Numerical: bin <= threshold -> left; the NaN bin (last bin when
    missing_type == NAN) follows `default_left`. Categorical: bins set in
    `cat_mask` ([B] bool — the device analog of the reference's category
    bitset, tree.h:375) go left. No-op when `valid` is False.
    """
    fbins = feature_bins(bins_fm, feature, bundle)  # [N]
    nan_bin = num_bins[feature] - 1
    is_nan = (missing_type[feature] == MISSING_NAN) & (fbins == nan_bin)
    numerical = jnp.where(is_nan, default_left, fbins <= threshold)
    go_left = jnp.where(is_categorical[feature], cat_mask[fbins], numerical)
    move = valid & (row_leaf == leaf_id) & ~go_left
    return jnp.where(move, new_leaf_id, row_leaf)
