"""User-facing Dataset / Booster API.

Mirrors the reference python-package surface
(ref: python-package/lightgbm/basic.py:1692 Dataset, :3495 Booster) with
lazy Dataset construction, aligned validation binning via `reference=`,
and a Booster wrapping the TPU boosting engine instead of ctypes into
lib_lightgbm.so.
"""

from __future__ import annotations

import abc
import json
from copy import deepcopy
from pathlib import Path
from typing import Any, Dict, List, Optional, Union
from typing import Sequence as _SequenceT

import numpy as np

from .boosting import GBDT, create_boosting
from .config import Config
from .dataset import BinnedDataset, Metadata
from .metrics import create_metrics
from .model_io import (dump_model_to_json, load_model_from_string,
                       save_model_to_string, LoadedModel)
from .objectives import create_objective


class LightGBMError(Exception):
    """(ref: basic.py LightGBMError)"""


from .dataset import is_sparse as _is_sparse


def _to_2d(data):
    if _is_sparse(data):
        # kept sparse end-to-end (see BinnedDataset.from_sparse);
        # normalized to CSR so row slicing (subset, cv folds) works
        return data.tocsr()
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr


class Sequence(abc.ABC):
    """Generic batched data-access interface (ref: basic.py:841
    lightgbm.Sequence): subclasses provide random row access
    (``seq[i]`` -> 1D row, ``seq[a:b]`` -> 2D batch) and ``len(seq)``;
    ``batch_size`` bounds how many rows are read per range access.
    A Dataset accepts one Sequence or a list of them (row-concatenated)
    and reads through them in batches, so producers never hand over one
    giant in-memory matrix."""

    batch_size: int = 4096

    @abc.abstractmethod
    def __getitem__(self, idx):
        raise NotImplementedError("scikit-learn requires __getitem__")

    @abc.abstractmethod
    def __len__(self) -> int:
        raise NotImplementedError


def _materialize_sequences(seqs) -> np.ndarray:
    """Batched read-through of one or more Sequence objects -> [N, F]."""
    parts = []
    for seq in seqs:
        n = len(seq)
        bs = max(int(getattr(seq, "batch_size", 4096) or 4096), 1)
        for lo in range(0, n, bs):
            batch = np.asarray(seq[lo:min(lo + bs, n)], np.float64)
            parts.append(batch if batch.ndim == 2 else batch[None, :])
    if not parts:
        raise LightGBMError("empty Sequence data")
    return np.concatenate(parts, axis=0)


class Dataset:
    """Lazily-constructed training dataset (ref: basic.py:1692)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = False, position=None):
        if isinstance(data, Sequence):
            data = _materialize_sequences([data])
        elif isinstance(data, (list, tuple)) and data and any(
                isinstance(s, Sequence) for s in data):
            if not all(isinstance(s, Sequence) for s in data):
                raise TypeError(
                    "a chunked Dataset input must be a list of Sequence "
                    "objects only (mixed Sequence/array lists are not "
                    "supported)")
            data = _materialize_sequences(data)
        if isinstance(data, (str, Path)):
            path = str(data)
            with open(path, "rb") as fh:
                magic = fh.read(2)
            loaded = None
            if magic == b"PK":  # zip container: try the binary-dataset path
                from .io.binary_format import load_dataset_binary
                try:
                    loaded = load_dataset_binary(path)
                except Exception:
                    loaded = None  # not ours — fall through to text parsing
            if loaded is not None:
                self.__dict__.update(loaded.__dict__)
                # user-supplied metadata overrides the stored copy
                for value, setter in ((label, self.set_label),
                                      (weight, self.set_weight),
                                      (group, self.set_group),
                                      (init_score, self.set_init_score)):
                    if value is not None:
                        setter(value)
                if reference is not None:
                    # stored bins must match the reference's mappers, or
                    # eval would silently run on mis-binned data
                    reference.construct()
                    ref_b, own_b = reference._binned, self._binned
                    same = (len(ref_b.mappers) == len(own_b.mappers) and all(
                        rm.num_bins == om.num_bins and
                        rm.is_categorical == om.is_categorical and
                        (rm.bin_upper_bound is None or
                         om.bin_upper_bound is None or
                         np.array_equal(rm.bin_upper_bound,
                                        om.bin_upper_bound))
                        for rm, om in zip(ref_b.mappers, own_b.mappers)))
                    if not same:
                        raise LightGBMError(
                            f"binary dataset {path} was binned differently "
                            "from the reference dataset; rebuild it with "
                            "save_binary against the same training data, "
                            "or pass the text file instead")
                    self.reference = reference
                _DATASET_PARAM_KEYS = {
                    "max_bin", "max_bin_by_feature", "min_data_in_bin",
                    "bin_construct_sample_cnt", "use_missing",
                    "zero_as_missing", "feature_pre_filter",
                    "categorical_feature", "forcedbins_filename"}
                dropped = _DATASET_PARAM_KEYS & set(params or {})
                if dropped:
                    import warnings
                    warnings.warn(
                        f"dataset params {sorted(dropped)} are ignored when "
                        "loading a binary dataset file (binning is fixed)")
                return
            from .io.text_loader import (load_svmlight_or_csv,
                                         sidecar_init_score,
                                         sidecar_position)
            data, file_label, file_weight, file_group = \
                load_svmlight_or_csv(path, params or {})
            if label is None:
                label = file_label
            if weight is None:
                weight = file_weight
            if group is None:
                group = file_group
            if init_score is None:
                init_score = sidecar_init_score(path)
            if position is None:
                position = sidecar_position(path)
        from .io.arrow_ingest import arrow_to_matrix, arrow_to_vector, is_arrow
        if is_arrow(data):
            # Arrow table via the PyCapsule C-ABI protocol — no pyarrow
            # needed (ref: arrow.h:34, LGBM_DatasetCreateFromArrow)
            data, arrow_names = arrow_to_matrix(data)
            if feature_name == "auto" and arrow_names:
                feature_name = arrow_names
        if label is not None and is_arrow(label):
            label = arrow_to_vector(label)
        if weight is not None and is_arrow(weight):
            weight = arrow_to_vector(weight)
        if init_score is not None and is_arrow(init_score):
            init_score = arrow_to_vector(init_score)
        if group is not None and is_arrow(group):
            group = arrow_to_vector(group)
        self.data = _to_2d(data)
        self.label = label
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.position = position
        self.reference = reference
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._binned: Optional[BinnedDataset] = None
        self.used_indices = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._binned is not None:
            return self
        cfg = Config.from_params(self.params)
        meta = Metadata(self.data.shape[0])
        if self.label is not None:
            meta.set_label(self.label)
        else:
            meta.set_label(np.zeros(self.data.shape[0]))
        meta.set_weight(self.weight)
        if self.group is not None:
            meta.set_group(self.group)
        meta.set_init_score(self.init_score)
        if self.position is not None:
            meta.set_position(self.position)

        cat_indices: List[int] = []
        names = self._feature_names()
        if isinstance(self.categorical_feature, (list, tuple)):
            for c in self.categorical_feature:
                if isinstance(c, str) and c in names:
                    cat_indices.append(names.index(c))
                elif isinstance(c, (int, np.integer)):
                    cat_indices.append(int(c))

        ref_binned = None
        if self.reference is not None:
            self.reference.construct()
            ref_binned = self.reference._binned

        forced_bins = None
        fb_file = cfg.forcedbins_filename
        if fb_file:
            with open(fb_file) as fh:
                spec = json.load(fh)
            forced_bins = {int(e["feature"]): e["bin_upper_bound"]
                           for e in spec}

        from .obs.trace import global_tracer
        with global_tracer.span("data/binning"):
            if _is_sparse(self.data):
                self._binned = BinnedDataset.from_sparse(
                    self.data, cfg, metadata=meta,
                    categorical_features=cat_indices,
                    feature_names=names, reference=ref_binned,
                    forced_bins=forced_bins)
            else:
                self._binned = BinnedDataset.from_matrix(
                    self.data, cfg, metadata=meta,
                    categorical_features=cat_indices,
                    feature_names=names, reference=ref_binned,
                    forced_bins=forced_bins)
        return self

    def _feature_names(self) -> List[str]:
        if isinstance(self.feature_name, list):
            return list(self.feature_name)
        return [f"Column_{i}" for i in range(self.data.shape[1])]

    # ------------------------------------------------------------------
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._binned is not None:
            self._binned.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._binned is not None:
            self._binned.metadata.set_weight(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._binned is not None:
            self._binned.metadata.set_group(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._binned is not None:
            self._binned.metadata.set_init_score(init_score)
        return self

    def get_label(self):
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def get_data(self):
        return self.data

    def num_data(self) -> int:
        if self.data is None and self._binned is not None:
            return self._binned.num_data
        return self.data.shape[0]

    def num_feature(self) -> int:
        if self.data is None and self._binned is not None:
            return self._binned.num_total_features
        return self.data.shape[1]

    def get_feature_name(self) -> List[str]:
        return self._feature_names()

    def subset(self, used_indices: _SequenceT[int],
               params: Optional[Dict] = None) -> "Dataset":
        """Row-subset view (ref: basic.py Dataset.subset)."""
        if self.data is None:
            raise LightGBMError(
                "cannot subset a dataset loaded from a binary file "
                "(raw feature values are not stored)")
        idx = np.asarray(used_indices)
        sub = Dataset(
            self.data[idx],
            label=None if self.label is None else np.asarray(self.label)[idx],
            weight=None if self.weight is None else np.asarray(self.weight)[idx],
            init_score=None if self.init_score is None
            else np.asarray(self.init_score)[idx],
            feature_name=self.feature_name,
            categorical_feature=self.categorical_feature,
            params=params or self.params,
            reference=self if self._binned is not None else None)
        sub.used_indices = idx
        return sub

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, weight=weight, group=group,
                       init_score=init_score, reference=self,
                       params=params or self.params)

    def save_binary(self, filename) -> "Dataset":
        """Binary serialization of the binned dataset
        (ref: Dataset::SaveBinaryFile dataset.h:710)."""
        self.construct()
        from .io.binary_format import save_dataset_binary
        save_dataset_binary(self, filename)
        return self


class Booster:
    """Training/prediction handle (ref: basic.py:3495)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._loaded: Optional[LoadedModel] = None
        self._gbdt: Optional[GBDT] = None
        self.train_set: Optional[Dataset] = None
        self._valid_sets: List[Dataset] = []
        self._name_valid_sets: List[str] = []
        self._metrics_cache: Dict[int, list] = {}
        self._network_params = None

        if model_file is not None:
            with open(model_file) as fh:
                self._loaded = load_model_from_string(fh.read())
            self._plumb_loaded_predict_params()
            return
        if model_str is not None:
            self._loaded = load_model_from_string(model_str)
            self._plumb_loaded_predict_params()
            return
        if train_set is None:
            raise LightGBMError(
                "Booster requires train_set, model_file or model_str")

        self.config = Config.from_params(self.params)
        from . import log
        log.set_verbosity(self.config.verbosity)
        # warm start by default: arm the persistent XLA compile cache at
        # THE training program boundary (compile_cache.py policy — a
        # second process re-running the same shapes pays ~zero compile
        # seconds). No-op when conftest/env/operator already armed one.
        from .compile_cache import configure as _configure_compile_cache
        _configure_compile_cache(self.config.tpu_compile_cache,
                                 self.config.tpu_compile_cache_dir or None)
        if self.config.trace_output:
            # param twin of LGBM_TPU_TRACE: record spans for this run and
            # write a Chrome trace at exit (obs/trace.py)
            from .obs.trace import global_tracer
            global_tracer.enable(path=self.config.trace_output)
        train_set.params = {**train_set.params, **self.params}
        train_set.construct()
        self.train_set = train_set
        objective = create_objective(self.config)
        if objective is None and self.config.objective not in ("none",):
            raise LightGBMError(f"unknown objective {self.config.objective}")
        binned = train_set._binned
        if self.config.tree_learner in ("data", "voting", "feature") or \
                self.config.num_machines > 1 or \
                int(self.params.get("tpu_num_shards", 0) or 0) > 1:
            from .parallel.data_parallel import create_parallel_boosting
            self._gbdt = create_parallel_boosting(self.config, binned,
                                                  objective)
        else:
            self._gbdt = create_boosting(self.config, binned, objective)

    def _plumb_loaded_predict_params(self) -> None:
        """Serving knobs for a loaded (file/string) model: alias-resolve
        the Booster params and hand tpu_predict_chunk / tpu_num_shards
        to the LoadedModel's streaming predict engine."""
        canon = {Config.canonical_key(k): v for k, v in self.params.items()}
        chunk = canon.get("tpu_predict_chunk")
        if chunk:
            self._loaded.predict_chunk = int(chunk)
        shards = int(canon.get("tpu_num_shards", 0) or 0)
        if shards > 1:
            self._loaded.predict_shards = shards

    # ------------------------------------------------------------------
    def _load_init_model(self, init_model) -> "Booster":
        """Continued training from a model file / string / Booster
        (ref: engine.py train init_model; boosting.cpp:74-90)."""
        if isinstance(init_model, Booster):
            loaded = load_model_from_string(init_model.model_to_string())
        elif isinstance(init_model, LoadedModel):
            loaded = init_model
        elif isinstance(init_model, str):
            with open(init_model) as fh:
                loaded = load_model_from_string(fh.read())
        else:
            raise TypeError(
                "init_model must be a Booster, LoadedModel, or filename")
        self._gbdt.init_from_loaded(loaded)
        return self

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.reference = data.reference or self.train_set
        data.construct()
        self._valid_sets.append(data)
        self._name_valid_sets.append(name)
        self._gbdt.add_valid(data._binned, data.data)
        return self

    def reset_train_set(self, train_set: Dataset) -> "Booster":
        """Replace the training data, keeping the current model
        (ref: GBDT::ResetTrainingData gbdt.cpp:214 /
        LGBM_BoosterResetTrainingData c_api.cpp:2086). The new data is
        binned against the current mappers and the existing trees'
        scores are replayed onto it."""
        if self._gbdt is None:
            raise LightGBMError(
                "reset_train_set requires a booster built on a Dataset")
        saved = None
        if any(self._gbdt.models):
            saved = load_model_from_string(self.model_to_string())
        train_set.reference = train_set.reference or self.train_set
        train_set.params = {**train_set.params, **self.params}
        train_set.construct()
        self.train_set = train_set
        self._metrics_cache.clear()
        objective = create_objective(self.config)
        binned = train_set._binned
        if self.config.tree_learner in ("data", "voting", "feature") or \
                self.config.num_machines > 1 or \
                int(self.params.get("tpu_num_shards", 0) or 0) > 1:
            from .parallel.data_parallel import create_parallel_boosting
            self._gbdt = create_parallel_boosting(self.config, binned,
                                                  objective)
        else:
            self._gbdt = create_boosting(self.config, binned, objective)
        if saved is not None:
            self._gbdt.init_from_loaded(saved)
        for ds in self._valid_sets:
            self._gbdt.add_valid(ds._binned, ds.data)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; True means training should stop
        (ref: basic.py Booster.update -> LGBM_BoosterUpdateOneIter)."""
        if train_set is not None and train_set is not self.train_set:
            self.reset_train_set(train_set)
        self._ensure_network()
        if fobj is not None:
            grad, hess = fobj(self._raw_train_scores(), self.train_set)
            return self._gbdt.train_one_iter(np.asarray(grad),
                                             np.asarray(hess))
        return self._gbdt.train_one_iter()

    def _raw_train_scores(self) -> np.ndarray:
        # score storage may carry padded tail rows when sharded
        s = np.asarray(self._gbdt.scores)[:, :self._gbdt.num_data]
        return s[0] if s.shape[0] == 1 else s.T.reshape(-1)

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        if self._loaded is not None:
            return self._loaded.num_iterations
        return self._gbdt.current_iteration()

    def num_trees(self) -> int:
        if self._loaded is not None:
            return len(self._loaded.trees)
        return self._gbdt.num_trees

    def num_feature(self) -> int:
        if self._loaded is not None:
            return self._loaded.max_feature_idx + 1
        return self.train_set.num_feature()

    def feature_name(self) -> List[str]:
        if self._loaded is not None:
            return self._loaded.feature_names
        return self.train_set.get_feature_name()

    # ------------------------------------------------------------------
    def _metrics_for(self, ds_binned, num_data: int):
        key = id(ds_binned)
        if key not in self._metrics_cache:
            names = self.config.metric or self.config.default_metric()
            ms = create_metrics(self.config, names)
            for m in ms:
                m.init(ds_binned.metadata, num_data)
            self._metrics_cache[key] = ms
        return self._metrics_cache[key]

    def _eval_scores(self, raw: np.ndarray, binned, name: str):
        obj = self._gbdt.objective
        raw2 = raw if raw.ndim == 2 else raw[:, None]
        squeezed = raw2[:, 0] if raw2.shape[1] == 1 else raw2
        prob = obj.convert_output(squeezed) if obj is not None else squeezed
        out = []
        for metric in self._metrics_for(binned, binned.num_data):
            for mname, value, hib in metric.eval(prob, squeezed):
                out.append((name, mname, value, hib))
        return out

    def eval_train(self, feval=None):
        raw = np.asarray(self._gbdt.scores)[:, :self._gbdt.num_data].T
        # [N, K]; padded tail rows (sharded storage) dropped above
        res = self._eval_scores(raw, self.train_set._binned, "training")
        if feval is not None:
            res += _call_feval(feval, raw, self.train_set, "training")
        return res

    def eval_valid(self, feval=None):
        out = []
        for i, (vs, name) in enumerate(zip(self._valid_sets,
                                           self._name_valid_sets)):
            raw = self._gbdt.valid_raw_scores(i)  # [N, K]
            out += self._eval_scores(raw, vs._binned, name)
            if feval is not None:
                out += _call_feval(feval, raw, vs, name)
        return out

    def eval(self, data: Dataset, name: str, feval=None):
        for i, vs in enumerate(self._valid_sets):
            if vs is data:
                raw = self._gbdt.valid_raw_scores(i)
                res = self._eval_scores(raw, vs._binned, name)
                if feval is not None:
                    res += _call_feval(feval, raw, vs, name)
                return res
        raw = self._gbdt.predict_raw(data.data)
        res = self._eval_scores(raw, data.construct()._binned, name)
        if feval is not None:
            res += _call_feval(feval, raw, data, name)
        return res

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        # per-call serving-engine override (alias-aware), e.g.
        # predict(X, tpu_predict_chunk=65536). Every alias is popped by
        # MEMBERSHIP (a falsy value left behind would collide with the
        # explicit kwarg in the sparse-batch recursion below)
        predict_chunk = None
        for key in ("tpu_predict_chunk", "predict_chunk",
                    "predict_chunk_rows"):
            if key in kwargs:
                val = kwargs.pop(key)
                if val and predict_chunk is None:
                    predict_chunk = int(val)
        if _is_sparse(data):
            # tree traversal reads raw feature values: densify in
            # row batches so peak host memory stays bounded
            from .dataset import sparse_row_batches
            if data.shape[0] == 0:
                data = np.zeros(data.shape)
            else:
                outs = [self.predict(b, start_iteration=start_iteration,
                                     num_iteration=num_iteration,
                                     raw_score=raw_score,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib,
                                     tpu_predict_chunk=predict_chunk,
                                     **kwargs)
                        for b in sparse_row_batches(data)]
                return np.concatenate(outs, axis=0)
        data = np.asarray(data, dtype=np.float64)
        if data.ndim == 1:
            data = data.reshape(1, -1)
        if self._loaded is not None:
            if pred_contrib:
                from .shap import loaded_pred_contrib
                return loaded_pred_contrib(self._loaded, data,
                                           start_iteration, num_iteration,
                                           predict_chunk=predict_chunk)
            if pred_leaf:
                return self._loaded.predict_leaf(
                    data, start_iteration=start_iteration,
                    num_iteration=num_iteration)
            return self._loaded.predict(data, raw_score=raw_score,
                                        start_iteration=start_iteration,
                                        num_iteration=num_iteration,
                                        predict_chunk=predict_chunk)
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        return self._gbdt.predict(data, raw_score=raw_score,
                                  start_iteration=start_iteration,
                                  num_iteration=num_iteration,
                                  pred_leaf=pred_leaf,
                                  pred_contrib=pred_contrib,
                                  predict_chunk=predict_chunk)

    def refit(self, data, label, decay_rate: float = 0.9, weight=None,
              **kwargs):
        """(ref: Booster.refit basic.py; GBDT::RefitTree gbdt.cpp:267)"""
        from .refit import refit_booster
        return refit_booster(self, data, label, decay_rate, weight=weight)

    # ------------------------------------------------------------------
    def model_to_string(self, num_iteration: int = -1,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        if self._loaded is not None:
            from .model_io import loaded_model_to_string
            return loaded_model_to_string(self._loaded, num_iteration,
                                          start_iteration, importance_type)
        return save_model_to_string(self._gbdt, num_iteration,
                                    start_iteration, importance_type)

    def save_model(self, filename, num_iteration: int = -1,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        with open(filename, "w") as fh:
            fh.write(self.model_to_string(num_iteration, start_iteration,
                                          importance_type))
        return self

    def dump_model(self, num_iteration: int = -1, start_iteration: int = 0
                   ) -> dict:
        return dump_model_to_json(self._gbdt, num_iteration, start_iteration)

    # ------------------------------------------------------------------
    def attr(self, key: str):
        """Booster attribute by name, or None (ref: Booster.attr
        python-package basic.py / LGBM_BoosterGetAttr)."""
        return getattr(self, "_attr", {}).get(key)

    def set_attr(self, **kwargs) -> "Booster":
        """Set string attributes; a None value deletes the key
        (ref: Booster.set_attr / LGBM_BoosterSetAttr)."""
        store = getattr(self, "_attr", None)
        if store is None:
            store = self._attr = {}
        for key, value in kwargs.items():
            if value is None:
                store.pop(key, None)
            else:
                if not isinstance(value, str):
                    raise LightGBMError(
                        "Only string values are accepted as attributes")
                store[key] = value
        return self

    def trees_to_dataframe(self):
        """The fitted model as one pandas row per node, with the
        reference's column schema (ref: Booster.trees_to_dataframe,
        python-package basic.py:3775)."""
        import pandas as pd

        if self.num_trees() == 0:
            raise LightGBMError(
                "There are no trees in this Booster and thus nothing "
                "to parse")
        feature_names = self.feature_name()
        rows = []

        def walk(node, tree_index, depth, parent):
            is_split = "split_index" in node
            node_id = (f"{tree_index}-S{node['split_index']}" if is_split
                       else f"{tree_index}-L{node.get('leaf_index', 0)}")
            rec = {
                "tree_index": tree_index,
                "node_depth": depth,
                "node_index": node_id,
                "left_child": None,
                "right_child": None,
                "parent_index": parent,
                "split_feature": None,
                "split_gain": np.nan,
                "threshold": np.nan,
                "decision_type": None,
                "missing_direction": None,
                "missing_type": None,
                "value": node.get("leaf_value"),
                "weight": node.get("leaf_weight"),
                "count": node.get("leaf_count"),
            }
            if is_split:
                f = node["split_feature"]
                rec.update(
                    split_feature=(feature_names[f]
                                   if f < len(feature_names)
                                   else f"Column_{f}"),
                    split_gain=node["split_gain"],
                    threshold=node["threshold"],
                    decision_type=node["decision_type"],
                    missing_direction=("left" if node["default_left"]
                                       else "right"),
                    missing_type=node["missing_type"],
                    value=node["internal_value"],
                    weight=node["internal_weight"],
                    count=node["internal_count"],
                )
            rows.append(rec)
            if is_split:
                left, right = node["left_child"], node["right_child"]

                def child_id(c):
                    return (f"{tree_index}-S{c['split_index']}"
                            if "split_index" in c
                            else f"{tree_index}-L{c.get('leaf_index', 0)}")

                rec["left_child"] = child_id(left)
                rec["right_child"] = child_id(right)
                walk(left, tree_index, depth + 1, node_id)
                walk(right, tree_index, depth + 1, node_id)

        if self._loaded is not None:
            # text-loaded models carry Tree objects directly
            tree_infos = [t.to_json(i)
                          for i, t in enumerate(self._loaded.trees)]
        else:
            tree_infos = self.dump_model()["tree_info"]
        for t in tree_infos:
            walk(t["tree_structure"], t["tree_index"], 1, None)
        return pd.DataFrame(rows)

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        if self._loaded is not None:
            # text-loaded model: accumulate over parsed trees, with the
            # same dtype/semantics as the live path
            # (ref: GBDT::FeatureImportance gbdt.cpp)
            n = self._loaded.max_feature_idx + 1
            out = np.zeros(n, np.float64)
            trees = self._loaded.trees
            # iteration <= 0 means all trees (ref: gbdt_model_text.cpp
            # FeatureImportance 'if (num_iteration > 0)')
            if iteration > 0:
                trees = trees[:iteration *
                              max(self._loaded.num_tree_per_iteration, 1)]
            for tree in trees:
                for i in range(tree.num_internal):
                    # the reference only counts splits with positive gain
                    # (ref: GBDT::FeatureImportance gbdt_model_text.cpp)
                    if float(tree.split_gain[i]) <= 0.0:
                        continue
                    f = int(tree.split_feature[i])
                    if importance_type == "split":
                        out[f] += 1.0
                    else:
                        out[f] += float(tree.split_gain[i])
            return out
        return self._gbdt.feature_importance(importance_type, iteration)

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self.config.update(params)
        self._gbdt.config = self.config
        from .ops.split import SplitHyperParams
        self._gbdt.hp = SplitHyperParams.from_config(self.config)
        self._gbdt.shrinkage_rate = self.config.learning_rate
        return self

    def free_dataset(self) -> "Booster":
        return self

    def free_network(self) -> "Booster":
        self._network_params = None
        return self

    def set_network(self, machines, local_listen_port=12400,
                    listen_time_out=120, num_machines=1) -> "Booster":
        """Join the machine list's distributed runtime. The reference's
        TCP collectives become jax.distributed + XLA collectives: the
        first machine is the coordinator and this process's rank comes
        from the LGBM_TPU_RANK env var (each reference worker likewise
        locates itself in mlist.txt)."""
        from . import log
        from .parallel import distributed as dist
        if (not num_machines or int(num_machines) <= 1) and machines:
            # reference configs often leave num_machines at 1 and rely
            # on the machine list length
            num_machines = len(dist.parse_machine_list(machines))
        # Like the reference's SetNetwork, only RECORD the config here;
        # joining the runtime blocks until all ranks arrive, so it is
        # deferred to the first update() (see _ensure_network) instead of
        # hanging API-compat callers at set_network time.
        self._network_params = dict(machines=machines,
                                    local_listen_port=local_listen_port,
                                    listen_time_out=listen_time_out,
                                    num_machines=num_machines)
        import os
        if (num_machines and int(num_machines) > 1
                and os.environ.get("LGBM_TPU_RANK") is None
                and not dist.is_initialized()):
            log.warning(
                "set_network: machine list given but LGBM_TPU_RANK is "
                "unset — cannot determine this process's rank, so the "
                "distributed runtime will NOT be initialized; set "
                "LGBM_TPU_RANK or call parallel.distributed."
                "init_distributed(process_id=...) directly")
        return self

    def _ensure_network(self) -> None:
        """Join the recorded machine list at training start (deferred
        from set_network; no-op when the runtime is already up)."""
        from . import log
        from .parallel import distributed as dist
        np_ = self._network_params
        if not np_ or dist.is_initialized():
            return
        num_machines = np_.get("num_machines") or 1
        if int(num_machines) <= 1:
            return
        import os
        if os.environ.get("LGBM_TPU_RANK") is None:
            return  # already warned at set_network time
        timeout_min = np_.get("listen_time_out")
        try:
            dist.init_distributed(
                machines=np_["machines"],
                num_processes=int(num_machines),
                # listen_time_out follows the reference's unit (minutes,
                # config.h time_out); jax wants seconds
                initialization_timeout=(None if timeout_min is None
                                        else float(timeout_min) * 60.0))
        except RuntimeError as exc:
            if "already initialized" in str(exc).lower():
                # the caller brought up the JAX runtime themselves — fine
                log.warning(f"set_network: distributed init skipped: {exc}")
            else:
                raise

    def shuffle_models(self, start_iteration=0, end_iteration=-1) -> "Booster":
        models = self._gbdt.models
        end = len(models) if end_iteration < 0 else end_iteration
        seg = models[start_iteration:end]
        np.random.shuffle(seg)
        self._gbdt.models = models[:start_iteration] + list(seg) + \
            models[end:]
        return self

    def __copy__(self):
        return self

    def __deepcopy__(self, memo):
        model_str = self.model_to_string()
        return Booster(model_str=model_str)


def _call_feval(feval, raw, dataset, name):
    out = []
    fevals = feval if isinstance(feval, (list, tuple)) else [feval]
    preds = raw[:, 0] if raw.ndim == 2 and raw.shape[1] == 1 else raw
    for f in fevals:
        res = f(preds, dataset)
        if isinstance(res, list):
            for mname, value, hib in res:
                out.append((name, mname, value, hib))
        else:
            mname, value, hib = res
            out.append((name, mname, value, hib))
    return out
