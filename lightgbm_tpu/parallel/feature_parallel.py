"""Feature-parallel tree learner — explicit shard_map collectives.

TPU-native FeatureParallelTreeLearner (ref: parallel_tree_learner.h:27,
src/treelearner/feature_parallel_tree_learner.cpp:63-80): every shard
holds the FULL row set (data replicated, like every machine loading the
full dataset), but histogram construction and split search are sharded
over the feature axis. Each shard finds the best split among its feature
slice, then the per-shard winners are all-gathered and the global best
chosen (SyncUpGlobalBestSplit's Allgather + reduce). Row partitioning
needs no sync — every shard applies the same split to its full row copy.

Compute per shard drops to O(F/W * B); comm per split is one SplitInfo
all_gather (O(W) scalars) — the cheapest of the three strategies, at the
price of replicated data (exactly the reference's trade-off).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..learner import TreeArrays, _LeafSplits, _store_split
from ..obs import health as obs_health
from ..obs import xla as obs_xla
from ..ops import histogram as hist_ops
from ..ops import partition as part_ops
from ..ops import split as split_ops
from ..ops.split import (FeatureMeta, K_MIN_SCORE, SplitHyperParams,
                         SplitInfo, find_best_split, leaf_output,
                         propagate_monotone_bounds)
from . import mesh as mesh_lib
from .scatter import allgather_argmax_best


def _sync_best_split(info: SplitInfo, feat_offset, axis_name,
                     loop_factor: int = 1) -> SplitInfo:
    """All-gather per-shard winners, keep the globally best
    (ref: feature_parallel_tree_learner.cpp:63 SyncUpGlobalBestSplit).
    Shared combiner with the reduce-scatter learner (parallel/scatter.py);
    this learner's feature indices are slice-local, so they shift to
    global before the gather. loop_factor: static trip count of the
    enclosing scan, for the health wrappers' byte/call attribution."""
    info = info._replace(feature=info.feature + feat_offset)
    return allgather_argmax_best(info, axis_name, tag="split/all_gather",
                                 loop_factor=loop_factor)


def grow_tree_feature_parallel(bins_fm, grad, hess, sample_mask,
                               feature_mask, meta: FeatureMeta,
                               hp: SplitHyperParams, max_depth,
                               *, num_leaves: int, max_bins: int,
                               num_shards: int,
                               axis_name: str = mesh_lib.DATA_AXIS,
                               hist_dtype=jnp.float32,
                               hist_impl: str = "xla",
                               hist_deterministic: bool = False,
                               has_categorical: bool = True,
                               mono_pairwise: bool = False):
    """Runs INSIDE shard_map with fully-replicated inputs; each shard
    works on its feature slice. Outputs are replicated.

    mono_pairwise: exact pairwise leaf-box bounds (intermediate/advanced
    monotone methods); the [L, F] box state is over GLOBAL feature
    indices and fully replicated — identical deterministic updates on
    every shard, no extra collective."""
    num_features = bins_fm.shape[0]
    L = num_leaves
    f32 = hist_dtype
    # overlapping slices when W doesn't divide F: the last shards re-scan
    # a few features — duplicate candidates only tie in the argmax
    fp = -(-num_features // num_shards)
    start = jnp.minimum(lax.axis_index(axis_name) * fp,
                        jnp.maximum(num_features - fp, 0))
    fp = min(fp, num_features)

    bins_loc = lax.dynamic_slice_in_dim(bins_fm, start, fp, axis=0)
    meta_loc = jax.tree_util.tree_map(
        lambda a: lax.dynamic_slice_in_dim(a, start, fp, axis=0), meta)
    fmask_loc = lax.dynamic_slice_in_dim(feature_mask, start, fp, axis=0)

    build = functools.partial(hist_ops.build_histogram, max_bins=max_bins,
                              dtype=f32, row_chunk=0, impl=hist_impl,
                              deterministic=hist_deterministic)
    sync = functools.partial(_sync_best_split, feat_offset=start,
                             axis_name=axis_name)

    root_hist = build(bins_loc, grad, hess, sample_mask)
    root_g = jnp.sum(grad * sample_mask, dtype=f32)
    root_h = jnp.sum(hess * sample_mask, dtype=f32)
    root_c = jnp.sum(sample_mask, dtype=f32)
    root_out = leaf_output(root_g, root_h, hp)
    neg_inf, pos_inf = jnp.float32(-jnp.inf), jnp.float32(jnp.inf)
    root_split = sync(find_best_split(root_hist, root_g, root_h, root_c,
                                      meta_loc, hp, fmask_loc, root_out,
                                      neg_inf, pos_inf, jnp.int32(0),
                                      has_categorical))

    zero_l = jnp.zeros((L,), f32)
    leaves = _LeafSplits(
        sum_grad=zero_l, sum_hess=zero_l, count=zero_l,
        depth=jnp.zeros((L,), jnp.int32), output=zero_l,
        gain=jnp.full((L,), K_MIN_SCORE, f32),
        feature=jnp.zeros((L,), jnp.int32),
        threshold=jnp.zeros((L,), jnp.int32),
        default_left=jnp.zeros((L,), jnp.bool_),
        left_sum_grad=zero_l, left_sum_hess=zero_l, left_count=zero_l,
        left_output=zero_l, right_output=zero_l,
        cat_mask=jnp.zeros((L, max_bins), jnp.bool_),
        min_bound=jnp.full((L,), -jnp.inf, f32),
        max_bound=jnp.full((L,), jnp.inf, f32),
    )
    leaves = _store_split(leaves, 0, root_split, jnp.int32(1), root_out,
                          root_g, root_h, root_c, neg_inf, pos_inf, True)

    pool = jnp.zeros((L, fp, max_bins, hist_ops.NUM_HIST_CHANNELS), f32)
    pool = pool.at[0].set(root_hist)
    row_leaf0 = jnp.zeros((bins_fm.shape[1],), jnp.int32)
    box_lo0 = (jnp.zeros((L, num_features), jnp.int32)
               if mono_pairwise else None)
    box_hi0 = (jnp.full((L, num_features), max_bins - 1, jnp.int32)
               if mono_pairwise else None)

    def step(carry, step_idx):
        row_leaf, pool, leaves, box_lo, box_hi = carry
        best_leaf = jnp.argmax(leaves.gain).astype(jnp.int32)
        valid = leaves.gain[best_leaf] > 0.0
        new_leaf = (step_idx + 1).astype(jnp.int32)

        feat = leaves.feature[best_leaf]  # GLOBAL feature index
        thr = leaves.threshold[best_leaf]
        dleft = leaves.default_left[best_leaf]
        cmask = leaves.cat_mask[best_leaf]

        # full data on every shard: apply the split locally, no row sync
        # (ref: feature-parallel "no row sync" property)
        row_leaf = part_ops.apply_split(
            row_leaf, bins_fm, best_leaf, new_leaf, feat, thr, dleft, cmask,
            meta.num_bins, meta.missing_type, meta.is_categorical, valid)

        lg = leaves.left_sum_grad[best_leaf]
        lh = leaves.left_sum_hess[best_leaf]
        lc = leaves.left_count[best_leaf]
        pg, ph, pc = (leaves.sum_grad[best_leaf],
                      leaves.sum_hess[best_leaf], leaves.count[best_leaf])
        rg, rh, rc = pg - lg, ph - lh, pc - lc

        left_smaller = lc <= rc
        small_id = jnp.where(left_smaller, best_leaf, new_leaf)
        small_mask = sample_mask * (row_leaf == small_id) * valid
        small_hist = build(bins_loc, grad, hess, small_mask)
        parent_hist = pool[best_leaf]
        large_hist = hist_ops.subtract_histogram(parent_hist, small_hist)
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        pool = pool.at[best_leaf].set(
            jnp.where(valid, left_hist, parent_hist))
        pool = pool.at[new_leaf].set(
            jnp.where(valid, right_hist, pool[new_leaf]))

        parent_out = leaves.output[best_leaf]
        p_minb = leaves.min_bound[best_leaf]
        p_maxb = leaves.max_bound[best_leaf]
        out_l = leaves.left_output[best_leaf]
        out_r = leaves.right_output[best_leaf]

        if mono_pairwise:
            # see voting.py: re-clip stored candidate outputs to the
            # CURRENT bounds, then refresh pairwise leaf-box bounds
            out_l = jnp.clip(out_l, p_minb, p_maxb)
            out_r = jnp.clip(out_r, p_minb, p_maxb)
            box_lo, box_hi = split_ops.split_child_boxes(
                box_lo, box_hi, best_leaf, new_leaf, feat, thr,
                meta.is_categorical[feat], valid)
            out_now = leaves.output.at[best_leaf].set(
                jnp.where(valid, out_l, parent_out))
            out_now = out_now.at[new_leaf].set(
                jnp.where(valid, out_r,
                          out_now[jnp.minimum(new_leaf, L - 1)]))
            leaf_in_use = jnp.arange(L, dtype=jnp.int32) <= \
                jnp.where(valid, new_leaf, step_idx)
            minb_all, maxb_all = split_ops.compute_box_bounds(
                box_lo, box_hi, out_now, leaf_in_use, meta.monotone)
            leaves = leaves._replace(
                min_bound=jnp.where(valid, minb_all, leaves.min_bound),
                max_bound=jnp.where(valid, maxb_all, leaves.max_bound))
            l_min, l_max = minb_all[best_leaf], maxb_all[best_leaf]
            r_min, r_max = minb_all[new_leaf], maxb_all[new_leaf]
        else:
            l_min, l_max, r_min, r_max = propagate_monotone_bounds(
                out_l, out_r, meta.monotone[feat].astype(jnp.int32),
                meta.is_categorical[feat], p_minb, p_maxb)

        child_depth = leaves.depth[best_leaf] + 1
        pen_depth = child_depth - 1
        split_l = sync(find_best_split(left_hist, lg, lh, lc, meta_loc,
                                       hp, fmask_loc, out_l, l_min, l_max,
                                       pen_depth, has_categorical),
                       loop_factor=L - 1)
        split_r = sync(find_best_split(right_hist, rg, rh, rc, meta_loc,
                                       hp, fmask_loc, out_r, r_min, r_max,
                                       pen_depth, has_categorical),
                       loop_factor=L - 1)
        depth_ok = (max_depth <= 0) | (child_depth < max_depth)
        split_l = split_l._replace(
            gain=jnp.where(depth_ok, split_l.gain, K_MIN_SCORE))
        split_r = split_r._replace(
            gain=jnp.where(depth_ok, split_r.gain, K_MIN_SCORE))

        chosen_gain = leaves.gain[best_leaf]
        leaves = _store_split(leaves, best_leaf, split_l, child_depth,
                              out_l, lg, lh, lc, l_min, l_max, valid)
        leaves = _store_split(leaves, new_leaf, split_r, child_depth,
                              out_r, rg, rh, rc, r_min, r_max, valid)

        record = dict(
            split_leaf=jnp.where(valid, best_leaf, -1),
            split_feature=feat,
            split_bin_threshold=thr,
            split_default_left=dleft,
            split_gain=jnp.where(valid, chosen_gain, 0.0),
            split_cat_mask=cmask,
            internal_value=parent_out,
            internal_weight=ph,
            internal_count=pc,
        )
        return (row_leaf, pool, leaves, box_lo, box_hi), record

    (row_leaf, pool, leaves, _, _), records = lax.scan(
        step, (row_leaf0, pool, leaves, box_lo0, box_hi0),
        jnp.arange(L - 1, dtype=jnp.int32), unroll=2 if L > 2 else 1)

    num_leaves_out = 1 + jnp.sum(records["split_leaf"] >= 0).astype(
        jnp.int32)
    tree = TreeArrays(
        split_leaf=records["split_leaf"],
        split_feature=records["split_feature"],
        split_bin_threshold=records["split_bin_threshold"],
        split_default_left=records["split_default_left"],
        split_gain=records["split_gain"],
        split_cat_mask=records["split_cat_mask"],
        internal_value=records["internal_value"],
        internal_weight=records["internal_weight"],
        internal_count=records["internal_count"],
        leaf_value=leaves.output,
        leaf_weight=leaves.sum_hess,
        leaf_count=leaves.count,
        num_leaves=num_leaves_out,
    )
    return tree, row_leaf


def make_sharded_feature_grow(mesh, *, num_leaves: int, max_bins: int,
                              hist_impl: str = "xla",
                              has_categorical: bool = True,
                              mono_pairwise: bool = False,
                              hist_deterministic: bool = False):
    """jit(shard_map(grow_tree_feature_parallel)): everything replicated
    in and out; sharding is purely over the computation."""
    grow = functools.partial(grow_tree_feature_parallel,
                             num_leaves=num_leaves, max_bins=max_bins,
                             num_shards=mesh.size, hist_impl=hist_impl,
                             has_categorical=has_categorical,
                             mono_pairwise=mono_pairwise,
                             hist_deterministic=hist_deterministic)
    rep = P()
    meta_spec = FeatureMeta(*([rep] * len(FeatureMeta._fields)))
    hp_spec = SplitHyperParams(*([rep] * len(SplitHyperParams._fields)))
    tree_spec = TreeArrays(*([rep] * len(TreeArrays._fields)))
    from .mesh import shard_map as _shard_map
    sharded = _shard_map(
        grow, mesh=mesh,
        in_specs=(rep, rep, rep, rep, rep, meta_spec, hp_spec, rep),
        out_specs=(tree_spec, rep))
    # instrumented boundary: health manifests attribute the per-split
    # SplitInfo all_gathers per runtime call (see parallel/voting.py)
    return obs_xla.instrumented_jit("parallel/feature_grow", sharded,
                                    phase="grow")
