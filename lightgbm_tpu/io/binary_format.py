"""Binned-dataset binary serialization (fast reload path).

(ref: Dataset::SaveBinaryFile / SerializeReference dataset.h:710,715 and
the loader fast path LoadFromBinFile dataset_loader.cpp:425.) The on-disk
container is a single .npz archive: numeric arrays verbatim plus one JSON
header for mapper/meta structure — a TPU-first choice (the bin matrix is
exactly what ships to the device, so reload is one mmap + one transfer)
rather than the reference's custom byte layout.
"""

from __future__ import annotations

import io
import json
from typing import List, Optional

import numpy as np

_MAGIC = "lightgbm_tpu.dataset.v1"


def _mapper_state(m) -> dict:
    return {
        "num_bins": int(m.num_bins),
        "is_categorical": bool(m.is_categorical),
        "missing_type": int(m.missing_type),
        "default_bin": int(m.default_bin),
        "most_freq_bin": int(m.most_freq_bin),
        "min_value": float(m.min_value),
        "max_value": float(m.max_value),
        "is_trivial": bool(m.is_trivial),
        "bin_upper_bound": None if m.bin_upper_bound is None
        else [float(v) for v in m.bin_upper_bound],
        "cat_bin_to_value": None if m.cat_bin_to_value is None
        else [int(v) for v in m.cat_bin_to_value],
    }


def _mapper_from_state(state: dict):
    from ..binning import BinMapper
    m = BinMapper()
    m.num_bins = state["num_bins"]
    m.is_categorical = state["is_categorical"]
    m.missing_type = state["missing_type"]
    m.default_bin = state["default_bin"]
    m.most_freq_bin = state["most_freq_bin"]
    m.min_value = state["min_value"]
    m.max_value = state["max_value"]
    m.is_trivial = state["is_trivial"]
    if state["bin_upper_bound"] is not None:
        m.bin_upper_bound = np.asarray(state["bin_upper_bound"], np.float64)
    if state["cat_bin_to_value"] is not None:
        vals = np.asarray(state["cat_bin_to_value"], np.float64)
        m.cat_bin_to_value = vals
        m.cat_value_to_bin = {int(v): i + 1 for i, v in enumerate(vals)}
        order = np.argsort(vals)
        m._cat_sorted_vals = vals[order]
        m._cat_sorted_bins = (order + 1).astype(np.int32)
    return m


def save_dataset_binary(dataset, filename) -> None:
    """dataset: basic.Dataset (constructed)."""
    binned = dataset._binned
    meta = binned.metadata
    header = {
        "magic": _MAGIC,
        "num_total_features": binned.num_total_features,
        "used_features": [int(c) for c in binned.used_features],
        "feature_names": list(binned.feature_names),
        "label_idx": int(binned.label_idx),
        "mappers": [_mapper_state(m) for m in binned.mappers],
    }
    if binned.bundle_info is not None:
        header["bundles"] = [list(b) for b in binned.bundle_info.bundles]
    arrays = {"bins_fm": binned.bins_fm,
              "header": np.frombuffer(
                  json.dumps(header).encode(), dtype=np.uint8)}
    if binned.sparse_coo is not None:
        # COO sparse storage: bins_fm is only a [1, N] placeholder, the
        # real payload is the (rows, feats, bins, zero_bins) triples
        rows, feats, bins, zb = binned.sparse_coo
        arrays["sparse_rows"] = rows
        arrays["sparse_feats"] = feats
        arrays["sparse_bins"] = bins
        arrays["sparse_zero_bins"] = zb
    for name in ("label", "weight", "init_score", "query_boundaries",
                 "positions"):
        value = getattr(meta, name)
        if value is not None:
            arrays["meta_" + name] = value
    with open(filename, "wb") as fh:
        np.savez_compressed(fh, **arrays)


def load_dataset_binary(filename):
    """Returns a constructed basic.Dataset backed by the stored bins
    (raw data unavailable — prediction on raw values needs the original
    file, same as the reference's binary datasets)."""
    from ..basic import Dataset
    from ..dataset import BinnedDataset, Metadata

    with np.load(filename, allow_pickle=False) as z:
        header = json.loads(bytes(z["header"].tobytes()).decode())
        if header.get("magic") != _MAGIC:
            raise ValueError(f"{filename}: not a lightgbm_tpu binary dataset")
        bins_fm = z["bins_fm"]
        meta = Metadata(bins_fm.shape[1])
        if "meta_label" in z:
            meta.set_label(z["meta_label"])
        else:
            meta.set_label(np.zeros(bins_fm.shape[1]))
        if "meta_weight" in z:
            meta.set_weight(z["meta_weight"])
        if "meta_init_score" in z:
            meta.set_init_score(z["meta_init_score"])
        if "meta_query_boundaries" in z:
            meta.query_boundaries = np.asarray(z["meta_query_boundaries"],
                                               np.int32)
        if "meta_positions" in z:
            meta.positions = np.asarray(z["meta_positions"], np.int32)
        sparse_arrays = {k: np.asarray(z[k], np.int32)
                         for k in ("sparse_rows", "sparse_feats",
                                   "sparse_bins", "sparse_zero_bins")
                         if k in z}

    mappers = [_mapper_from_state(s) for s in header["mappers"]]
    binned = BinnedDataset(
        bins_fm, mappers, header["used_features"],
        header["num_total_features"], meta,
        feature_names=header["feature_names"],
        label_idx=header["label_idx"])
    if "bundles" in header:
        # rebuild the BundleInfo mapping (storage is already bundled)
        from ..bundling import BundleInfo
        binned.bundle_info = BundleInfo.from_bundles(
            header["bundles"], [m.num_bins for m in mappers])
    if sparse_arrays:
        binned.sparse_coo = (
            sparse_arrays["sparse_rows"], sparse_arrays["sparse_feats"],
            sparse_arrays["sparse_bins"],
            sparse_arrays["sparse_zero_bins"])

    return make_dataset_shell(binned, {})


def make_dataset_shell(binned, params: dict):
    """A basic.Dataset wrapper around an already-constructed
    BinnedDataset (no raw data) — shared by the binary loader and the
    C-ABI serialized-reference path so the shell attribute set has a
    single source."""
    from ..basic import Dataset
    meta = binned.metadata
    ds = Dataset.__new__(Dataset)
    ds.data = None
    ds.label = meta.label
    ds.weight = meta.weight
    ds.group = None
    ds.init_score = meta.init_score
    ds.position = meta.positions
    ds.reference = None
    ds.feature_name = list(binned.feature_names)
    ds.categorical_feature = "auto"
    ds.params = dict(params)
    ds.free_raw_data = True
    ds._binned = binned
    ds.used_indices = None
    return ds
