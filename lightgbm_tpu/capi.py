"""In-process backend of the C-ABI shim.

`native/src/lgbm_tpu_capi.cpp` embeds a CPython interpreter, imports this
module, and forwards every `LGBM_*` call here with raw pointers passed as
integers. This module wraps those pointers with ctypes/NumPy, drives the
ordinary Python API (`basic.Dataset`/`basic.Booster`), and returns
primitive values the C side can marshal back — giving reference harnesses
and third-party tooling the familiar `lib_lightgbm` calling convention
(ref: include/LightGBM/c_api.h; internal Booster wrapper c_api.cpp:170).

Handles are small integers into a registry (the C side casts them to the
opaque `DatasetHandle`/`BoosterHandle` pointers the reference API uses).
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict

import numpy as np

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    # honor an explicit CPU pin even under the axon sitecustomize, whose
    # PJRT plugin overrides JAX_PLATFORMS (see hostenv.cpu_child_env)
    import jax
    jax.config.update("jax_platforms", "cpu")

from .basic import Booster, Dataset
from .config import Config

# C_API_DTYPE_* (ref: c_api.h:36-39)
_DTYPES = {0: ctypes.c_float, 1: ctypes.c_double,
           2: ctypes.c_int32, 3: ctypes.c_int64}
_NP_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}

# C_API_PREDICT_* (ref: c_api.h:41-44)
_PREDICT_NORMAL, _PREDICT_RAW, _PREDICT_LEAF, _PREDICT_CONTRIB = range(4)

_registry: Dict[int, object] = {}
_next_handle = [1]


def _new_handle(obj) -> int:
    h = _next_handle[0]
    _next_handle[0] += 1
    _registry[h] = obj
    return h


def _get(handle: int):
    try:
        return _registry[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}")


def _array_from_ptr(ptr: int, count: int, dtype: int) -> np.ndarray:
    if count == 0:
        return np.empty(0, _NP_DTYPES[dtype])
    ct = _DTYPES[dtype]
    buf = (ct * count).from_address(ptr)
    return np.asarray(np.ctypeslib.as_array(buf), _NP_DTYPES[dtype]).copy()


def _write_doubles(ptr: int, values: np.ndarray) -> int:
    values = np.ascontiguousarray(values, np.float64)
    ctypes.memmove(ptr, values.ctypes.data, values.nbytes)
    return int(values.size)


def _parse_params(parameters: str) -> Dict[str, str]:
    return Config.kv2map((parameters or "").split())


# -- dataset ---------------------------------------------------------------
def dataset_create_from_mat(data_ptr: int, data_type: int, nrow: int,
                            ncol: int, is_row_major: int, parameters: str,
                            reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromMat c_api.cpp:1311)"""
    flat = _array_from_ptr(data_ptr, nrow * ncol, data_type)
    mat = (flat.reshape(nrow, ncol) if is_row_major
           else flat.reshape(ncol, nrow).T)
    ref = _resolve_ds(_get(reference)) if reference else None
    ds = Dataset(np.asarray(mat, np.float64), reference=ref,
                 params=_parse_params(parameters))
    return _new_handle(ds)


def _csr_from_ptrs(indptr_ptr: int, indptr_type: int, indices_ptr: int,
                   data_ptr: int, data_type: int, nindptr: int,
                   nelem: int, num_col: int):
    from scipy import sparse
    indptr = _array_from_ptr(indptr_ptr, nindptr, indptr_type)
    indices = _array_from_ptr(indices_ptr, nelem, 2)  # int32
    data = _array_from_ptr(data_ptr, nelem, data_type)
    return sparse.csr_matrix(
        (np.asarray(data, np.float64), indices, indptr),
        shape=(nindptr - 1, num_col))


def dataset_create_from_csr(indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int,
                            data_type: int, nindptr: int, nelem: int,
                            num_col: int, parameters: str,
                            reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromCSR c_api.cpp:1311) — feeds the
    densification-free sparse ingestion path."""
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    ref = _resolve_ds(_get(reference)) if reference else None
    ds = Dataset(csr, reference=ref, params=_parse_params(parameters))
    return _new_handle(ds)


def _predict_into(bst, matrix, predict_type: int, start_iteration: int,
                  num_iteration: int, out_ptr: int) -> int:
    """Shared predict dispatch + result write for the dense and CSR
    entry points."""
    pred = bst.predict(matrix, start_iteration=start_iteration,
                       num_iteration=num_iteration,
                       raw_score=predict_type == _PREDICT_RAW,
                       pred_leaf=predict_type == _PREDICT_LEAF,
                       pred_contrib=predict_type == _PREDICT_CONTRIB)
    return _write_doubles(out_ptr, np.asarray(pred).reshape(-1))


def booster_predict_for_csr(handle: int, indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int,
                            data_type: int, nindptr: int, nelem: int,
                            num_col: int, predict_type: int,
                            start_iteration: int, num_iteration: int,
                            out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForCSR c_api.cpp)"""
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    return _predict_into(_get(handle), csr, predict_type, start_iteration,
                         num_iteration, out_ptr)


def dataset_create_from_file(filename: str, parameters: str,
                             reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromFile c_api.cpp:1044)"""
    ref = _resolve_ds(_get(reference)) if reference else None
    ds = Dataset(filename, reference=ref, params=_parse_params(parameters))
    return _new_handle(ds)


def dataset_set_field(handle: int, field: str, ptr: int, count: int,
                      dtype: int) -> None:
    """(ref: LGBM_DatasetSetField c_api.cpp)"""
    ds = _resolve_ds(_get(handle))
    values = _array_from_ptr(ptr, count, dtype)
    if field == "label":
        ds.set_label(values)
    elif field == "weight":
        ds.set_weight(values)
    elif field in ("group", "query"):
        ds.set_group(values)
    elif field == "init_score":
        ds.set_init_score(values)
    else:
        raise ValueError(f"unknown field {field}")


def dataset_num_data(handle: int) -> int:
    obj = _get(handle)
    if isinstance(obj, _StreamingDataset):
        return obj.num_total_row
    return int(obj.num_data())


def dataset_num_feature(handle: int) -> int:
    obj = _get(handle)
    if isinstance(obj, _StreamingDataset):
        return obj.ncol
    return int(obj.num_feature())


def handle_free(handle: int) -> None:
    _registry.pop(handle, None)
    _eval_counts.pop(handle, None)
    _field_cache.pop(handle, None)


# -- booster ---------------------------------------------------------------
def booster_create(train_handle: int, parameters: str) -> int:
    """(ref: LGBM_BoosterCreate c_api.cpp:1998)"""
    bst = Booster(_parse_params(parameters), _resolve_ds(_get(train_handle)))
    return _new_handle(bst)


def booster_create_from_modelfile(filename: str) -> tuple:
    """(ref: LGBM_BoosterCreateFromModelfile)"""
    bst = Booster(model_file=filename)
    return _new_handle(bst), int(bst.num_trees())


def booster_add_valid_data(handle: int, valid_handle: int) -> None:
    bst = _get(handle)
    bst.add_valid(_resolve_ds(_get(valid_handle)),
                  f"valid_{len(bst._name_valid_sets)}")


def booster_update_one_iter(handle: int) -> int:
    """Returns 1 when training is finished
    (ref: LGBM_BoosterUpdateOneIter c_api.cpp:2121)."""
    return int(bool(_get(handle).update()))


def booster_current_iteration(handle: int) -> int:
    return int(_get(handle).current_iteration())


_eval_counts: Dict[int, int] = {}


def booster_get_eval_counts(handle: int) -> int:
    # the metric set is fixed after Booster creation; cache so harnesses
    # polling the count each iteration don't pay a full evaluation
    if handle not in _eval_counts:
        _eval_counts[handle] = len(_get(handle).eval_train())
    return _eval_counts[handle]


def booster_get_eval(handle: int, data_idx: int, out_ptr: int) -> int:
    """data_idx 0 = train, 1.. = valid sets (ref: LGBM_BoosterGetEval)."""
    bst = _get(handle)
    if data_idx == 0:
        results = bst.eval_train()
    else:
        name = bst._name_valid_sets[data_idx - 1]
        results = [r for r in bst.eval_valid() if r[0] == name]
    return _write_doubles(out_ptr, np.asarray([r[2] for r in results]))


def booster_predict_for_mat(handle: int, data_ptr: int, data_type: int,
                            nrow: int, ncol: int, is_row_major: int,
                            predict_type: int, start_iteration: int,
                            num_iteration: int, out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForMat c_api.cpp:2558)"""
    flat = _array_from_ptr(data_ptr, nrow * ncol, data_type)
    mat = (flat.reshape(nrow, ncol) if is_row_major
           else flat.reshape(ncol, nrow).T)
    return _predict_into(_get(handle), np.asarray(mat, np.float64),
                         predict_type, start_iteration, num_iteration,
                         out_ptr)


def booster_save_model(handle: int, start_iteration: int,
                       num_iteration: int, importance_type: int,
                       filename: str) -> None:
    """(ref: LGBM_BoosterSaveModel)"""
    _get(handle).save_model(
        filename, num_iteration=num_iteration,
        start_iteration=start_iteration,
        importance_type="gain" if importance_type == 1 else "split")


def booster_save_model_to_string(handle: int, start_iteration: int,
                                 num_iteration: int,
                                 importance_type: int) -> str:
    return _get(handle).model_to_string(
        num_iteration=num_iteration, start_iteration=start_iteration,
        importance_type="gain" if importance_type == 1 else "split")


def booster_num_feature(handle: int) -> int:
    return int(_get(handle).num_feature())


# -- streaming dataset construction ----------------------------------------
# (ref: c_api.cpp:1330 LGBM_DatasetPushRows* + chunked_array.hpp; scenario
# coverage modeled on tests/cpp_tests/test_stream.cpp:253,304)
class _StreamingDataset:
    """A fixed-size dataset being filled by PushRows calls. Auto-finishes
    when pushed rows reach num_total_row (unless wait_manual), after which
    `built` holds the constructed Dataset."""

    def __init__(self, num_total_row: int, ncol: int, params, reference):
        self.num_total_row = int(num_total_row)
        self.ncol = int(ncol)
        self.params = params
        self.reference = reference
        self.X = np.zeros((self.num_total_row, self.ncol), np.float64)
        self.label = np.zeros(self.num_total_row, np.float32)
        self.weight = None
        self.init_score = None
        self.query = None
        self.nclasses = 1
        self.pushed = 0
        self.wait_manual = False
        self.built = None

    def init_streaming(self, has_weights, has_init_scores, has_queries,
                       nclasses):
        if has_weights:
            self.weight = np.zeros(self.num_total_row, np.float32)
        if has_init_scores:
            self.nclasses = max(int(nclasses), 1)
            self.init_score = np.zeros(
                self.num_total_row * self.nclasses, np.float64)
        if has_queries:
            self.query = np.zeros(self.num_total_row, np.int32)
        # InitStreaming implies the manual-finish contract
        # (ref: test_stream.cpp streaming flow step 4: MarkFinished)
        self.wait_manual = True

    def push(self, rows: np.ndarray, start_row: int, label=None,
             weight=None, init_score=None, query=None):
        if self.built is not None:
            raise ValueError("dataset already finished")
        n = rows.shape[0]
        if start_row + n > self.num_total_row:
            raise ValueError(
                f"push of {n} rows at {start_row} exceeds num_total_row="
                f"{self.num_total_row}")
        self.X[start_row:start_row + n] = rows
        if label is not None:
            self.label[start_row:start_row + n] = label
        if weight is not None and self.weight is not None:
            self.weight[start_row:start_row + n] = weight
        if init_score is not None and self.init_score is not None:
            # column-format [nclasses x nrow] slices (ref: c_api.h:259)
            for c in range(self.nclasses):
                dst = c * self.num_total_row + start_row
                self.init_score[dst:dst + n] = init_score[c * n:(c + 1) * n]
        if query is not None and self.query is not None:
            self.query[start_row:start_row + n] = query
        self.pushed += n
        if not self.wait_manual and self.pushed >= self.num_total_row:
            self.finish()

    def finish(self):
        if self.built is not None:
            return self.built
        group = None
        if self.query is not None:
            # per-row query ids -> group sizes (run-length; the reference
            # metadata does the same boundary conversion)
            _, counts = np.unique(self.query, return_counts=True)
            # np.unique sorts; queries arrive contiguous, so preserve
            # first-appearance order via index of first occurrence
            _, first = np.unique(self.query, return_index=True)
            order = np.argsort(first)
            group = counts[order]
        # init_score stays in class-major (column) format: both the C API
        # contract (c_api.h:259) and GBDT's consumer
        # (boosting.py init.reshape(K, N)) use [class * num_row + row]
        init_score = self.init_score
        ds = Dataset(self.X, label=self.label, weight=self.weight,
                     init_score=init_score, group=group,
                     reference=self.reference, params=dict(self.params))
        self.built = ds.construct()
        return self.built


def _resolve_ds(obj):
    if isinstance(obj, _StreamingDataset):
        if obj.built is None:
            raise ValueError("streaming dataset is not finished yet "
                             "(push all rows or call MarkFinished)")
        return obj.built
    return obj


def dataset_create_by_reference(ref_handle: int, num_total_row: int) -> int:
    """(ref: LGBM_DatasetCreateByReference c_api.cpp:1245)"""
    ref = _get(ref_handle)
    ref.construct()
    sd = _StreamingDataset(num_total_row, ref.num_feature(),
                           dict(ref.params or {}), ref)
    return _new_handle(sd)


def dataset_create_from_sampled_column(sample_data_ptr: int,
                                       sample_indices_ptr: int, ncol: int,
                                       num_per_col_ptr: int,
                                       num_sample_row: int,
                                       num_local_row: int,
                                       parameters: str) -> int:
    """Build the dataset 'schema' (bin mappers) from per-column sampled
    values, sized for num_local_row pushed rows
    (ref: LGBM_DatasetCreateFromSampledColumn c_api.cpp:1112; the
    streaming flow of test_stream.cpp:253 step 1)."""
    num_per_col = _array_from_ptr(num_per_col_ptr, ncol, 2)
    dptrs = _array_from_ptr(sample_data_ptr, ncol, 3)   # double* per col
    iptrs = _array_from_ptr(sample_indices_ptr, ncol, 3)  # int* per col
    S = np.zeros((num_sample_row, ncol), np.float64)
    for j in range(ncol):
        cnt = int(num_per_col[j])
        if cnt == 0:
            continue
        vals = _array_from_ptr(int(dptrs[j]), cnt, 1)
        rows = _array_from_ptr(int(iptrs[j]), cnt, 2)
        S[rows, j] = vals
    params = _parse_params(parameters)
    schema = Dataset(S, params=dict(params)).construct()
    sd = _StreamingDataset(num_local_row, ncol, params, schema)
    return _new_handle(sd)


def dataset_init_streaming(handle: int, has_weights: int,
                           has_init_scores: int, has_queries: int,
                           nclasses: int) -> None:
    sd = _get(handle)
    if not isinstance(sd, _StreamingDataset):
        raise ValueError("InitStreaming requires a streaming dataset "
                         "(CreateByReference/CreateFromSampledColumn)")
    sd.init_streaming(has_weights, has_init_scores, has_queries, nclasses)


def dataset_push_rows(handle: int, data_ptr: int, data_type: int,
                      nrow: int, ncol: int, start_row: int) -> None:
    """(ref: LGBM_DatasetPushRows c_api.cpp:1330)"""
    sd = _get(handle)
    flat = _array_from_ptr(data_ptr, nrow * ncol, data_type)
    sd.push(flat.reshape(nrow, ncol), start_row)


def dataset_push_rows_with_metadata(handle: int, data_ptr: int,
                                    data_type: int, nrow: int, ncol: int,
                                    start_row: int, label_ptr: int,
                                    weight_ptr: int, init_score_ptr: int,
                                    query_ptr: int) -> None:
    sd = _get(handle)
    flat = _array_from_ptr(data_ptr, nrow * ncol, data_type)
    label = _array_from_ptr(label_ptr, nrow, 0) if label_ptr else None
    weight = _array_from_ptr(weight_ptr, nrow, 0) if weight_ptr else None
    init_score = (_array_from_ptr(init_score_ptr, nrow * sd.nclasses, 1)
                  if init_score_ptr else None)
    query = _array_from_ptr(query_ptr, nrow, 2) if query_ptr else None
    sd.push(flat.reshape(nrow, ncol), start_row, label, weight,
            init_score, query)


def dataset_push_rows_by_csr(handle: int, indptr_ptr: int, indptr_type: int,
                             indices_ptr: int, data_ptr: int,
                             data_type: int, nindptr: int, nelem: int,
                             num_col: int, start_row: int) -> None:
    """(ref: LGBM_DatasetPushRowsByCSR c_api.cpp:1383)"""
    sd = _get(handle)
    ncol = int(num_col) if num_col > 0 else sd.ncol
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, ncol)
    sd.push(np.asarray(csr.todense()), start_row)


def dataset_push_rows_by_csr_with_metadata(
        handle: int, indptr_ptr: int, indptr_type: int, indices_ptr: int,
        data_ptr: int, data_type: int, nindptr: int, nelem: int,
        start_row: int, label_ptr: int, weight_ptr: int,
        init_score_ptr: int, query_ptr: int) -> None:
    sd = _get(handle)
    nrow = nindptr - 1
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, sd.ncol)
    label = _array_from_ptr(label_ptr, nrow, 0) if label_ptr else None
    weight = _array_from_ptr(weight_ptr, nrow, 0) if weight_ptr else None
    init_score = (_array_from_ptr(init_score_ptr, nrow * sd.nclasses, 1)
                  if init_score_ptr else None)
    query = _array_from_ptr(query_ptr, nrow, 2) if query_ptr else None
    sd.push(np.asarray(csr.todense()), start_row, label, weight,
            init_score, query)


def dataset_set_wait_for_manual_finish(handle: int, wait: int) -> None:
    sd = _get(handle)
    if isinstance(sd, _StreamingDataset):
        sd.wait_manual = bool(wait)


def dataset_mark_finished(handle: int) -> None:
    """(ref: LGBM_DatasetMarkFinished -> Dataset::FinishLoad)"""
    sd = _get(handle)
    if isinstance(sd, _StreamingDataset):
        sd.finish()


def get_sample_count(num_total_row: int, parameters: str) -> int:
    """(ref: LGBM_GetSampleCount c_api.cpp)"""
    params = _parse_params(parameters)
    cnt = int(params.get("bin_construct_sample_cnt", 200000))
    return min(max(cnt, 1), int(num_total_row))


def sample_indices(num_total_row: int, parameters: str, out_ptr: int) -> int:
    """Sorted uniform sample without replacement, seeded by
    data_random_seed (ref: LGBM_SampleIndices -> CreateSampleIndices)."""
    params = _parse_params(parameters)
    cnt = get_sample_count(num_total_row, parameters)
    seed = int(params.get("data_random_seed", 1))
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    idx = np.sort(rng.choice(num_total_row, size=cnt,
                             replace=False).astype(np.int32))
    ctypes.memmove(out_ptr, idx.ctypes.data, idx.nbytes)
    return int(idx.size)


# -- dataset field access / utilities --------------------------------------
# GetField returns a pointer into a buffer we must keep alive for the
# handle's lifetime (the reference returns pointers into Metadata's own
# vectors, c_api.cpp LGBM_DatasetGetField)
_field_cache: Dict[int, Dict[str, np.ndarray]] = {}


def dataset_get_field(handle: int, field: str) -> tuple:
    """Returns (ptr, len, dtype_code) (ref: LGBM_DatasetGetField)."""
    ds = _resolve_ds(_get(handle))
    if field == "label":
        arr, code = np.ascontiguousarray(ds.get_label(), np.float32), 0
    elif field == "weight":
        w = ds.get_weight()
        if w is None:
            return 0, 0, 0
        arr, code = np.ascontiguousarray(w, np.float32), 0
    elif field in ("group", "query"):
        g = ds.get_group()
        if g is None:
            return 0, 0, 2
        # boundaries, not sizes (ref: Metadata::query_boundaries_)
        arr = np.concatenate([[0], np.cumsum(np.asarray(g))]).astype(
            np.int32)
        code = 2
    elif field == "init_score":
        s = ds.get_init_score()
        if s is None:
            return 0, 0, 1
        arr, code = np.ascontiguousarray(s, np.float64).reshape(-1), 1
    else:
        raise ValueError(f"unknown field {field}")
    _field_cache.setdefault(handle, {})[field] = arr
    return int(arr.ctypes.data), int(arr.size), code


def dataset_get_feature_names(handle: int) -> list:
    return list(_resolve_ds(_get(handle)).get_feature_name())


def dataset_set_feature_names(handle: int, names: list) -> None:
    ds = _resolve_ds(_get(handle))
    ds.feature_name = [str(n) for n in names]


def dataset_get_feature_num_bin(handle: int, feature: int) -> int:
    """(ref: LGBM_DatasetGetFeatureNumBin -> FeatureNumBin)"""
    ds = _resolve_ds(_get(handle)).construct()
    binned = ds._binned
    for j, raw in enumerate(binned.used_features):
        if raw == feature:
            return int(binned.mappers[j].num_bins)
    return 1  # trivial (unused) feature: single bin


def dataset_save_binary(handle: int, filename: str) -> None:
    _resolve_ds(_get(handle)).construct().save_binary(filename)


def dataset_dump_text(handle: int, filename: str) -> None:
    """(ref: LGBM_DatasetDumpText c_api.cpp)"""
    ds = _resolve_ds(_get(handle)).construct()
    X = np.asarray(ds.get_data(), np.float64)
    lab = ds.get_label()
    with open(filename, "w") as fh:
        names = ds.get_feature_name()
        fh.write("\t".join(["label"] + list(names)) + "\n")
        for i in range(X.shape[0]):
            row = [repr(float(lab[i]))] if lab is not None else []
            row += [repr(float(v)) for v in X[i]]
            fh.write("\t".join(row) + "\n")


def dataset_get_subset(handle: int, indices_ptr: int, num_indices: int,
                       parameters: str) -> int:
    """(ref: LGBM_DatasetGetSubset c_api.cpp)"""
    ds = _resolve_ds(_get(handle))
    idx = _array_from_ptr(indices_ptr, num_indices, 2)
    sub = ds.subset(idx, params=_parse_params(parameters))
    return _new_handle(sub)


def dataset_update_param_checking(old_parameters: str,
                                  new_parameters: str) -> None:
    """(ref: LGBM_DatasetUpdateParamChecking — raises when a
    dataset-affecting parameter changed)."""
    old = _parse_params(old_parameters)
    new = _parse_params(new_parameters)
    binning_keys = ("max_bin", "min_data_in_bin", "bin_construct_sample_cnt",
                    "categorical_feature", "use_missing", "zero_as_missing",
                    "feature_pre_filter")
    for k in binning_keys:
        if k in new and old.get(k) != new.get(k):
            raise ValueError(
                f"cannot change {k} after constructing Dataset")


# -- booster extras --------------------------------------------------------
def booster_load_model_from_string(model_str: str) -> tuple:
    """(ref: LGBM_BoosterLoadModelFromString)"""
    bst = Booster(model_str=model_str)
    return _new_handle(bst), int(bst.num_trees())


def booster_reset_parameter(handle: int, parameters: str) -> None:
    """(ref: LGBM_BoosterResetParameter c_api.cpp:2095)"""
    _get(handle).reset_parameter(_parse_params(parameters))


def booster_reset_training_data(handle: int, train_handle: int) -> None:
    """(ref: LGBM_BoosterResetTrainingData c_api.cpp:2086): swap the
    training data, keep the model — no extra boosting iteration."""
    _get(handle).reset_train_set(_resolve_ds(_get(train_handle)))


def booster_rollback_one_iter(handle: int) -> None:
    _get(handle).rollback_one_iter()


def booster_get_num_classes(handle: int) -> int:
    bst = _get(handle)
    if bst._gbdt is not None:
        cfg = bst._gbdt.config
        return int(getattr(cfg, "num_class", 1))
    return max(int(bst._loaded.num_tree_per_iteration), 1)


def booster_num_model_per_iteration(handle: int) -> int:
    bst = _get(handle)
    if bst._gbdt is not None:
        return int(bst._gbdt.num_tree_per_iteration)
    return max(int(bst._loaded.num_tree_per_iteration), 1)


def booster_number_of_total_model(handle: int) -> int:
    return _booster_total_models(_get(handle))


def _booster_total_models(bst) -> int:
    if bst._gbdt is not None:
        return sum(len(it) for it in bst._gbdt.models)
    return len(bst._loaded.trees)


def booster_get_eval_names(handle: int) -> list:
    """Metric names WITHOUT evaluating (ref: LGBM_BoosterGetEvalNames —
    the reference lists name strings only)."""
    bst = _get(handle)
    if bst._gbdt is None or bst.train_set is None:
        return []
    metrics = bst._metrics_for(bst.train_set._binned,
                               bst._gbdt.num_data)
    return [m.name for m in metrics]


def booster_get_feature_names(handle: int) -> list:
    return list(_get(handle).feature_name())


def booster_get_linear(handle: int) -> int:
    bst = _get(handle)
    if bst._gbdt is not None:
        return int(bool(bst._gbdt.config.linear_tree))
    return 0


def booster_calc_num_predict(handle: int, num_row: int, predict_type: int,
                             start_iteration: int,
                             num_iteration: int) -> int:
    """(ref: LGBM_BoosterCalcNumPredict c_api.cpp:2585)"""
    bst = _get(handle)
    k = booster_num_model_per_iteration(handle)
    total_iter = _booster_total_models(bst) // max(k, 1)
    start = max(int(start_iteration), 0)
    iters = total_iter - start if num_iteration <= 0 else \
        min(int(num_iteration), total_iter - start)
    if predict_type == _PREDICT_LEAF:
        return int(num_row) * k * max(iters, 0)
    if predict_type == _PREDICT_CONTRIB:
        return int(num_row) * k * (int(booster_num_feature(handle)) + 1)
    return int(num_row) * k


def booster_get_num_predict(handle: int, data_idx: int) -> int:
    bst = _get(handle)
    k = booster_num_model_per_iteration(handle)
    if data_idx == 0:
        n = bst._gbdt.num_data
    else:
        n = bst._valid_sets[data_idx - 1].num_data()
    return int(n) * k


def booster_get_predict(handle: int, data_idx: int, out_ptr: int) -> int:
    """Current (transformed) scores for train (0) or valid set idx
    (ref: LGBM_BoosterGetPredict -> GBDT::GetPredictAt)."""
    bst = _get(handle)
    gbdt = bst._gbdt
    if data_idx == 0:
        raw = np.asarray(gbdt.scores).T       # [N, K]
    else:
        raw = np.asarray(gbdt.valid_raw_scores(data_idx - 1))  # [N, K]
    obj = gbdt.objective
    out = obj.convert_output(raw) if obj is not None else raw
    return _write_doubles(out_ptr, np.asarray(out).reshape(-1))


def booster_predict_for_file(handle: int, data_filename: str,
                             data_has_header: int, predict_type: int,
                             start_iteration: int, num_iteration: int,
                             parameter: str, result_filename: str) -> None:
    """(ref: LGBM_BoosterPredictForFile c_api.cpp:2496 -> Predictor)"""
    from .io.text_loader import load_svmlight_or_csv
    params = _parse_params(parameter)
    params.setdefault("header", str(bool(data_has_header)).lower())
    X, _y, _w, _g = load_svmlight_or_csv(data_filename, params)
    bst = _get(handle)
    canon = {Config.canonical_key(pk): pv for pk, pv in params.items()}
    # per-call serving override; also caps the SHAP row chunks when
    # predict_type is contribution (ops/shap.py)
    chunk = canon.get("tpu_predict_chunk")
    pred = bst.predict(X, start_iteration=start_iteration,
                       num_iteration=num_iteration,
                       raw_score=predict_type == _PREDICT_RAW,
                       pred_leaf=predict_type == _PREDICT_LEAF,
                       pred_contrib=predict_type == _PREDICT_CONTRIB,
                       tpu_predict_chunk=int(chunk) if chunk else None)
    pred = np.asarray(pred)
    if pred.ndim == 1:
        pred = pred[:, None]
    with open(result_filename, "w") as fh:
        for row in pred:
            fh.write("\t".join(repr(float(v)) for v in row) + "\n")


def booster_dump_model(handle: int, start_iteration: int,
                       num_iteration: int) -> str:
    """(ref: LGBM_BoosterDumpModel — JSON text)"""
    import json
    return json.dumps(_get(handle).dump_model(
        num_iteration=num_iteration, start_iteration=start_iteration))


def booster_feature_importance(handle: int, num_iteration: int,
                               importance_type: int, out_ptr: int) -> int:
    """(ref: LGBM_BoosterFeatureImportance c_api.cpp:2933)"""
    imp = _get(handle).feature_importance(
        "gain" if importance_type == 1 else "split",
        iteration=num_iteration if num_iteration > 0 else -1)
    return _write_doubles(out_ptr, np.asarray(imp, np.float64))


def _all_trees(bst):
    if bst._gbdt is not None:
        return [t for it in bst._gbdt.models for t in it]
    return list(bst._loaded.trees)


def booster_get_leaf_value(handle: int, tree_idx: int,
                           leaf_idx: int) -> float:
    trees = _all_trees(_get(handle))
    return float(trees[tree_idx].leaf_value[leaf_idx])


def _invalidate_packed(bst) -> None:
    """Drop the packed device-ensemble cache after structural edits
    (ops/predict.py predict_raw_cached keys on owner._packed_key; the
    incremental EnsemblePackers identify trees by (id, pack_version)
    tokens, which in-place leaf edits don't change — so they must be
    dropped wholesale too)."""
    for owner in (bst._gbdt, getattr(bst, "_loaded", None)):
        if owner is not None and hasattr(owner, "_packed_key"):
            owner._packed_key = None
        if owner is not None and hasattr(owner, "_packers"):
            owner._packers = {}


def booster_set_leaf_value(handle: int, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    """(ref: LGBM_BoosterSetLeafValue -> Tree::SetLeafOutput)"""
    bst = _get(handle)
    trees = _all_trees(bst)
    trees[tree_idx].leaf_value[leaf_idx] = val
    _invalidate_packed(bst)


def booster_get_upper_bound_value(handle: int) -> float:
    """(ref: LGBM_BoosterGetUpperBoundValue -> GBDT::GetUpperBoundValue)"""
    bst = _get(handle)
    total = sum(float(np.max(t.leaf_value[:max(t.num_leaves, 1)]))
                for t in _all_trees(bst))
    return total


def booster_get_lower_bound_value(handle: int) -> float:
    bst = _get(handle)
    return sum(float(np.min(t.leaf_value[:max(t.num_leaves, 1)]))
               for t in _all_trees(bst))


def booster_shuffle_models(handle: int, start_iter: int,
                           end_iter: int) -> None:
    _get(handle).shuffle_models(start_iter, end_iter)


def booster_merge(handle: int, other_handle: int) -> None:
    """(ref: LGBM_BoosterMerge — appends other's models)"""
    bst, other = _get(handle), _get(other_handle)
    if bst._gbdt is None or other._gbdt is None:
        raise ValueError("merge requires trained boosters")
    bst._gbdt.models = bst._gbdt.models + other._gbdt.models
    _invalidate_packed(bst)


def booster_update_one_iter_custom(handle: int, grad_ptr: int,
                                   hess_ptr: int) -> int:
    """(ref: LGBM_BoosterUpdateOneIterCustom c_api.cpp:2140)"""
    bst = _get(handle)
    gbdt = bst._gbdt
    n = gbdt.num_data * gbdt.num_tree_per_iteration
    grad = _array_from_ptr(grad_ptr, n, 0)
    hess = _array_from_ptr(hess_ptr, n, 0)
    return int(bool(bst.update(fobj=lambda _scores, _ds: (grad, hess))))


def booster_refit(handle: int, leaf_preds_ptr: int, nrow: int,
                  ncol: int) -> None:
    """(ref: LGBM_BoosterRefit c_api.cpp:2109 -> GBDT::RefitTree).

    The booster's current train set supplies features/labels (the
    python-package flow resets training data first, then calls this);
    the refitted model replaces the handle's booster in the registry.
    leaf_preds is accepted for signature parity — refit.py re-derives
    leaf assignments from the train features, which is equivalent for
    data that produced those leaf predictions."""
    bst = _get(handle)
    _array_from_ptr(leaf_preds_ptr, nrow * ncol, 2)  # validate readable
    ds = bst.train_set
    if ds is None or ds.data is None:
        raise ValueError("refit requires a booster with raw train data")
    new = bst.refit(np.asarray(ds.get_data(), np.float64),
                    np.asarray(ds.get_label(), np.float32))
    _registry[handle] = new


# -- single-row / fast-path prediction -------------------------------------
class _FastConfig:
    """Pre-bound prediction configuration (ref: FastConfigHandle,
    c_api.cpp FastConfig + LGBM_BoosterPredictForMatSingleRowFastInit
    c_api.cpp:2605-2625). Binding booster + predict params once lets the
    per-call path skip parameter parsing; repeated single-row predicts
    also reuse the jitted packed-ensemble program (shape-stable)."""

    def __init__(self, booster, predict_type, start_iteration,
                 num_iteration, data_type, ncol):
        self.booster = booster
        self.predict_type = int(predict_type)
        self.start_iteration = int(start_iteration)
        self.num_iteration = int(num_iteration)
        self.data_type = int(data_type)
        self.ncol = int(ncol)


def booster_predict_for_mat_single_row(handle: int, data_ptr: int,
                                       data_type: int, ncol: int,
                                       predict_type: int,
                                       start_iteration: int,
                                       num_iteration: int,
                                       out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForMatSingleRow c_api.cpp:2558)"""
    row = _array_from_ptr(data_ptr, ncol, data_type).reshape(1, ncol)
    return _predict_into(_get(handle), np.asarray(row, np.float64),
                         predict_type, start_iteration, num_iteration,
                         out_ptr)


def fast_config_init(handle: int, predict_type: int, start_iteration: int,
                     num_iteration: int, data_type: int, ncol: int) -> int:
    """Shared by the Mat and CSR FastInit entry points."""
    fc = _FastConfig(_get(handle), predict_type, start_iteration,
                     num_iteration, data_type, ncol)
    return _new_handle(fc)


def booster_predict_single_row_fast(fc_handle: int, data_ptr: int,
                                    out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForMatSingleRowFast c_api.cpp:2625)"""
    fc = _get(fc_handle)
    row = _array_from_ptr(data_ptr, fc.ncol, fc.data_type).reshape(
        1, fc.ncol)
    return _predict_into(fc.booster, np.asarray(row, np.float64),
                         fc.predict_type, fc.start_iteration,
                         fc.num_iteration, out_ptr)


def booster_predict_csr_single_row_fast(fc_handle: int, indptr_ptr: int,
                                        indptr_type: int, indices_ptr: int,
                                        data_ptr: int, nindptr: int,
                                        nelem: int, out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForCSRSingleRowFast c_api.cpp:2651)"""
    fc = _get(fc_handle)
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         fc.data_type, nindptr, nelem, fc.ncol)
    return _predict_into(fc.booster, csr, fc.predict_type,
                         fc.start_iteration, fc.num_iteration, out_ptr)


def booster_predict_csr_single_row(handle: int, indptr_ptr: int,
                                   indptr_type: int, indices_ptr: int,
                                   data_ptr: int, data_type: int,
                                   nindptr: int, nelem: int, num_col: int,
                                   predict_type: int, start_iteration: int,
                                   num_iteration: int, out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForCSRSingleRow)"""
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    return _predict_into(_get(handle), csr, predict_type, start_iteration,
                         num_iteration, out_ptr)


def booster_predict_for_mats(handle: int, row_ptrs_ptr: int,
                             data_type: int, nrow: int, ncol: int,
                             predict_type: int, start_iteration: int,
                             num_iteration: int, out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForMats — array of row pointers)"""
    ptrs = _array_from_ptr(row_ptrs_ptr, nrow, 3)  # void* per row
    mat = np.empty((nrow, ncol), np.float64)
    for i in range(nrow):
        mat[i] = _array_from_ptr(int(ptrs[i]), ncol, data_type)
    return _predict_into(_get(handle), mat, predict_type, start_iteration,
                         num_iteration, out_ptr)


# -- global utilities ------------------------------------------------------
_max_threads = [-1]


def set_max_threads(n: int) -> None:
    """(ref: LGBM_SetMaxThreads — bounds the native thread pool; XLA
    device parallelism is unaffected, like the reference's CUDA path).
    Any negative value resets to the 'use default' sentinel -1, exactly
    like the reference (tests/c_api_test/test_.py
    test_max_thread_control pins this contract)."""
    _max_threads[0] = int(n) if n > 0 else -1
    os.environ["LGBM_TPU_NUM_THREADS"] = str(n if n > 0 else 0)


def get_max_threads() -> int:
    return _max_threads[0]


def dump_param_aliases() -> str:
    """(ref: LGBM_DumpParamAliases c_api.cpp — JSON alias map)"""
    import json
    from .config import _ALIAS_TO_CANONICAL
    out: Dict[str, list] = {}
    for alias, canonical in _ALIAS_TO_CANONICAL.items():
        if alias != canonical:
            out.setdefault(canonical, []).append(alias)
    return json.dumps(out, indent=2)


_log_callback = [None]


def register_log_callback(ptr: int) -> None:
    """Route framework logging through a C callback
    (ref: LGBM_RegisterLogCallback c_api.cpp:90)."""
    from . import log as log_mod
    cb = ctypes.CFUNCTYPE(None, ctypes.c_char_p)(ptr)
    _log_callback[0] = cb

    class _CallbackLogger:
        @staticmethod
        def info(msg: str) -> None:
            cb(str(msg).encode("utf-8"))

        warning = info

    log_mod.register_logger(_CallbackLogger())


_network_conf = [None]


def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> None:
    """API-parity seam for LGBM_NetworkInit (c_api.cpp:2845). The socket
    machine list is recorded but collectives ride the jax.distributed /
    ICI mesh (parallel/distributed.py) rather than reference TCP — use
    lightgbm_tpu.cluster / jax.distributed.initialize for real
    multi-host runs."""
    _network_conf[0] = {"machines": machines,
                       "local_listen_port": int(local_listen_port),
                       "listen_time_out": int(listen_time_out),
                       "num_machines": int(num_machines)}


def network_free() -> None:
    _network_conf[0] = None


def booster_validate_feature_names(handle: int, names: list) -> None:
    """(ref: LGBM_BoosterValidateFeatureNames c_api.cpp)"""
    model_names = booster_get_feature_names(handle)
    data_names = [str(n) for n in names]
    if len(model_names) != len(data_names) or any(
            a != b for a, b in zip(model_names, data_names)):
        raise ValueError(
            f"feature names mismatch: model has {model_names}, "
            f"data has {data_names}")


# -- serialized dataset reference + ByteBuffer ------------------------------
# (ref: LGBM_DatasetSerializeReferenceToBinary c_api.cpp +
#  LGBM_DatasetCreateFromSerializedReference — ship the dataset SCHEMA
#  (bin mappers, used features) to another process, which then fills a
#  same-aligned dataset via the streaming push API; ByteBufferHandle is
#  the transport, c_api.h:117)
def dataset_serialize_reference(handle: int) -> int:
    """Returns a ByteBuffer handle whose bytes encode the schema."""
    import json as _json
    from .io.binary_format import _mapper_state
    ds = _resolve_ds(_get(handle)).construct()
    binned = ds._binned
    payload = {
        "num_total_features": binned.num_total_features,
        "used_features": [int(c) for c in binned.used_features],
        "feature_names": list(binned.feature_names),
        "mappers": [_mapper_state(m) for m in binned.mappers],
    }
    buf = _json.dumps(payload).encode("utf-8")
    return _new_handle(buf)


def byte_buffer_size(handle: int) -> int:
    return len(_get(handle))


def byte_buffer_get_at(handle: int, index: int) -> int:
    return _get(handle)[index]


def dataset_create_from_serialized_reference(buf_ptr: int, buf_size: int,
                                             num_row: int,
                                             num_classes: int,
                                             parameters: str) -> int:
    """(ref: LGBM_DatasetCreateFromSerializedReference c_api.cpp:1245)"""
    import json as _json
    from .dataset import BinnedDataset, Metadata
    from .io.binary_format import _mapper_from_state
    raw = ctypes.string_at(buf_ptr, buf_size)
    payload = _json.loads(raw.decode("utf-8"))
    mappers = [_mapper_from_state(s) for s in payload["mappers"]]
    used = payload["used_features"]
    ref_binned = BinnedDataset(
        np.zeros((1, 0), np.uint8), mappers, used,
        payload["num_total_features"], Metadata(0),
        feature_names=payload["feature_names"])
    from .io.binary_format import make_dataset_shell
    ref = make_dataset_shell(ref_binned, _parse_params(parameters))
    sd = _StreamingDataset(num_row, payload["num_total_features"],
                           _parse_params(parameters), ref)
    if num_classes > 1:
        sd.nclasses = int(num_classes)
    return _new_handle(sd)


def booster_get_loaded_param(handle: int) -> str:
    """(ref: LGBM_BoosterGetLoadedParam — JSON of the model's stored
    parameters block)."""
    import json as _json
    bst = _get(handle)
    params = dict(getattr(bst, "_loaded", None) and bst._loaded.params
                  or bst.params or {})
    return _json.dumps(params)


# -- sparse (CSR) prediction output ----------------------------------------
def booster_predict_sparse_output(handle: int, indptr_ptr: int,
                                  indptr_type: int, indices_ptr: int,
                                  data_ptr: int, data_type: int,
                                  nindptr: int, nelem: int, num_col: int,
                                  predict_type: int, start_iteration: int,
                                  num_iteration: int) -> tuple:
    """Feature contributions as CSR (ref: LGBM_BoosterPredictSparseOutput
    c_api.cpp — contrib matrices are mostly zero on sparse input).
    Returns (indptr_bytes, indices_bytes, data_bytes, out_nindptr,
    out_nelem); the C side copies into malloc'd buffers the caller
    frees with LGBM_BoosterFreePredictSparse."""
    from scipy import sparse
    if predict_type != _PREDICT_CONTRIB:
        raise ValueError(
            "sparse output is defined for contribution prediction")
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    bst = _get(handle)
    contrib = np.asarray(bst.predict(
        csr, start_iteration=start_iteration, num_iteration=num_iteration,
        pred_contrib=True), np.float64)
    k = booster_num_model_per_iteration(handle)
    if k > 1:
        # reference layout: num_class * num_data rows x (num_feature + 1)
        # cols, class-major like its other multi-output surfaces
        # (c_api.h:1092)
        n = contrib.shape[0]
        contrib = contrib.reshape(n, k, -1).transpose(1, 0, 2).reshape(
            n * k, -1)
    out = sparse.csr_matrix(contrib)
    # outputs carry the CALLER's indptr/data element types, like the
    # reference's allocation (FreePredictSparse takes both types)
    indptr = np.ascontiguousarray(out.indptr, _NP_DTYPES[indptr_type])
    indices = np.ascontiguousarray(out.indices, np.int32)
    vals = np.ascontiguousarray(out.data, _NP_DTYPES[data_type])
    return (indptr.tobytes(), indices.tobytes(), vals.tobytes(),
            int(len(indptr)), int(len(vals)))


# -- Arrow C-data entry points (raw struct pointers) ------------------------
# The PyCapsule-protocol ingestion in io/arrow_ingest.py does all the
# work; these shims wrap the C API's raw ArrowArray/ArrowSchema/
# ArrowArrayStream pointers in no-destructor capsules so the same
# (dependency-free) reader consumes them (ref: c_api.cpp
# LGBM_DatasetCreateFromArrow* family via nanoarrow).
_PyCapsule_New = ctypes.pythonapi.PyCapsule_New
_PyCapsule_New.restype = ctypes.py_object
_PyCapsule_New.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_void_p]


class _RawArrowArray:
    def __init__(self, schema_ptr: int, array_ptr: int):
        self._schema_ptr = schema_ptr
        self._array_ptr = array_ptr

    def __arrow_c_array__(self, requested_schema=None):
        return (_PyCapsule_New(self._schema_ptr, b"arrow_schema", None),
                _PyCapsule_New(self._array_ptr, b"arrow_array", None))


class _RawArrowStream:
    def __init__(self, stream_ptr: int):
        self._stream_ptr = stream_ptr

    def __arrow_c_stream__(self, requested_schema=None):
        return _PyCapsule_New(self._stream_ptr, b"arrow_array_stream",
                              None)


def _arrow_chunks_matrix(n_chunks: int, chunks_ptr: int, schema_ptr: int):
    from .io.arrow_ingest import ArrowArray, arrow_to_matrix
    if n_chunks <= 0 or not chunks_ptr or not schema_ptr:
        raise ValueError("empty Arrow chunked array")
    sz = ctypes.sizeof(ArrowArray)
    mats, names = [], None
    for i in range(int(n_chunks)):
        m, names = arrow_to_matrix(
            _RawArrowArray(schema_ptr, chunks_ptr + i * sz))
        mats.append(m)
    return (np.concatenate(mats, axis=0) if len(mats) > 1 else mats[0],
            names)


def dataset_create_from_arrow(n_chunks: int, chunks_ptr: int,
                              schema_ptr: int, parameters: str,
                              reference: int) -> int:
    mat, names = _arrow_chunks_matrix(n_chunks, chunks_ptr, schema_ptr)
    ref = _resolve_ds(_get(reference)) if reference else None
    ds = Dataset(np.asarray(mat, np.float64), reference=ref,
                 feature_name=names or "auto",
                 params=_parse_params(parameters))
    return _new_handle(ds)


def dataset_create_from_arrow_stream(stream_ptr: int, parameters: str,
                                     reference: int) -> int:
    from .io.arrow_ingest import arrow_to_matrix
    mat, names = arrow_to_matrix(_RawArrowStream(stream_ptr))
    ref = _resolve_ds(_get(reference)) if reference else None
    ds = Dataset(np.asarray(mat, np.float64), reference=ref,
                 feature_name=names or "auto",
                 params=_parse_params(parameters))
    return _new_handle(ds)


def _set_field_values(handle: int, field: str, values: np.ndarray) -> None:
    ds = _resolve_ds(_get(handle))
    if field == "label":
        ds.set_label(values)
    elif field == "weight":
        ds.set_weight(values)
    elif field in ("group", "query"):
        ds.set_group(values)
    elif field == "init_score":
        ds.set_init_score(values)
    else:
        raise ValueError(f"unknown field {field}")


def dataset_set_field_from_arrow(handle: int, field: str, n_chunks: int,
                                 chunks_ptr: int, schema_ptr: int) -> None:
    from .io.arrow_ingest import ArrowArray, arrow_to_vector
    if n_chunks <= 0 or not chunks_ptr or not schema_ptr:
        raise ValueError("empty Arrow chunked array")
    sz = ctypes.sizeof(ArrowArray)
    parts = [arrow_to_vector(_RawArrowArray(schema_ptr,
                                            chunks_ptr + i * sz))
             for i in range(int(n_chunks))]
    _set_field_values(handle, field,
                      np.concatenate(parts) if len(parts) > 1 else parts[0])


def dataset_set_field_from_arrow_stream(handle: int, field: str,
                                        stream_ptr: int) -> None:
    from .io.arrow_ingest import _iter_stream, _primitive_to_numpy
    parts = []
    for schema, array, _keep in _iter_stream(_RawArrowStream(stream_ptr)):
        parts.append(_primitive_to_numpy(schema, array))
    if not parts:
        raise ValueError("empty Arrow stream")
    _set_field_values(handle, field,
                      np.concatenate(parts) if len(parts) > 1 else parts[0])


def booster_predict_for_arrow(handle: int, n_chunks: int, chunks_ptr: int,
                              schema_ptr: int, predict_type: int,
                              start_iteration: int, num_iteration: int,
                              out_ptr: int) -> int:
    mat, _names = _arrow_chunks_matrix(n_chunks, chunks_ptr, schema_ptr)
    return _predict_into(_get(handle), np.asarray(mat, np.float64),
                         predict_type, start_iteration, num_iteration,
                         out_ptr)


def booster_predict_for_arrow_stream(handle: int, stream_ptr: int,
                                     predict_type: int,
                                     start_iteration: int,
                                     num_iteration: int,
                                     out_ptr: int) -> int:
    from .io.arrow_ingest import arrow_to_matrix
    mat, _names = arrow_to_matrix(_RawArrowStream(stream_ptr))
    return _predict_into(_get(handle), np.asarray(mat, np.float64),
                         predict_type, start_iteration, num_iteration,
                         out_ptr)


# -- CSC / multi-matrix creation -------------------------------------------
def _csc_from_ptrs(col_ptr: int, col_ptr_type: int, indices_ptr: int,
                   data_ptr: int, data_type: int, ncol_ptr: int,
                   nelem: int, num_row: int):
    from scipy import sparse
    colptr = _array_from_ptr(col_ptr, ncol_ptr, col_ptr_type)
    indices = _array_from_ptr(indices_ptr, nelem, 2)
    data = _array_from_ptr(data_ptr, nelem, data_type)
    return sparse.csc_matrix(
        (np.asarray(data, np.float64), indices, colptr),
        shape=(num_row, ncol_ptr - 1))


def dataset_create_from_csc(col_ptr: int, col_ptr_type: int,
                            indices_ptr: int, data_ptr: int,
                            data_type: int, ncol_ptr: int, nelem: int,
                            num_row: int, parameters: str,
                            reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromCSC c_api.cpp — the col-wise twin)"""
    csc = _csc_from_ptrs(col_ptr, col_ptr_type, indices_ptr, data_ptr,
                         data_type, ncol_ptr, nelem, num_row)
    ref = _resolve_ds(_get(reference)) if reference else None
    ds = Dataset(csc, reference=ref, params=_parse_params(parameters))
    return _new_handle(ds)


def booster_predict_for_csc(handle: int, col_ptr: int, col_ptr_type: int,
                            indices_ptr: int, data_ptr: int,
                            data_type: int, ncol_ptr: int, nelem: int,
                            num_row: int, predict_type: int,
                            start_iteration: int, num_iteration: int,
                            out_ptr: int) -> int:
    csc = _csc_from_ptrs(col_ptr, col_ptr_type, indices_ptr, data_ptr,
                         data_type, ncol_ptr, nelem, num_row)
    return _predict_into(_get(handle), csc.tocsr(), predict_type,
                         start_iteration, num_iteration, out_ptr)


def dataset_create_from_mats(nmat: int, data_ptrs_ptr: int, data_type: int,
                             nrow_ptr: int, ncol: int,
                             is_row_major_ptr: int, parameters: str,
                             reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromMats — stacked sub-matrices)"""
    ptrs = _array_from_ptr(data_ptrs_ptr, nmat, 3)
    nrows = _array_from_ptr(nrow_ptr, nmat, 2)
    majors = _array_from_ptr(is_row_major_ptr, nmat, 2)
    mats = []
    for i in range(nmat):
        n = int(nrows[i])
        flat = _array_from_ptr(int(ptrs[i]), n * ncol, data_type)
        mats.append(flat.reshape(n, ncol) if majors[i]
                    else flat.reshape(ncol, n).T)
    mat = np.concatenate(mats, axis=0) if len(mats) > 1 else mats[0]
    ref = _resolve_ds(_get(reference)) if reference else None
    ds = Dataset(np.asarray(mat, np.float64), reference=ref,
                 params=_parse_params(parameters))
    return _new_handle(ds)


def _as_dense(ds) -> np.ndarray:
    data = ds.get_data()
    if hasattr(data, "todense"):
        return np.asarray(data.todense(), np.float64)
    return np.asarray(data, np.float64)


def dataset_add_features_from(target: int, source: int) -> None:
    """(ref: LGBM_DatasetAddFeaturesFrom dataset.cpp:1437 — append the
    source dataset's features to the target). Requires raw data on both
    (re-bins the combined matrix; the reference splices bin mappers).
    The target's metadata (label/weight/group/init_score/position) and
    both sides' feature names are preserved."""
    tgt = _resolve_ds(_get(target))
    src = _resolve_ds(_get(source))
    if tgt.data is None or src.data is None:
        raise ValueError("AddFeaturesFrom requires raw data on both "
                         "datasets")
    names = None
    tn, sn = tgt.get_feature_name(), src.get_feature_name()
    if tn and sn:
        names = list(tn) + list(sn)
    merged = Dataset(np.hstack([_as_dense(tgt), _as_dense(src)]),
                     label=tgt.get_label(), weight=tgt.get_weight(),
                     group=tgt.get_group(),
                     init_score=tgt.get_init_score(),
                     feature_name=names or "auto",
                     params=dict(tgt.params or {}))
    merged.position = getattr(tgt, "position", None)
    merged.construct()
    _registry[target] = merged


def network_init_with_functions(num_machines: int, rank: int,
                                reduce_scatter_ptr: int,
                                allgather_ptr: int) -> None:
    """API-parity seam for LGBM_NetworkInitWithFunctions
    (c_api.cpp:2867): external collective callbacks are recorded but
    collectives ride XLA over the jax mesh (see network_init)."""
    _network_conf[0] = {"machines": "<external-functions>",
                        "num_machines": int(num_machines),
                        "rank": int(rank),
                        "reduce_scatter_ext": int(reduce_scatter_ptr),
                        "allgather_ext": int(allgather_ptr)}
