"""Elastic mesh-resize resume: restore a checkpoint taken on W shards
onto a W'-shard mesh, with the rejoin validated before it votes.

PR 8's checkpoint/resume restores sharded [N] row state through the
*rebuilt* booster's sharding (``jax.device_put(host, like.sharding)``),
so the mechanics of landing W-shard state on a W'-shard mesh already
exist. What was missing is the *policy* and the *safety net*:

- **Policy** — the checkpoint fingerprint now records ``mesh_shards``.
  ``check_fingerprint`` tolerates a fingerprint that differs in mesh
  shape ONLY (and only when ``tpu_elastic_resume`` is on); any other
  structural drift — objective, dataset shape, tree counts — raises
  ``ResumeMismatchError`` exactly as before. An elastic resume is a
  deliberate, named event (``resilience/elastic_resumes`` /
  ``resilience/mesh_resizes`` counters), not a silent accident.

- **Safety net** — before the first resumed iteration contributes, the
  rejoined replicas are gated with obs/health.py drift digests
  (``gate_rejoin``): a compact host-side digest of the restored row
  state (scores, bagging mask, valid scores, iteration counter) is
  replicated onto the rebuilt mesh and digest-compared per shard,
  together with any restored state that is genuinely replicated on the
  mesh. In a multi-process elastic rejoin each process computes the
  digest from the checkpoint IT loaded — a shard that read a stale or
  torn container diverges here and the resume fails fast with
  ``ElasticResumeError`` naming the shard ordinal(s), instead of
  silently forking the model on the first psum.

The deterministic chaos twin is ``resize_at_iter`` in
resilience/faults.py: kill at iteration k, re-run with a different
``tpu_num_shards``, and this module proves the rejoin
(tools/check_continual.py drives it end-to-end).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .errors import ElasticResumeError, ResumeMismatchError

# the only fingerprint keys an elastic resume may tolerate drifting —
# everything else is structural and always refuses
MESH_KEYS = ("mesh_shards",)


def mesh_shards_of(gbdt) -> int:
    """The booster's mesh width (1 for the serial/unsharded path)."""
    mesh = getattr(gbdt, "_shard_mesh", None)
    if mesh is None:
        mesh = getattr(gbdt, "mesh", None)
    return int(getattr(mesh, "size", 1) or 1) if mesh is not None else 1


def fingerprint_diffs(fp_ck: Dict[str, Any],
                      fp_now: Dict[str, Any]) -> Dict[str, tuple]:
    """{key: (checkpoint value, current value)} over keys that differ.
    A key absent from the CHECKPOINT fingerprint is skipped (an older
    container written before that key existed cannot be blamed for it);
    a key absent from the current fingerprint still reports."""
    return {k: (fp_ck.get(k), fp_now.get(k)) for k in fp_ck
            if fp_ck.get(k) != fp_now.get(k)}


def check_fingerprint(fp_ck: Dict[str, Any], fp_now: Dict[str, Any],
                      elastic: bool) -> bool:
    """Validate a checkpoint fingerprint against the freshly-built run.
    Returns True when this is a (tolerated) mesh resize; raises
    ``ResumeMismatchError`` on any structural drift, and on mesh drift
    too when ``elastic`` is off."""
    diffs = fingerprint_diffs(fp_ck, fp_now)
    if not diffs:
        return False
    structural = {k: v for k, v in diffs.items() if k not in MESH_KEYS}
    if structural:
        raise ResumeMismatchError(
            f"checkpoint is incompatible with this run: {structural} "
            "(checkpoint value, current value)")
    if not elastic:
        raise ResumeMismatchError(
            f"checkpoint was taken on a different mesh shape: {diffs} "
            "(checkpoint value, current value); set "
            "tpu_elastic_resume=true to resume across a mesh resize")
    return True


# ---------------------------------------------------------------------------
# rejoin validation
def restore_digest(state: Dict[str, Any]) -> np.ndarray:
    """Compact [8] f32 digest of the row state a checkpoint restores:
    iteration counter, score sum/sumsq/abs-sum (nonfinite zeroed, like
    the obs/health drift digests), bagging-mask sum, valid-score sum,
    tree count, nonfinite count. Computed on HOST from the loaded
    container — in a multi-process rejoin, each process digests what it
    actually read, so a stale/torn load diverges at the gate."""
    scores = np.asarray(state.get("scores", np.zeros(1)), np.float64)
    finite = np.isfinite(scores)
    sz = np.where(finite, scores, 0.0)
    mask = np.asarray(state.get("sample_mask", np.zeros(1)), np.float64)
    vsum = float(sum(
        np.where(np.isfinite(v), np.asarray(v, np.float64), 0.0).sum()
        for v in state.get("valid_scores", ())))
    return np.asarray([
        float(state.get("iteration", -1)),
        sz.sum(), (sz * sz).sum(), np.abs(sz).sum(),
        float((~finite).sum()),
        float(np.where(np.isfinite(mask), mask, 0.0).sum()),
        vsum,
        float(len(state.get("trees", ()))),
    ], np.float32)


def gate_rejoin(gbdt, state: Dict[str, Any], *,
                resized: bool = False) -> None:
    """Digest-validate the restored state across the (possibly resized)
    mesh BEFORE the first resumed iteration votes. Single-device meshes
    return immediately; a diverged shard raises ``ElasticResumeError``
    naming its ordinal(s). Also counts the resume/resize events the
    continual exporter publishes (``lgbmtpu_continual_*``)."""
    from ..obs.metrics import global_metrics
    global_metrics.inc_counter("resilience/resumes")
    if resized:
        global_metrics.inc_counter("resilience/mesh_resizes")
        global_metrics.inc_counter("resilience/elastic_resumes")
    mesh = getattr(gbdt, "_shard_mesh", None)
    if mesh is None:
        mesh = getattr(gbdt, "mesh", None)
    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return
    import jax

    from ..obs import health as obs_health
    from ..obs.health import DriftError
    from ..parallel.mesh import is_replicated_on, replicate

    arrays: Dict[str, Any] = {
        # the host-loaded container's digest, replicated: every shard
        # must have restored from the SAME bytes
        "restore_digest": replicate(mesh, restore_digest(state)),
    }
    # restored buffers that are genuinely replicated on this mesh
    # (voting / feature-parallel learners replicate scores) are
    # digest-compared directly — a torn device_put fails here
    if isinstance(gbdt.scores, jax.Array) and \
            is_replicated_on(mesh, gbdt.scores):
        arrays["restored_scores"] = gbdt.scores
    if isinstance(getattr(gbdt, "_sample_mask", None), jax.Array) and \
            is_replicated_on(mesh, gbdt._sample_mask):
        arrays["restored_sample_mask"] = gbdt._sample_mask
    try:
        obs_health.global_health.check_drift(
            mesh, arrays, mode="error",
            where="elastic rejoin" if resized else "checkpoint restore")
    except DriftError as exc:
        shards = _diverged_shards(obs_health.global_health)
        global_metrics.inc_counter("resilience/elastic_gate_failures")
        raise ElasticResumeError(
            f"elastic resume rejected: restored state diverged across "
            f"the rebuilt mesh (shard(s) {shards}) — {exc}",
            shards=shards) from exc


def _diverged_shards(health) -> List[int]:
    last = getattr(health, "last_drift", None) or {}
    shards: List[int] = []
    for m in last.get("mismatches", ()):
        for s in m.get("shards", ()):
            if s not in shards:
                shards.append(int(s))
    return shards


def elastic_enabled(config) -> bool:
    v = getattr(config, "tpu_elastic_resume", True)
    return str(v).lower() not in ("off", "0", "false", "none", "")


def resume_summary() -> Optional[Dict[str, int]]:
    """The resume/resize counter snapshot bench and the continual
    exporter fold into their summaries; None when nothing resumed."""
    from ..obs.metrics import global_metrics
    out = {k.rsplit("/", 1)[1]: int(v)
           for k, v in global_metrics.counters.items()
           if k in ("resilience/resumes", "resilience/mesh_resizes",
                    "resilience/elastic_resumes",
                    "resilience/elastic_gate_failures")}
    return out or None
