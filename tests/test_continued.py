"""Continued training (init_model) + snapshot resume
(ref: boosting.cpp:74-90 LoadFileToBoosting, application.cpp:92-100
continued-training init score, engine.py train(init_model=...))."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from tests.conftest import make_binary, make_multiclass, make_regression


def _logloss(y, p):
    p = np.clip(p, 1e-7, 1 - 1e-7)
    return float(-(y * np.log(p) + (1 - y) * np.log(1 - p)).mean())


PARAMS = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
          "verbosity": -1}


def test_resume_zero_rounds_is_exact():
    X, y = make_binary(800)
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=6)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y),
                        num_boost_round=0, init_model=b)
    assert resumed.current_iteration() == 6
    np.testing.assert_array_equal(resumed.predict(X), b.predict(X))


def test_split_training_matches_quality():
    X, y = make_binary(1500)
    full = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    half = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y),
                        num_boost_round=5, init_model=half)
    assert resumed.current_iteration() == 10
    ll_full = _logloss(y, full.predict(X))
    ll_res = _logloss(y, resumed.predict(X))
    # greedy splits may flip on re-derived scores; quality must agree
    assert ll_res < ll_full * 1.2 + 0.02


def test_resume_from_file(tmp_path):
    X, y = make_regression(800)
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    path = str(tmp_path / "model.txt")
    b.save_model(path)
    resumed = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=4, init_model=path)
    assert resumed.current_iteration() == 8
    mse_resumed = np.mean((resumed.predict(X) - y) ** 2)
    mse_half = np.mean((b.predict(X) - y) ** 2)
    assert mse_resumed < mse_half  # more rounds must help on train data


def test_resume_multiclass():
    X, y = make_multiclass(600)
    params = {"objective": "multiclass", "num_class": 4, "num_leaves": 15,
              "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    resumed = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=3, init_model=b)
    assert resumed.current_iteration() == 6
    acc = (resumed.predict(X).argmax(1) == y).mean()
    assert acc > 0.8


def test_resume_class_mismatch_raises():
    X, y = make_binary(500)
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=2)
    Xm, ym = make_multiclass(500)
    params = {"objective": "multiclass", "num_class": 4, "verbosity": -1}
    with pytest.raises(Exception, match="trees per"):
        lgb.train(params, lgb.Dataset(Xm, label=ym),
                  num_boost_round=2, init_model=b)


def test_model_file_roundtrip_after_resume(tmp_path):
    X, y = make_binary(600)
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=3)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y),
                        num_boost_round=3, init_model=b)
    loaded = lgb.Booster(model_str=resumed.model_to_string())
    np.testing.assert_allclose(loaded.predict(X), resumed.predict(X),
                               rtol=1e-9)


def test_cli_snapshot_resume(tmp_path):
    """task=train with input_model= resumes from a snapshot
    (ref: Application::InitTrain input_model, application.cpp:92-100)."""
    from lightgbm_tpu.cli import main as cli_main
    X, y = make_binary(600)
    data = tmp_path / "train.tsv"
    np.savetxt(data, np.column_stack([y, X]), delimiter="\t", fmt="%.6f")
    model1 = tmp_path / "m1.txt"
    cli_main(["task=train", f"data={data}", "objective=binary",
              "num_trees=3", "num_leaves=7", "verbosity=-1",
              f"output_model={model1}", "label_column=0"])
    model2 = tmp_path / "m2.txt"
    cli_main(["task=train", f"data={data}", "objective=binary",
              "num_trees=3", "num_leaves=7", "verbosity=-1",
              f"input_model={model1}", f"output_model={model2}",
              "label_column=0"])
    b = lgb.Booster(model_file=str(model2))
    assert b._loaded.num_iterations == 6
