"""Tree model: flat-array binary tree, prediction, text serialization.

Host-side mirror of the reference Tree (ref: include/LightGBM/tree.h:27,
src/io/tree.cpp). Trees are built from the learner's TreeArrays record by
replaying splits (the same numbering as Tree::Split: internal node s is
created by split s; the left child keeps the parent's leaf id, the right
child becomes leaf id s+1). Serialization follows the reference text model
format (ref: src/boosting/gbdt_model_text.cpp:315) so models interoperate.

Child index convention (same as reference): >= 0 -> internal node id,
< 0 -> ~leaf_id.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2

_CATEGORICAL_MASK = 1
_DEFAULT_LEFT_MASK = 2


class Tree:
    """One decision tree with LightGBM-compatible arrays."""

    def __init__(self, num_leaves: int):
        n = max(num_leaves, 1)
        self.num_leaves = n
        self.num_internal = max(n - 1, 0)
        i = self.num_internal
        self.split_feature = np.zeros(i, np.int32)       # raw feature index
        self.split_feature_inner = np.zeros(i, np.int32)  # used-feature index
        self.threshold = np.zeros(i, np.float64)          # real-valued
        self.threshold_bin = np.zeros(i, np.int32)
        self.decision_type = np.zeros(i, np.int32)
        self.left_child = np.full(i, -1, np.int32)
        self.right_child = np.full(i, -1, np.int32)
        self.split_gain = np.zeros(i, np.float64)
        self.internal_value = np.zeros(i, np.float64)
        self.internal_weight = np.zeros(i, np.float64)
        self.internal_count = np.zeros(i, np.int64)
        self.leaf_value = np.zeros(n, np.float64)
        self.leaf_weight = np.zeros(n, np.float64)
        self.leaf_count = np.zeros(n, np.int64)
        self.leaf_parent = np.full(n, -1, np.int32)
        self.shrinkage = 1.0
        # categorical support: threshold_bin indexes cat_boundaries segments
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []  # packed uint32 bitsets
        self.num_cat = 0
        # bumped by in-place leaf-value mutation (apply_shrinkage /
        # add_bias / refit renewal) so the incremental ensemble packer
        # (ops/predict.py EnsemblePacker) can detect stale packed slots
        # by (id, pack_version) token
        self.pack_version = 0
        # linear-tree leaves (ref: tree.h is_linear_, LinearTreeLearner)
        self.is_linear = False
        self.leaf_const = np.zeros(n, np.float64)
        self.leaf_coeff: List[np.ndarray] = [np.zeros(0)] * n
        self.leaf_features: List[List[int]] = [[] for _ in range(n)]

    # ------------------------------------------------------------------
    @classmethod
    def from_arrays(cls, rec, mappers, used_features) -> "Tree":
        """Build from learner TreeArrays (numpy-converted)."""
        num_leaves = int(rec["num_leaves"])
        tree = cls(num_leaves)
        split_leaf = rec["split_leaf"]

        # leaf id -> (node, side) reference for replay
        leaf_ref = {}
        for s in range(tree.num_internal):
            if split_leaf[s] < 0:
                break
            leaf = int(split_leaf[s])
            node = s
            if leaf in leaf_ref:
                pnode, side = leaf_ref[leaf]
                if side == 0:
                    tree.left_child[pnode] = node
                else:
                    tree.right_child[pnode] = node
            inner = int(rec["split_feature"][s])
            mapper = mappers[inner]
            tbin = int(rec["split_bin_threshold"][s])
            tree.split_feature_inner[node] = inner
            tree.split_feature[node] = used_features[inner]
            tree.threshold_bin[node] = tbin
            dt = 0
            if mapper.is_categorical:
                dt |= _CATEGORICAL_MASK
                tree._add_categorical(node, mapper, tbin, rec, s)
            else:
                tree.threshold[node] = mapper.bin_to_value(tbin)
            if bool(rec["split_default_left"][s]):
                dt |= _DEFAULT_LEFT_MASK
            dt |= int(mapper.missing_type) << 2
            tree.decision_type[node] = dt
            tree.split_gain[node] = float(rec["split_gain"][s])
            tree.internal_value[node] = float(rec["internal_value"][s])
            tree.internal_weight[node] = float(rec["internal_weight"][s])
            tree.internal_count[node] = int(rec["internal_count"][s])
            tree.left_child[node] = ~leaf
            tree.right_child[node] = ~(s + 1)
            leaf_ref[leaf] = (node, 0)
            leaf_ref[s + 1] = (node, 1)

        for leaf, (pnode, _) in leaf_ref.items():
            if leaf < num_leaves:
                tree.leaf_parent[leaf] = pnode
        tree.leaf_value = np.asarray(rec["leaf_value"][:num_leaves], np.float64)
        tree.leaf_weight = np.asarray(rec["leaf_weight"][:num_leaves], np.float64)
        tree.leaf_count = np.asarray(rec["leaf_count"][:num_leaves], np.int64)
        return tree

    def _add_categorical(self, node, mapper, tbin, rec, s):
        """Categorical split: the bins in the recorded cat_mask go left,
        converted to a bitset over raw category values
        (ref: tree.h:375 CategoricalDecision bitset,
        Common::ConstructBitset). Legacy records without a mask fall back
        to one-hot on the threshold bin."""
        mask = rec.get("split_cat_mask")
        if mask is not None:
            bins_left = [int(b) for b in np.flatnonzero(mask[s])
                         if 1 <= b <= len(mapper.cat_bin_to_value)]
        else:
            bins_left = []
        if not bins_left:
            bins_left = [int(tbin)]
        values = [int(mapper.bin_to_value(b)) for b in bins_left]
        values = [v for v in values if v >= 0] or [0]
        max_val = max(values)
        nwords = max_val // 32 + 1
        bits = [0] * nwords
        for v in values:
            bits[v // 32] |= 1 << (v % 32)
        self.threshold[node] = self.num_cat  # index into cat_boundaries
        self.cat_boundaries.append(self.cat_boundaries[-1] + nwords)
        self.cat_threshold.extend(bits)
        self.num_cat += 1

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        """(ref: tree.h:189 Tree::Shrinkage)"""
        self.pack_version += 1
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate
        if self.is_linear:
            self.leaf_const *= rate
            self.leaf_coeff = [c * rate for c in self.leaf_coeff]

    def add_bias(self, value: float) -> None:
        self.pack_version += 1
        self.leaf_value += value
        self.internal_value += value
        if self.is_linear:
            self.leaf_const += value

    # ------------------------------------------------------------------
    def _decide(self, node: int, value: float) -> bool:
        """True -> go left (ref: tree.h:338 NumericalDecision)."""
        dt = self.decision_type[node]
        if dt & _CATEGORICAL_MASK:
            if np.isnan(value):
                return False
            iv = int(value)
            if iv < 0:
                return False
            cat_idx = int(self.threshold[node])
            lo = self.cat_boundaries[cat_idx]
            hi = self.cat_boundaries[cat_idx + 1]
            word = iv // 32
            if word >= hi - lo:
                return False
            return bool((self.cat_threshold[lo + word] >> (iv % 32)) & 1)
        missing_type = (dt >> 2) & 3
        default_left = bool(dt & _DEFAULT_LEFT_MASK)
        if np.isnan(value) and missing_type != MISSING_ZERO:
            if missing_type == MISSING_NAN:
                return default_left
            value = 0.0
        if missing_type == MISSING_ZERO and (np.isnan(value) or
                                             abs(value) <= 1e-35):
            return default_left
        return value <= self.threshold[node]

    def predict_row(self, row: np.ndarray) -> float:
        return self.leaf_value[self.predict_leaf_row(row)]

    def predict_leaf_row(self, row: np.ndarray) -> int:
        if self.num_internal == 0:
            return 0
        node = 0
        while True:
            child = (self.left_child[node]
                     if self._decide(node, row[self.split_feature[node]])
                     else self.right_child[node])
            if child < 0:
                return ~child
            node = child

    def predict(self, data: np.ndarray) -> np.ndarray:
        """Vectorized batch prediction over raw feature values."""
        return self.predict_given_leaves(data, self.predict_leaf(data))

    def predict_given_leaves(self, data: np.ndarray,
                             leaves: np.ndarray) -> np.ndarray:
        """Leaf outputs for rows whose leaf assignment is already known
        (e.g. the grower's row->leaf map — skips re-traversal)."""
        if not self.is_linear:
            return self.leaf_value[leaves]
        # linear leaves: const + coeff . x, falling back to leaf_value for
        # rows with NaN in any used feature (ref: tree.h linear predict)
        out = self.leaf_value[leaves].copy()
        order = np.argsort(leaves, kind="stable")
        bounds = np.searchsorted(leaves[order],
                                 np.arange(self.num_leaves + 1))
        for leaf in range(self.num_leaves):
            rows = order[bounds[leaf]:bounds[leaf + 1]]
            if rows.size == 0:
                continue
            feats = self.leaf_features[leaf]
            if not feats:
                out[rows] = self.leaf_const[leaf]
                continue
            x = data[np.ix_(rows, feats)]
            ok = ~np.isnan(x).any(axis=1)
            lin = self.leaf_const[leaf] + x[ok] @ np.asarray(
                self.leaf_coeff[leaf])
            out[rows[ok]] = lin
        return out

    def predict_leaf(self, data: np.ndarray) -> np.ndarray:
        n = data.shape[0]
        if self.num_internal == 0:
            return np.zeros(n, np.int32)
        # iterative vectorized traversal: node id per row; leaves = ~id
        node = np.zeros(n, np.int32)
        done = np.zeros(n, bool)
        out = np.zeros(n, np.int32)
        for _ in range(self.num_internal + 1):
            if done.all():
                break
            active = ~done
            nd = node[active]
            feat = self.split_feature[nd]
            vals = data[active, feat]
            go_left = self._decide_vec(nd, vals)
            child = np.where(go_left, self.left_child[nd],
                             self.right_child[nd])
            is_leaf = child < 0
            idx = np.flatnonzero(active)
            out[idx[is_leaf]] = ~child[is_leaf]
            done[idx[is_leaf]] = True
            node[idx[~is_leaf]] = child[~is_leaf]
        return out

    def _decide_vec(self, nodes: np.ndarray, values: np.ndarray) -> np.ndarray:
        dt = self.decision_type[nodes]
        thr = self.threshold[nodes]
        missing_type = (dt >> 2) & 3
        default_left = (dt & _DEFAULT_LEFT_MASK) > 0
        is_cat = (dt & _CATEGORICAL_MASK) > 0
        isnan = np.isnan(values)
        vals = np.where(isnan, 0.0, values)

        res = vals <= thr
        # missing routing
        use_default = (isnan & (missing_type == MISSING_NAN)) | \
            ((missing_type == MISSING_ZERO) & (isnan | (np.abs(vals) <= 1e-35)))
        res = np.where(use_default, default_left, res)
        # NaN with non-nan missing type: treated as 0.0 (already via vals)
        if is_cat.any():
            cat_rows = np.flatnonzero(is_cat)
            for r in cat_rows:
                res[r] = self._decide(nodes[r], values[r])
        return res

    # ------------------------------------------------------------------
    def to_string(self, tree_idx: int) -> str:
        """Serialize (ref: gbdt_model_text.cpp per-tree block)."""
        lines = [f"Tree={tree_idx}"]
        lines.append(f"num_leaves={self.num_leaves}")
        lines.append(f"num_cat={self.num_cat}")
        if self.num_internal:
            lines.append("split_feature=" +
                         " ".join(map(str, self.split_feature)))
            lines.append("split_gain=" +
                         " ".join(_fmt(v) for v in self.split_gain))
            lines.append("threshold=" +
                         " ".join(_fmt(v) for v in self.threshold))
            lines.append("decision_type=" +
                         " ".join(map(str, self.decision_type)))
            lines.append("left_child=" + " ".join(map(str, self.left_child)))
            lines.append("right_child=" + " ".join(map(str, self.right_child)))
            lines.append("internal_value=" +
                         " ".join(_fmt(v) for v in self.internal_value))
            lines.append("internal_weight=" +
                         " ".join(_fmt(v) for v in self.internal_weight))
            lines.append("internal_count=" +
                         " ".join(map(str, self.internal_count)))
        lines.append("leaf_value=" + " ".join(_fmt(v) for v in self.leaf_value))
        lines.append("leaf_weight=" + " ".join(_fmt(v) for v in self.leaf_weight))
        lines.append("leaf_count=" + " ".join(map(str, self.leaf_count)))
        if self.num_cat > 0:
            lines.append("cat_boundaries=" +
                         " ".join(map(str, self.cat_boundaries)))
            lines.append("cat_threshold=" +
                         " ".join(map(str, self.cat_threshold)))
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear:
            # (ref: gbdt_model_text.cpp linear-tree block: per-leaf const,
            # feature count, flattened feature ids and coefficients)
            lines.append("leaf_const=" +
                         " ".join(_fmt(v) for v in self.leaf_const))
            lines.append("num_features=" + " ".join(
                str(len(f)) for f in self.leaf_features))
            lines.append("leaf_features=" + " ".join(
                str(f) for feats in self.leaf_features for f in feats))
            lines.append("leaf_coeff=" + " ".join(
                _fmt(c) for coeffs in self.leaf_coeff for c in coeffs))
        lines.append(f"shrinkage={_fmt(self.shrinkage)}")
        lines.append("")
        return "\n".join(lines)

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        """Parse one Tree= block (ref: tree.cpp Tree(const char*))."""
        kv = {}
        for line in text.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        num_leaves = int(kv["num_leaves"])
        tree = cls(num_leaves)
        tree.num_cat = int(kv.get("num_cat", 0))

        def parse(key, dtype, default=None):
            if key not in kv or not kv[key]:
                return default
            return np.array([float(x) for x in kv[key].split()]).astype(dtype)

        i = tree.num_internal
        if i > 0:
            tree.split_feature = parse("split_feature", np.int32)
            tree.split_feature_inner = tree.split_feature.copy()
            tree.split_gain = parse("split_gain", np.float64,
                                    np.zeros(i)) if "split_gain" in kv else np.zeros(i)
            tree.threshold = parse("threshold", np.float64)
            tree.decision_type = parse("decision_type", np.int32, np.zeros(i, np.int32))
            if tree.decision_type is None:
                tree.decision_type = np.zeros(i, np.int32)
            tree.left_child = parse("left_child", np.int32)
            tree.right_child = parse("right_child", np.int32)
            iv = parse("internal_value", np.float64)
            tree.internal_value = iv if iv is not None else np.zeros(i)
            iw = parse("internal_weight", np.float64)
            tree.internal_weight = iw if iw is not None else np.zeros(i)
            ic = parse("internal_count", np.int64)
            tree.internal_count = ic if ic is not None else np.zeros(i, np.int64)
        tree.leaf_value = parse("leaf_value", np.float64)
        lw = parse("leaf_weight", np.float64)
        tree.leaf_weight = lw if lw is not None else np.zeros(num_leaves)
        lc = parse("leaf_count", np.int64)
        tree.leaf_count = lc if lc is not None else np.zeros(num_leaves, np.int64)
        if tree.num_cat > 0:
            tree.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            tree.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
        tree.shrinkage = float(kv.get("shrinkage", 1.0))
        if int(kv.get("is_linear", 0)):
            tree.is_linear = True
            tree.leaf_const = parse("leaf_const", np.float64,
                                    np.zeros(num_leaves))
            counts = [int(x) for x in kv.get("num_features", "").split()]
            flat_feats = [int(x) for x in kv.get("leaf_features", "").split()]
            flat_coeff = [float(x) for x in kv.get("leaf_coeff", "").split()]
            pos = 0
            tree.leaf_features, tree.leaf_coeff = [], []
            for c in counts:
                tree.leaf_features.append(flat_feats[pos:pos + c])
                tree.leaf_coeff.append(np.asarray(flat_coeff[pos:pos + c]))
                pos += c
            while len(tree.leaf_features) < num_leaves:
                tree.leaf_features.append([])
                tree.leaf_coeff.append(np.zeros(0))
        return tree

    # ------------------------------------------------------------------
    def to_json(self, tree_idx: int) -> dict:
        """(ref: tree.h ToJSON)"""
        return {
            "tree_index": tree_idx,
            "num_leaves": int(self.num_leaves),
            "num_cat": int(self.num_cat),
            "shrinkage": float(self.shrinkage),
            "tree_structure": self._node_json(0 if self.num_internal else ~0),
        }

    def _node_json(self, node: int) -> dict:
        if node < 0:
            leaf = ~node
            return {
                "leaf_index": int(leaf),
                "leaf_value": float(self.leaf_value[leaf]),
                "leaf_weight": float(self.leaf_weight[leaf]),
                "leaf_count": int(self.leaf_count[leaf]),
            }
        dt = int(self.decision_type[node])
        out = {
            "split_index": int(node),
            "split_feature": int(self.split_feature[node]),
            "split_gain": float(self.split_gain[node]),
            "threshold": float(self.threshold[node]),
            "decision_type": "==" if dt & _CATEGORICAL_MASK else "<=",
            "default_left": bool(dt & _DEFAULT_LEFT_MASK),
            "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
            "internal_value": float(self.internal_value[node]),
            "internal_weight": float(self.internal_weight[node]),
            "internal_count": int(self.internal_count[node]),
            "left_child": self._node_json(self.left_child[node]),
            "right_child": self._node_json(self.right_child[node]),
        }
        return out


def _fmt(v: float) -> str:
    """Shortest round-trip float formatting (the reference uses
    Common::DoubleToStr with %.17g)."""
    return repr(float(v))
