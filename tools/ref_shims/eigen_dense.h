// Build shim for the vendored Eigen (submodule not present in this offline
// environment). LightGBM's linear tree learner uses only:
//   MatrixXd(r, c), operator()(i, j), operator()(i),
//   m.fullPivLu().inverse(), operator* (matmul), unary minus.
// Inverse is Gauss-Jordan with partial pivoting — same algorithm family as
// Eigen's FullPivLU; results agree to machine precision on the
// well-conditioned normal-equation matrices the linear learner builds.
#ifndef EIGEN_DENSE_SHIM_H_
#define EIGEN_DENSE_SHIM_H_

#include <cmath>
#include <cstddef>
#include <utility>
#include <vector>

namespace Eigen {

class FullPivLU;

class MatrixXd {
 public:
  MatrixXd() : rows_(0), cols_(0) {}
  MatrixXd(std::ptrdiff_t r, std::ptrdiff_t c)
      : rows_(r), cols_(c), data_(static_cast<size_t>(r * c), 0.0) {}

  double& operator()(std::ptrdiff_t i, std::ptrdiff_t j) {
    return data_[static_cast<size_t>(i * cols_ + j)];
  }
  double operator()(std::ptrdiff_t i, std::ptrdiff_t j) const {
    return data_[static_cast<size_t>(i * cols_ + j)];
  }
  // single-index access (column vectors)
  double& operator()(std::ptrdiff_t i) { return data_[static_cast<size_t>(i)]; }
  double operator()(std::ptrdiff_t i) const {
    return data_[static_cast<size_t>(i)];
  }

  std::ptrdiff_t rows() const { return rows_; }
  std::ptrdiff_t cols() const { return cols_; }

  MatrixXd operator*(const MatrixXd& o) const {
    MatrixXd out(rows_, o.cols_);
    for (std::ptrdiff_t i = 0; i < rows_; ++i) {
      for (std::ptrdiff_t k = 0; k < cols_; ++k) {
        double a = (*this)(i, k);
        if (a == 0.0) continue;
        for (std::ptrdiff_t j = 0; j < o.cols_; ++j) {
          out(i, j) += a * o(k, j);
        }
      }
    }
    return out;
  }

  MatrixXd operator-() const {
    MatrixXd out = *this;
    for (auto& v : out.data_) v = -v;
    return out;
  }

  inline FullPivLU fullPivLu() const;

  std::ptrdiff_t rows_, cols_;
  std::vector<double> data_;
};

class FullPivLU {
 public:
  explicit FullPivLU(const MatrixXd& m) : m_(m) {}

  MatrixXd inverse() const {
    std::ptrdiff_t n = m_.rows();
    // augmented [A | I] Gauss-Jordan with partial (row) pivoting
    MatrixXd a = m_;
    MatrixXd inv(n, n);
    for (std::ptrdiff_t i = 0; i < n; ++i) inv(i, i) = 1.0;
    for (std::ptrdiff_t col = 0; col < n; ++col) {
      std::ptrdiff_t piv = col;
      double best = std::fabs(a(col, col));
      for (std::ptrdiff_t r = col + 1; r < n; ++r) {
        if (std::fabs(a(r, col)) > best) {
          best = std::fabs(a(r, col));
          piv = r;
        }
      }
      if (best == 0.0) continue;  // singular direction: leave zeros
      if (piv != col) {
        for (std::ptrdiff_t j = 0; j < n; ++j) {
          std::swap(a(col, j), a(piv, j));
          std::swap(inv(col, j), inv(piv, j));
        }
      }
      double d = a(col, col);
      for (std::ptrdiff_t j = 0; j < n; ++j) {
        a(col, j) /= d;
        inv(col, j) /= d;
      }
      for (std::ptrdiff_t r = 0; r < n; ++r) {
        if (r == col) continue;
        double f = a(r, col);
        if (f == 0.0) continue;
        for (std::ptrdiff_t j = 0; j < n; ++j) {
          a(r, j) -= f * a(col, j);
          inv(r, j) -= f * inv(col, j);
        }
      }
    }
    return inv;
  }

 private:
  MatrixXd m_;
};

inline FullPivLU MatrixXd::fullPivLu() const { return FullPivLU(*this); }

}  // namespace Eigen

#endif  // EIGEN_DENSE_SHIM_H_
