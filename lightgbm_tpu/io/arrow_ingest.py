"""Arrow C-data-interface ingestion, dependency-free.

TPU-native counterpart of the reference's nanoarrow-based ingestion
(ref: include/LightGBM/arrow.h:34 ArrowChunkedArray,
src/arrow/array.hpp, c_api.cpp LGBM_DatasetCreateFromArrow). pyarrow is
not required: any object implementing the Arrow PyCapsule protocol
(``__arrow_c_array__`` / ``__arrow_c_stream__`` — pyarrow Tables,
polars DataFrames, nanoarrow wrappers...) is consumed directly through
the C ABI structs via ctypes.

Supported layouts: a struct array (table) of primitive numeric /
boolean children, or a primitive array for labels/weights. Validity
bitmaps map nulls to NaN, matching the reference's null_default
(src/arrow/array.hpp null_default -> quiet_NaN).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

PyCapsule_GetPointer = ctypes.pythonapi.PyCapsule_GetPointer
PyCapsule_GetPointer.restype = ctypes.c_void_p
PyCapsule_GetPointer.argtypes = [ctypes.py_object, ctypes.c_char_p]


class ArrowSchema(ctypes.Structure):
    pass


ArrowSchema._fields_ = [
    ("format", ctypes.c_char_p),
    ("name", ctypes.c_char_p),
    ("metadata", ctypes.c_char_p),
    ("flags", ctypes.c_int64),
    ("n_children", ctypes.c_int64),
    ("children", ctypes.POINTER(ctypes.POINTER(ArrowSchema))),
    ("dictionary", ctypes.POINTER(ArrowSchema)),
    ("release", ctypes.c_void_p),
    ("private_data", ctypes.c_void_p),
]


class ArrowArray(ctypes.Structure):
    pass


ArrowArray._fields_ = [
    ("length", ctypes.c_int64),
    ("null_count", ctypes.c_int64),
    ("offset", ctypes.c_int64),
    ("n_buffers", ctypes.c_int64),
    ("n_children", ctypes.c_int64),
    ("buffers", ctypes.POINTER(ctypes.c_void_p)),
    ("children", ctypes.POINTER(ctypes.POINTER(ArrowArray))),
    ("dictionary", ctypes.POINTER(ArrowArray)),
    ("release", ctypes.c_void_p),
    ("private_data", ctypes.c_void_p),
]


# Arrow format chars -> numpy dtype (primitive subset the reference's
# visitor supports, src/arrow/array.hpp visit())
_FORMAT_DTYPES = {
    b"c": np.int8, b"C": np.uint8,
    b"s": np.int16, b"S": np.uint16,
    b"i": np.int32, b"I": np.uint32,
    b"l": np.int64, b"L": np.uint64,
    b"f": np.float32, b"g": np.float64,
}


def _bitmap_to_bool(ptr: int, offset: int, length: int) -> np.ndarray:
    nbytes = (offset + length + 7) // 8
    raw = np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)), (nbytes,))
    bits = np.unpackbits(raw, bitorder="little")
    return bits[offset:offset + length].astype(bool)


def _primitive_to_numpy(schema: ArrowSchema, arr: ArrowArray,
                        parent_offset: int = 0,
                        parent_length: Optional[int] = None) -> np.ndarray:
    """Read a primitive child. Per the Arrow C data interface, a struct
    parent's offset/length apply logically to its children (sliced
    tables export offset on the parent while children keep full
    buffers), so element i of the parent reads child[i + parent_offset].
    """
    fmt = schema.format
    off = int(arr.offset) + int(parent_offset)
    length = (int(parent_length) if parent_length is not None
              else int(arr.length) - int(parent_offset))
    if fmt == b"b":  # boolean: bit-packed values buffer
        values = _bitmap_to_bool(arr.buffers[1], off, length).astype(
            np.float64)
    else:
        dtype = _FORMAT_DTYPES.get(fmt)
        if dtype is None:
            raise ValueError(
                f"unsupported Arrow type format {fmt!r} (primitive "
                "numeric/boolean only, like the reference's arrow.h)")
        n_items = off + length
        buf = np.ctypeslib.as_array(
            ctypes.cast(arr.buffers[1],
                        ctypes.POINTER(np.ctypeslib.as_ctypes_type(dtype))),
            (n_items,))
        values = buf[off:off + length].astype(np.float64)
    if arr.null_count != 0 and arr.buffers[0]:
        valid = _bitmap_to_bool(arr.buffers[0], off, length)
        values = np.where(valid, values, np.nan)
    return values


def _capsule_to_structs(obj) -> Tuple[ArrowSchema, ArrowArray]:
    schema_cap, array_cap = obj.__arrow_c_array__()
    schema_ptr = PyCapsule_GetPointer(schema_cap, b"arrow_schema")
    array_ptr = PyCapsule_GetPointer(array_cap, b"arrow_array")
    schema = ctypes.cast(schema_ptr, ctypes.POINTER(ArrowSchema)).contents
    array = ctypes.cast(array_ptr, ctypes.POINTER(ArrowArray)).contents
    # keep the capsules alive until we've copied out of the buffers
    return schema, array, (schema_cap, array_cap)


def arrow_to_matrix(obj) -> Tuple[np.ndarray, Optional[List[str]]]:
    """An Arrow struct array/table -> dense [N, F] float64 + column names.
    One-copy (column extraction), like the reference's row-iterator
    ingestion which also materializes into Dataset storage."""
    chunks: List[Tuple] = []
    if hasattr(obj, "__arrow_c_stream__"):
        chunks = list(_iter_stream(obj))
    elif hasattr(obj, "__arrow_c_array__"):
        chunks = [_capsule_to_structs(obj)]
    else:
        raise TypeError(
            "object does not speak the Arrow PyCapsule protocol "
            "(__arrow_c_array__/__arrow_c_stream__)")

    mats = []
    names: Optional[List[str]] = None
    for schema, array, keepalive in chunks:
        if schema.format != b"+s":
            raise ValueError("expected a struct array (table) for "
                             "feature data")
        f = int(schema.n_children)
        cols = []
        names = []
        for j in range(f):
            cschema = schema.children[j].contents
            carr = array.children[j].contents
            cols.append(_primitive_to_numpy(
                cschema, carr, parent_offset=int(array.offset),
                parent_length=int(array.length)))
            names.append((cschema.name or b"").decode() or f"Column_{j}")
        mats.append(np.column_stack(cols) if cols else
                    np.zeros((int(array.length), 0)))
        del keepalive
    return (np.concatenate(mats, axis=0) if len(mats) > 1 else mats[0],
            names)


def arrow_to_vector(obj) -> np.ndarray:
    """A primitive Arrow array -> [N] float64 (labels/weights/init
    scores; ref: c_api.cpp LGBM_DatasetSetFieldFromArrow)."""
    if hasattr(obj, "__arrow_c_array__"):
        schema, array, keepalive = _capsule_to_structs(obj)
        if schema.format == b"+s":
            raise ValueError("expected a primitive array, got a struct")
        out = _primitive_to_numpy(schema, array)
        del keepalive
        return out
    raise TypeError("object does not speak the Arrow PyCapsule protocol")


def _iter_stream(obj):
    """Drain an __arrow_c_stream__ exporter chunk by chunk."""
    cap = obj.__arrow_c_stream__()
    ptr = PyCapsule_GetPointer(cap, b"arrow_array_stream")

    class ArrowArrayStream(ctypes.Structure):
        _fields_ = [
            ("get_schema", ctypes.CFUNCTYPE(
                ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ArrowSchema))),
            ("get_next", ctypes.CFUNCTYPE(
                ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ArrowArray))),
            ("get_last_error", ctypes.CFUNCTYPE(
                ctypes.c_char_p, ctypes.c_void_p)),
            ("release", ctypes.c_void_p),
            ("private_data", ctypes.c_void_p),
        ]

    stream = ctypes.cast(ptr, ctypes.POINTER(ArrowArrayStream)).contents
    schema = ArrowSchema()
    if stream.get_schema(ptr, ctypes.byref(schema)) != 0:
        raise RuntimeError("Arrow stream: get_schema failed")
    release_t = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
    try:
        while True:
            array = ArrowArray()
            if stream.get_next(ptr, ctypes.byref(array)) != 0:
                raise RuntimeError("Arrow stream: get_next failed")
            if not array.release:
                break
            try:
                yield schema, array, (cap,)
            finally:
                # consumer owns each chunk: release after copying out
                # (Arrow C stream ownership contract)
                if array.release:
                    release_t(array.release)(ctypes.byref(array))
    finally:
        if schema.release:
            release_t(schema.release)(ctypes.byref(schema))
        # the consumer owns the stream (Arrow C stream spec): release it
        # after draining so producers can free private_data
        if stream.release:
            release_t(stream.release)(ptr)


def is_arrow(obj) -> bool:
    return (hasattr(obj, "__arrow_c_array__")
            or hasattr(obj, "__arrow_c_stream__"))
