"""Engine semantics the reference pins in its 4.9k-LoC test_engine.py
that weren't yet covered here: prediction iteration slicing, early
stopping min_delta, unseen categoricals, importance types, init_score
continuation (ref: tests/python_package_test/test_engine.py)."""

import numpy as np

from conftest import make_binary, make_multiclass, make_regression

import lightgbm_tpu as lgb


def _booster(params=None, rounds=12, n=600):
    X, y = make_binary(n)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1, **(params or {})}
    return lgb.train(p, lgb.Dataset(X, label=y),
                     num_boost_round=rounds), X, y


class TestPredictSlicing:
    def test_num_iteration_prefix(self):
        """predict(num_iteration=k) equals the raw-score sum of the
        first k trees (ref: LGBM_BoosterPredictForMat num_iteration)."""
        bst, X, _y = _booster()
        full = bst.predict(X, raw_score=True)
        half = bst.predict(X, raw_score=True, num_iteration=6)
        assert not np.allclose(full, half)
        # rebuild the prefix sum from the model dump
        from lightgbm_tpu.model_io import load_model_from_string
        prefix = load_model_from_string(
            bst.model_to_string(num_iteration=6))
        np.testing.assert_allclose(
            half, np.asarray(prefix.predict_raw(X)).reshape(-1),
            rtol=1e-5, atol=1e-6)

    def test_start_iteration_suffix(self):
        bst, X, _y = _booster()
        full = bst.predict(X, raw_score=True)
        head = bst.predict(X, raw_score=True, num_iteration=4)
        tail = bst.predict(X, raw_score=True, start_iteration=4,
                           num_iteration=-1)
        np.testing.assert_allclose(head + tail, full, rtol=1e-5,
                                   atol=1e-5)


class TestEarlyStoppingMinDelta:
    def _run(self, min_delta):
        X, y = make_binary(900, seed=3)
        Xt, yt = X[:600], y[:600]
        Xv, yv = X[600:], y[600:]
        ds = lgb.Dataset(Xt, label=yt)
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 31, "learning_rate":
             0.02, "min_data_in_leaf": 5, "metric": "binary_logloss",
             "verbosity": -1},
            ds, num_boost_round=60,
            valid_sets=[lgb.Dataset(Xv, label=yv, reference=ds)],
            callbacks=[lgb.early_stopping(5, min_delta=min_delta,
                                          verbose=False)])
        return bst.best_iteration

    def test_min_delta_stops_earlier(self):
        """A large min_delta must stop no later than min_delta=0
        (ref: callback.py early_stopping min_delta)."""
        loose = self._run(0.0)
        strict = self._run(0.05)
        assert strict <= loose
        assert strict < 60


class TestCategoricalEdge:
    def test_unseen_category_predicts(self):
        rng = np.random.RandomState(0)
        n = 600
        cat = rng.randint(0, 4, n).astype(np.float64)
        X = np.column_stack([cat, rng.randn(n)])
        y = (cat == 2).astype(np.float64) * 2 + 0.1 * rng.randn(n)
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "min_data_in_leaf": 5, "verbosity": -1},
                        lgb.Dataset(X, label=y,
                                    categorical_feature=[0]),
                        num_boost_round=10)
        Xq = np.array([[99.0, 0.0], [2.0, 0.0]])  # 99 never seen
        pred = bst.predict(Xq)
        assert np.isfinite(pred).all()
        # the unseen category must not land in category 2's leaf
        assert abs(pred[0] - pred[1]) > 0.5


class TestImportanceTypes:
    def test_split_and_gain(self):
        bst, X, _y = _booster()
        split = bst.feature_importance("split")
        gain = bst.feature_importance("gain")
        assert split.shape == gain.shape == (X.shape[1],)
        assert split.sum() > 0 and gain.sum() > 0
        assert np.all(split == split.astype(int))  # counts
        assert np.all(gain >= 0)
        # features never split have zero gain and zero count together
        assert np.array_equal(split == 0, gain == 0)


class TestInitScore:
    def test_training_continues_from_init_score(self):
        """A strong init_score should change the learned residual model
        (ref: Dataset.set_init_score / boost_from_average interplay)."""
        X, y = make_regression(600)
        base = np.full(len(y), y.mean(), np.float64)
        ds = lgb.Dataset(X, label=y)
        ds.set_init_score(base)
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "verbosity": -1, "boost_from_average": False},
                        ds, num_boost_round=20)
        # predictions EXCLUDE the dataset init_score (reference
        # semantics): adding it back should fit y well
        pred = bst.predict(X) + base
        assert np.mean((pred - y) ** 2) < np.var(y) * 0.2


class TestMulticlassPredictShape:
    def test_proba_rows_sum_to_one(self):
        X, y = make_multiclass(600)
        bst = lgb.train({"objective": "multiclass", "num_class": 4,
                         "num_leaves": 7, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=5)
        proba = bst.predict(X)
        assert proba.shape == (600, 4)
        np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-5)
        raw = bst.predict(X, raw_score=True)
        assert raw.shape == (600, 4)
