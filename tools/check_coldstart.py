#!/usr/bin/env python
"""Validator for warm start (ISSUE 14: persistent compile cache +
serialized AOT serving artifacts).

Drives the REAL code paths end-to-end — the acceptance scenario of the
cold-start PR, kept honest in CI:

1. **Second-process warm start** — the same small train run in two
   fresh interpreter processes sharing one fresh compile-cache dir:
   the cold run pays real XLA compiles, the warm rerun's
   ``compile_s_total`` (obs/xla, with persistent-cache hits attributed
   to ``cache_load_s_total`` instead) must be >= 5x smaller, and the
   warm run must actually HIT the cache (``n_cache_hits`` > 0).
2. **Artifact-restore serve smoke** — a model's low-latency ladder is
   exported to a serialized-artifact store, then a fresh registry +
   ``ModelServer`` (the replica-restart twin) warms from disk: ZERO
   ``serve/lowlat`` compiles (obs recompile counters), every program
   an ``serve/aot_loads``, first request + steady-state traffic with
   zero further recompiles, predictions bit-identical to the
   exporter's.
3. **Fingerprint mismatch falls back** — with the stored artifacts
   re-keyed under a foreign fingerprint, the same restore transparently
   RECOMPILES (counted) and still predicts bit-identically: artifacts
   are an accelerator, never a correctness dependency.

Graceful skip (exit 0 with a notice) where
``jax.experimental.serialize_executable`` is unavailable — step 1 still
runs; the cache needs no serialization support.

Exit 0 = all steps passed. Wired into the quick verification tier via
tests/test_coldstart.py (TestToolsWiring).
"""

import asyncio
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

_F = 8


def _model_str() -> str:
    import lightgbm_tpu as lgb
    r = np.random.RandomState(7)
    X = r.randn(600, _F)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.3).astype(np.float32)
    params = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                  max_bin=63, min_data_in_leaf=5, verbosity=-1)
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    return lgb.train(params, ds, num_boost_round=4).model_to_string()


def step1_second_process_warm_start() -> None:
    import bench
    cache_dir = tempfile.mkdtemp(prefix="check_cs_cache_")
    try:
        os.environ["COLDSTART_ITERS"] = "2"
        os.environ["COLDSTART_LEAVES"] = "31"
        cold = bench._coldstart_child_run(cache_dir, 8000)
        warm = bench._coldstart_child_run(cache_dir, 8000)
    finally:
        os.environ.pop("COLDSTART_ITERS", None)
        os.environ.pop("COLDSTART_LEAVES", None)
        shutil.rmtree(cache_dir, ignore_errors=True)
    assert cold["compile_s_total"] > 0, cold
    assert warm.get("n_cache_hits", 0) > 0, \
        f"warm rerun never hit the persistent cache: {warm}"
    reduction = cold["compile_s_total"] / max(warm["compile_s_total"], 1e-2)
    assert reduction >= 5.0, \
        (f"warm compile {warm['compile_s_total']:.3f}s vs cold "
         f"{cold['compile_s_total']:.3f}s — only {reduction:.2f}x")
    print(f"# step 1 OK: cold compile {cold['compile_s_total']:.2f}s -> "
          f"warm {warm['compile_s_total']:.2f}s ({reduction:.1f}x; "
          f"{warm.get('n_cache_hits', 0)} cache hit(s), "
          f"{warm.get('cache_load_s_total', 0.0):.2f}s loading)")


def step2_artifact_restore() -> bool:
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.serve import (ModelRegistry, ModelServer,
                                    SERVE_LOWLAT_TAG, serialize_available)
    if not serialize_available():
        print("# step 2 SKIPPED: jax.experimental.serialize_executable "
              "unavailable on this backend")
        return False
    model_str = _model_str()
    art_dir = tempfile.mkdtemp(prefix="check_cs_art_")
    try:
        req = np.random.RandomState(1).randn(5, _F)
        reg_a = ModelRegistry(artifact_dir=art_dir)
        entry_a = reg_a.load("m", model_str=model_str)
        n_progs = entry_a.lowlat.warm(_F)
        assert len(os.listdir(art_dir)) == n_progs, \
            "every compiled executable must have exported an artifact"
        ref = entry_a.lowlat(req)

        # replica restart: fresh registry + server over the same store
        reg_b = ModelRegistry(artifact_dir=art_dir)
        reg_b.load("m", model_str=model_str)
        server = ModelServer(reg_b)
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        loads0 = global_metrics.counters.get("serve/aot_loads", 0)

        async def run():
            outs = [await server.predict("m", req, raw_score=True)]
            for rows in (1, 3, 5, 2, 4):  # steady-state mixed smalls
                outs.append(await server.predict("m", req[:rows],
                                                 raw_score=True))
            await server.close()
            return outs

        outs = asyncio.run(run())
        d_compiles = global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0
        d_loads = global_metrics.counters.get("serve/aot_loads",
                                              0) - loads0
        assert d_compiles == 0, \
            (f"artifact-restored server paid {d_compiles} serve/lowlat "
             "compile(s); the whole ladder must come from disk")
        assert d_loads > 0, "restore never touched the artifact store"
        assert np.array_equal(np.squeeze(ref), np.asarray(outs[0])), \
            "restored predictions must be bit-identical"
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)
    print(f"# step 2 OK: restore served first request with 0 compiles "
          f"({d_loads} artifact load(s)), steady state clean, "
          "bit-identical")
    return True


def step3_fingerprint_mismatch() -> None:
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.serve import (ModelRegistry, SERVE_LOWLAT_TAG,
                                    serialize_available)
    from lightgbm_tpu.serve import artifacts as artifacts_mod
    if not serialize_available():
        print("# step 3 SKIPPED: no executable serialization")
        return
    model_str = _model_str()
    art_dir = tempfile.mkdtemp(prefix="check_cs_mismatch_")
    try:
        req = np.random.RandomState(2).randn(4, _F)
        reg_a = ModelRegistry(artifact_dir=art_dir)
        entry_a = reg_a.load("m", model_str=model_str)
        entry_a.lowlat.warm(_F)
        ref = entry_a.lowlat(req)

        # a "new jaxlib" replica: every stored fingerprint now foreign
        orig = artifacts_mod.ARTIFACT_VERSION
        artifacts_mod.ARTIFACT_VERSION = orig + 1
        try:
            reg_b = ModelRegistry(artifact_dir=art_dir)
            entry_b = reg_b.load("m", model_str=model_str)
            c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
            entry_b.lowlat.warm(_F)
            d = global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0
            assert d > 0, "mismatched fingerprints must recompile"
            out = entry_b.lowlat(req)
            assert np.array_equal(ref, out), \
                "fallback recompile must stay bit-identical"
        finally:
            artifacts_mod.ARTIFACT_VERSION = orig
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)
    print(f"# step 3 OK: foreign fingerprint fell back to {d} "
          "recompile(s), bit-identical either way")


def main() -> int:
    step1_second_process_warm_start()
    ran2 = step2_artifact_restore()
    step3_fingerprint_mismatch()
    n = 3 if ran2 else 1
    print(f"# coldstart validator OK ({n}/3 steps ran; skips are "
          "capability-gated)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
