"""Device (XLA) batch prediction over packed tree ensembles.

TPU-native analog of the reference prediction kernels
(ref: src/boosting/gbdt_prediction.cpp:16, CUDATree prediction kernels in
src/io/cuda/cuda_tree.cu). Trees are packed into dense [T, ...] tensors;
traversal is a `fori_loop` over depth with per-row gathers — all rows
advance one level per step (leaves self-loop), so the program has static
shape and vectorizes over the batch.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

_DEFAULT_LEFT_MASK = 2


class PackedEnsemble(NamedTuple):
    """Dense ensemble tensors. T trees, I = max internal nodes, L = max
    leaves, D = max depth. Child convention: >=0 internal, <0 = ~leaf."""
    split_feature: jax.Array   # [T, I] int32
    threshold: jax.Array       # [T, I] f32 (real-valued)
    decision_type: jax.Array   # [T, I] int32
    left_child: jax.Array      # [T, I] int32
    right_child: jax.Array     # [T, I] int32
    leaf_value: jax.Array      # [T, L] f32
    num_internal: jax.Array    # [T] int32
    max_depth: int             # static
    num_trees_per_class: int   # static (for multiclass reshape)


def pack_ensemble(trees: List, num_tree_per_iteration: int = 1
                  ) -> PackedEnsemble:
    """Pack host Tree objects (tree.py) into device tensors.

    Categorical splits are packed as equality splits on the single category
    value (the learner emits one-hot categorical splits)."""
    t = len(trees)
    max_i = max((tr.num_internal for tr in trees), default=0)
    max_i = max(max_i, 1)
    max_l = max((tr.num_leaves for tr in trees), default=1)
    sf = np.zeros((t, max_i), np.int32)
    th = np.zeros((t, max_i), np.float64)
    dt = np.zeros((t, max_i), np.int32)
    lc = np.full((t, max_i), -1, np.int32)
    rc = np.full((t, max_i), -1, np.int32)
    lv = np.zeros((t, max_l), np.float32)
    ni = np.zeros(t, np.int32)
    depth = 1
    for i, tr in enumerate(trees):
        n = tr.num_internal
        ni[i] = n
        if n:
            sf[i, :n] = tr.split_feature
            dt[i, :n] = tr.decision_type
            lc[i, :n] = tr.left_child
            rc[i, :n] = tr.right_child
            # categorical one-hot: threshold holds the category value and a
            # flag bit; decision becomes (value == threshold)
            for nd in range(n):
                if tr.decision_type[nd] & 1:
                    cat_idx = int(tr.threshold[nd])
                    lo = tr.cat_boundaries[cat_idx]
                    hi = tr.cat_boundaries[cat_idx + 1]
                    val = -1.0
                    for w in range(lo, hi):
                        bits = tr.cat_threshold[w]
                        for b in range(32):
                            if (bits >> b) & 1:
                                val = (w - lo) * 32 + b
                    th[i, nd] = val
                else:
                    th[i, nd] = tr.threshold[nd]
        lv[i, :tr.num_leaves] = tr.leaf_value
        depth = max(depth, _tree_depth(tr))
    return PackedEnsemble(
        split_feature=jnp.asarray(sf), threshold=jnp.asarray(th, jnp.float32),
        decision_type=jnp.asarray(dt), left_child=jnp.asarray(lc),
        right_child=jnp.asarray(rc), leaf_value=jnp.asarray(lv),
        num_internal=jnp.asarray(ni), max_depth=int(depth),
        num_trees_per_class=num_tree_per_iteration)


def _tree_depth(tr) -> int:
    if tr.num_internal == 0:
        return 1
    depth = np.zeros(tr.num_internal, np.int32)
    out = 1
    for nd in range(tr.num_internal):  # parents precede children
        for child in (tr.left_child[nd], tr.right_child[nd]):
            if child >= 0:
                depth[child] = depth[nd] + 1
                out = max(out, int(depth[child]) + 1)
    return out + 1


def predict_raw(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """x: [B, F] raw features (NaN = missing) -> raw scores [B, K]."""
    num_rows = x.shape[0]

    def one_tree(carry, tree):
        sf, th, dt, lc, rc, lv, ni = tree

        def body(_, node):
            feat = sf[jnp.maximum(node, 0)]
            val = jnp.take_along_axis(x, feat[:, None], axis=1)[:, 0]
            thr = th[jnp.maximum(node, 0)]
            d = dt[jnp.maximum(node, 0)]
            default_left = (d & _DEFAULT_LEFT_MASK) > 0
            missing_type = (d >> 2) & 3
            is_cat = (d & 1) > 0
            isnan = jnp.isnan(val)
            v0 = jnp.where(isnan, 0.0, val)
            go_left = jnp.where(is_cat, v0 == thr, v0 <= thr)
            use_default = (isnan & (missing_type == 2)) | \
                ((missing_type == 1) & (isnan | (jnp.abs(v0) <= 1e-35)))
            go_left = jnp.where(use_default & ~is_cat, default_left, go_left)
            nxt = jnp.where(go_left, lc[jnp.maximum(node, 0)],
                            rc[jnp.maximum(node, 0)])
            # leaves (node < 0) self-loop
            return jnp.where(node < 0, node, nxt)

        node0 = jnp.where(ni > 0, jnp.zeros(num_rows, jnp.int32),
                          jnp.full(num_rows, -1, jnp.int32))
        node = lax.fori_loop(0, ens.max_depth, body, node0)
        leaf = jnp.where(node < 0, ~node, 0)
        return carry + lv[leaf], None

    total, _ = lax.scan(
        one_tree, jnp.zeros(num_rows, jnp.float32),
        (ens.split_feature, ens.threshold, ens.decision_type,
         ens.left_child, ens.right_child, ens.leaf_value, ens.num_internal))
    return total


def predict_raw_multiclass(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """-> [B, K] for K = num_trees_per_class class streams."""
    k = ens.num_trees_per_class
    num_rows = x.shape[0]
    if k == 1:
        return predict_raw(ens, x)[:, None]
    t = ens.split_feature.shape[0]
    outs = []
    for ki in range(k):
        idx = jnp.arange(ki, t, k)
        sub = PackedEnsemble(
            split_feature=ens.split_feature[idx],
            threshold=ens.threshold[idx],
            decision_type=ens.decision_type[idx],
            left_child=ens.left_child[idx],
            right_child=ens.right_child[idx],
            leaf_value=ens.leaf_value[idx],
            num_internal=ens.num_internal[idx],
            max_depth=ens.max_depth, num_trees_per_class=1)
        outs.append(predict_raw(sub, x))
    return jnp.stack(outs, axis=1)
