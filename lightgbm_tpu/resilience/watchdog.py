"""Distributed-training watchdog: a deadline on peer liveness.

Multi-process training has one failure mode no local try/except can
see: a peer host hangs (kernel wedge, preempted VM, dead NIC) and every
subsequent collective stalls with it — forever, because XLA collectives
have no timeout. The watchdog turns that infinite stall into a bounded,
structured failure:

- every iteration boundary, :meth:`Watchdog.beat` runs a tiny heartbeat
  allgather (the obs/health straggler plumbing: `process_allgather` of a
  few floats) on a **daemon worker thread**;
- the main thread waits at most ``tpu_watchdog_deadline_s``; if the
  collective has not completed by then, a peer is hung or dead, and the
  beat raises :class:`~.errors.PeerLostError` instead of joining the
  stall;
- engine.train escalates: flight-recorder postmortem, checkpoint,
  ``SystemExit(EXIT_PREEMPTED)`` — the same exit-75 contract as a
  SIGTERM preemption, so a supervisor re-runs the survivors and
  PR-9's elastic resume restores onto the shrunk mesh.

The heartbeat payload carries each host's previous beat round-trip
time, so a completed beat doubles as a straggler probe: the gathered
RTT matrix goes through ``HealthRegistry.straggler_from_matrix`` and a
peer that is slowing down is visible in the skew stats before it is
declared lost. Single-process runs keep the full deadline machinery
(the chaos tests drive it with the ``hang_peer_at_iter`` fault) — the
heartbeat just has no peers to gather from.

Worker threads are daemons on purpose: a beat that never completes must
not keep the escalating process alive.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from .errors import PeerLostError


class Watchdog:
    """Per-iteration heartbeat with a hard deadline.

    One instance per training run. Not thread-safe across concurrent
    ``beat`` calls (the training loop is the only caller); internal
    state shared with worker threads is published via the per-beat
    result dict under the beat lock.
    """

    def __init__(self, deadline_s: float, name: str = "train"):
        self.deadline_s = float(deadline_s)
        self.name = str(name)
        self.beats = 0
        self.misses = 0
        self.last_rtt_s = 0.0
        self.worst_rtt_s = 0.0
        self.last_skew: Optional[Dict[str, Any]] = None
        self._closed = False

    # ------------------------------------------------------------------
    def _heartbeat(self, iteration: int, out: Dict[str, Any]) -> None:
        """The watched work. Runs on a daemon worker thread so a hang
        here (real peer loss, or the injected ``hang_peer_at_iter``
        fault) stalls the worker, never the training loop."""
        from .faults import global_faults
        if global_faults.armed:
            global_faults.maybe_hang_peer(iteration)
        t0 = time.perf_counter()
        try:
            import jax
            if jax.process_count() > 1:
                import numpy as np
                from jax.experimental import multihost_utils as mh
                # payload: [rank, iteration, previous beat's rtt]. The
                # gather itself is the liveness proof; the rtt column
                # feeds the straggler stats so a slowing peer shows up
                # before it is declared lost.
                payload = np.asarray(
                    [float(jax.process_index()), float(iteration),
                     float(self.last_rtt_s)], np.float64)
                gathered = np.asarray(mh.process_allgather(payload))
                out["n_peers"] = int(gathered.shape[0])
                iters = gathered[:, 1]
                if float(iters.min()) != float(iters.max()):
                    out["desync"] = {"min_iter": int(iters.min()),
                                     "max_iter": int(iters.max())}
                if self.beats > 1:  # first beat has no prior rtt
                    from ..obs.health import HealthRegistry
                    out["skew"] = HealthRegistry.straggler_from_matrix(
                        ["heartbeat"], gathered[:, 2:3])
            else:
                out["n_peers"] = 1
        except Exception as exc:
            # a gather that ERRORS (vs hangs) is still a completed beat:
            # the runtime answered. Note it; the deadline machinery is
            # for silence, not for loud failures.
            out["error"] = f"{type(exc).__name__}: {exc}"
        out["rtt_s"] = time.perf_counter() - t0
        out["ok"] = True

    # ------------------------------------------------------------------
    def beat(self, iteration: int) -> Dict[str, Any]:
        """Run one heartbeat; raise :class:`PeerLostError` if it does
        not complete within ``deadline_s``. Returns the beat stats on
        success ({"rtt_s": ..., "n_peers": ..., optional "skew"})."""
        if self._closed:
            return {"ok": False, "closed": True}
        self.beats += 1
        out: Dict[str, Any] = {}
        done = threading.Event()

        def _run() -> None:
            try:
                self._heartbeat(iteration, out)
            finally:
                done.set()

        worker = threading.Thread(
            target=_run, name=f"lgbmtpu-watchdog-{self.name}", daemon=True)
        worker.start()
        if not done.wait(self.deadline_s):
            self.misses += 1
            self._escalate(iteration)
        if "rtt_s" in out:
            self.last_rtt_s = float(out["rtt_s"])
            self.worst_rtt_s = max(self.worst_rtt_s, self.last_rtt_s)
        if out.get("skew") is not None:
            self.last_skew = out["skew"]
        from ..obs.metrics import global_metrics
        global_metrics.inc_counter("resilience/watchdog_beats")
        return out

    def _escalate(self, iteration: int) -> None:
        """Deadline expired: postmortem, then the structured error.
        The hung worker thread is abandoned (daemon) — there is no safe
        way to interrupt a thread stuck inside a collective."""
        from ..obs.metrics import global_metrics
        global_metrics.inc_counter("resilience/watchdog_beats")
        global_metrics.inc_counter("resilience/watchdog_misses")
        from ..obs.flightrec import global_flightrec
        if global_flightrec.armed:
            global_flightrec.record(
                "watchdog_heartbeat_miss", iteration=iteration,
                deadline_s=self.deadline_s, beats=self.beats,
                misses=self.misses, last_rtt_s=self.last_rtt_s)
            global_flightrec.maybe_dump(reason="watchdog_heartbeat_miss")
        raise PeerLostError(
            f"heartbeat collective did not complete within "
            f"{self.deadline_s:g}s at iteration {iteration} — a peer "
            f"process is hung or dead; escalating to checkpoint + "
            f"preemption exit so the survivors elastic-resume",
            deadline_s=self.deadline_s, iteration=iteration,
            phase="heartbeat")

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {"beats": self.beats, "misses": self.misses,
                "deadline_s": self.deadline_s,
                "last_rtt_s": round(self.last_rtt_s, 6),
                "worst_rtt_s": round(self.worst_rtt_s, 6),
                "skew": self.last_skew}

    def close(self) -> None:
        """Stop issuing beats. Hung workers (daemons) are abandoned."""
        self._closed = True


def from_config(cfg) -> Optional[Watchdog]:
    """Build the training watchdog when ``tpu_watchdog_deadline_s`` is
    set; None (no per-iteration overhead at all) otherwise."""
    deadline = float(getattr(cfg, "tpu_watchdog_deadline_s", 0.0) or 0.0)
    if deadline <= 0:
        return None
    return Watchdog(deadline)
