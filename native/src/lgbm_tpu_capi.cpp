// C-ABI compatibility shim: a subset of the reference's `LGBM_*` surface
// (ref: include/LightGBM/c_api.h, 131 functions; this shim covers the 19
// that dataset/booster lifecycle harnesses use, incl. dense + CSR
// creation and prediction) backed by the lightgbm_tpu Python framework
// through an embedded CPython interpreter.
//
// Design: every entry point forwards to lightgbm_tpu.capi with raw
// pointers passed as integers; that module wraps them with ctypes/NumPy
// and drives the ordinary Python API. Handles returned to C callers are
// small registry integers cast to opaque pointers — the same contract as
// the reference's DatasetHandle/BoosterHandle (c_api.h:28-34).
//
// The reference guards its Booster with shared/unique locks
// (c_api.cpp:170); here the GIL serves the same role: every call takes
// PyGILState_Ensure, so concurrent callers serialize safely.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

#define LGBM_API extern "C" __attribute__((visibility("default")))

typedef void* DatasetHandle;
typedef void* BoosterHandle;

static thread_local std::string g_last_error = "everything is fine";
static PyObject* g_capi_module = nullptr;
static std::once_flag g_py_once;

LGBM_API const char* LGBM_GetLastError() { return g_last_error.c_str(); }

namespace {

void EnsureInterpreter() {
  std::call_once(g_py_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // Release the GIL taken by Py_Initialize so PyGILState_Ensure
      // works uniformly from every (including this) thread.
      PyEval_SaveThread();
    }
  });
}

// RAII GIL + lazy import of lightgbm_tpu.capi.
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }
  Gil(const Gil&) = delete;
  Gil& operator=(const Gil&) = delete;

 private:
  PyGILState_STATE state_;
};

PyObject* CapiModule() {
  if (g_capi_module == nullptr) {
    g_capi_module = PyImport_ImportModule("lightgbm_tpu.capi");
  }
  return g_capi_module;
}

std::string FetchPyError() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

// Call lightgbm_tpu.capi.<fn>(args...) and return the result (new ref),
// or nullptr with g_last_error set.
PyObject* Call(const char* fn, const char* fmt, ...) {
  PyObject* mod = CapiModule();
  if (mod == nullptr) {
    g_last_error = "failed to import lightgbm_tpu.capi: " + FetchPyError();
    return nullptr;
  }
  PyObject* func = PyObject_GetAttrString(mod, fn);
  if (func == nullptr) {
    PyErr_Clear();  // a pending exception would poison later calls
    g_last_error = std::string("missing capi function ") + fn;
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  PyObject* result = nullptr;
  if (args != nullptr) {
    result = PyObject_CallObject(func, args);
    Py_DECREF(args);
  }
  Py_DECREF(func);
  if (result == nullptr) {
    g_last_error = std::string(fn) + ": " + FetchPyError();
    return nullptr;
  }
  return result;
}

int HandleResult(PyObject* r) {
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int64_t AsHandleInt(void* h) { return reinterpret_cast<intptr_t>(h); }

}  // namespace

// -- dataset ---------------------------------------------------------------

LGBM_API int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                                       int32_t nrow, int32_t ncol,
                                       int is_row_major,
                                       const char* parameters,
                                       const DatasetHandle reference,
                                       DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_mat", "(LiiiisL)",
                     (long long)(intptr_t)data, data_type, (int)nrow,
                     (int)ncol, is_row_major, parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col,
                                       const char* parameters,
                                       const DatasetHandle reference,
                                       DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_csr", "(LiLLiLLLsL)",
                     (long long)(intptr_t)indptr, indptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)nindptr, (long long)nelem,
                     (long long)num_col, parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetCreateFromFile(const char* filename,
                                        const char* parameters,
                                        const DatasetHandle reference,
                                        DatasetHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_create_from_file", "(ssL)", filename,
                     parameters ? parameters : "",
                     (long long)AsHandleInt(reference));
  if (r == nullptr) return -1;
  *out = reinterpret_cast<DatasetHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetSetField(DatasetHandle handle,
                                  const char* field_name,
                                  const void* field_data, int num_element,
                                  int type) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("dataset_set_field", "(LsLii)",
                           (long long)AsHandleInt(handle), field_name,
                           (long long)(intptr_t)field_data, num_element,
                           type));
}

LGBM_API int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_num_data", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("dataset_num_feature", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_DatasetFree(DatasetHandle handle) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("handle_free", "(L)",
                           (long long)AsHandleInt(handle)));
}

// -- booster ---------------------------------------------------------------

LGBM_API int LGBM_BoosterCreate(const DatasetHandle train_data,
                                const char* parameters,
                                BoosterHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_create", "(Ls)",
                     (long long)AsHandleInt(train_data),
                     parameters ? parameters : "");
  if (r == nullptr) return -1;
  *out = reinterpret_cast<BoosterHandle>((intptr_t)PyLong_AsLongLong(r));
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterCreateFromModelfile(const char* filename,
                                             int* out_num_iterations,
                                             BoosterHandle* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_create_from_modelfile", "(s)", filename);
  if (r == nullptr) return -1;
  long long handle = 0;
  int iters = 0;
  if (!PyArg_ParseTuple(r, "Li", &handle, &iters)) {
    PyErr_Clear();  // a pending exception would poison later calls
    Py_DECREF(r);
    g_last_error = "bad tuple from booster_create_from_modelfile";
    return -1;
  }
  Py_DECREF(r);
  *out = reinterpret_cast<BoosterHandle>((intptr_t)handle);
  *out_num_iterations = iters;
  return 0;
}

LGBM_API int LGBM_BoosterAddValidData(BoosterHandle handle,
                                      const DatasetHandle valid_data) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_add_valid_data", "(LL)",
                           (long long)AsHandleInt(handle),
                           (long long)AsHandleInt(valid_data)));
}

LGBM_API int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                       int* is_finished) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_update_one_iter", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *is_finished = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                             int* out_iteration) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_current_iteration", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out_iteration = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_eval_counts", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out_len = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx,
                                 int* out_len, double* out_results) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_get_eval", "(LiL)",
                     (long long)AsHandleInt(handle), data_idx,
                     (long long)(intptr_t)out_results);
  if (r == nullptr) return -1;
  *out_len = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForMat(BoosterHandle handle,
                                       const void* data, int data_type,
                                       int32_t nrow, int32_t ncol,
                                       int is_row_major, int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_mat", "(LLiiiiiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)data, data_type, (int)nrow,
                     (int)ncol, is_row_major, predict_type,
                     start_iteration, num_iteration,
                     (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterPredictForCSR(BoosterHandle handle,
                                       const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col, int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result) {
  (void)parameter;
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_predict_for_csr", "(LLiLLiLLLiiiL)",
                     (long long)AsHandleInt(handle),
                     (long long)(intptr_t)indptr, indptr_type,
                     (long long)(intptr_t)indices,
                     (long long)(intptr_t)data, data_type,
                     (long long)nindptr, (long long)nelem,
                     (long long)num_col, predict_type, start_iteration,
                     num_iteration, (long long)(intptr_t)out_result);
  if (r == nullptr) return -1;
  *out_len = (int64_t)PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterSaveModel(BoosterHandle handle,
                                   int start_iteration, int num_iteration,
                                   int feature_importance_type,
                                   const char* filename) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("booster_save_model", "(Liiis)",
                           (long long)AsHandleInt(handle), start_iteration,
                           num_iteration, feature_importance_type,
                           filename));
}

LGBM_API int LGBM_BoosterSaveModelToString(BoosterHandle handle,
                                           int start_iteration,
                                           int num_iteration,
                                           int feature_importance_type,
                                           int64_t buffer_len,
                                           int64_t* out_len,
                                           char* out_str) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_save_model_to_string", "(Liii)",
                     (long long)AsHandleInt(handle), start_iteration,
                     num_iteration, feature_importance_type);
  if (r == nullptr) return -1;
  Py_ssize_t size = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &size);
  if (s == nullptr) {
    Py_DECREF(r);
    g_last_error = "model string encode failed";
    return -1;
  }
  *out_len = (int64_t)size + 1;  // including trailing '\0', like the ref
  if (buffer_len >= size + 1) {
    std::memcpy(out_str, s, size + 1);
  }
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out) {
  EnsureInterpreter();
  Gil gil;
  PyObject* r = Call("booster_num_feature", "(L)",
                     (long long)AsHandleInt(handle));
  if (r == nullptr) return -1;
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

LGBM_API int LGBM_BoosterFree(BoosterHandle handle) {
  EnsureInterpreter();
  Gil gil;
  return HandleResult(Call("handle_free", "(L)",
                           (long long)AsHandleInt(handle)));
}
