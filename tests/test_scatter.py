"""Reduce-scatter histogram aggregation (tpu_hist_reduce=scatter).

The scatter mode must be BIT-IDENTICAL to the full-histogram psum
oracle (ref: data_parallel_tree_learner.cpp:287-297 ReduceScatter +
feature-subset search + one-SplitInfo Allgather): same models byte for
byte via model_to_string, while moving ~1/W of the histogram bytes per
collective. Satellite: non-divisible row counts now pad + shard rather
than degrade to replicated storage (boosting._pad_tail guards keep the
padded tail inert through bagging/GOSS/quantization)."""

import os
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc
from lightgbm_tpu.learner import (_sharded_pallas_build,
                                  _sharded_pallas_multi,
                                  collective_traffic_model)
from lightgbm_tpu.obs.health import global_health
from lightgbm_tpu.ops import histogram as hist_ops
from lightgbm_tpu.ops.split import split_info_nbytes
from lightgbm_tpu.parallel import mesh as mesh_lib
from lightgbm_tpu.parallel.scatter import resolve_hist_reduce
from tests.conftest import make_binary, make_regression


@pytest.fixture(autouse=True)
def _require_multi_device():
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device (XLA_FLAGS host platform count)")


def _train(params, X, y, rounds=3):
    return lgb.train({"verbosity": -1, **params}, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


def _model_str(bst):
    # the A/B knob itself is echoed in the params section; everything
    # else (trees, feature infos, leaf values) must be byte-identical
    return "\n".join(l for l in bst.model_to_string().splitlines()
                     if not l.startswith("[tpu_hist_reduce:"))


def _models_equal(pa, pb, X, y, rounds=3):
    a = _train(pa, X, y, rounds)
    b = _train(pb, X, y, rounds)
    return _model_str(a) == _model_str(b)


# ---------------------------------------------------------------------------
# knob resolution + analytic byte model (pure logic)

def test_resolve_hist_reduce():
    m8 = mesh_lib.get_mesh(8)
    m1 = mesh_lib.get_mesh(1)
    assert resolve_hist_reduce("auto", None, 8) == "psum"
    assert resolve_hist_reduce("auto", m1, 8) == "psum"
    assert resolve_hist_reduce("scatter", m1, 8) == "psum"
    assert resolve_hist_reduce("auto", m8, 8) == "scatter"
    assert resolve_hist_reduce("auto", m8, 13) == "psum"  # uneven
    assert resolve_hist_reduce("auto", m8, 13, pad_ok=True) == "scatter"
    assert resolve_hist_reduce("psum", m8, 8) == "psum"
    assert resolve_hist_reduce("scatter", m8, 13) == "scatter"  # pads
    with pytest.raises(ValueError):
        resolve_hist_reduce("ring", m8, 8)


def test_collective_traffic_model_reduction():
    """Modeled bytes/iter at the perf-gate fixture shape: scatter must
    cut >= 1.8x at W=4 and keep improving with width."""
    kw = dict(num_features=28, max_bins=15, num_leaves=255, wave_max=42)
    ratios, hist_ratios = {}, {}
    for w in (4, 16, 64):
        psum = collective_traffic_model(width=w, reduction="psum", **kw)
        scat = collective_traffic_model(width=w, reduction="scatter", **kw)
        assert scat["split_collective_bytes_per_iter"] > 0
        ratios[w] = (psum["collective_bytes_per_iter"]
                     / scat["collective_bytes_per_iter"])
        # the histogram collective itself shrinks exactly W-fold
        # (modulo feature-axis padding to a multiple of W)
        hist_ratios[w] = (psum["hist_collective_bytes_per_iter"]
                          / scat["hist_collective_bytes_per_iter"])
        assert hist_ratios[w] == pytest.approx(
            w * kw["num_features"] / scat["padded_features"])
    assert ratios[4] >= 1.8
    assert ratios[16] >= 1.8
    assert hist_ratios[64] > hist_ratios[16] > hist_ratios[4]
    # the O(W * SplitInfo) winner exchange eventually dominates on this
    # SMALL feature set — the model must show the crossover, not hide it
    assert ratios[64] < ratios[16] < ratios[4]
    # hierarchical: the DCN hop ships the owned slice once more
    hier = collective_traffic_model(width=4, dcn=4, reduction="scatter",
                                    **kw)
    flat = collective_traffic_model(width=4, reduction="scatter", **kw)
    assert hier["dcn_bytes_per_iter"] == flat["hist_collective_bytes_per_iter"]
    assert hier["collective_bytes_per_iter"] > flat[
        "collective_bytes_per_iter"]


def test_split_info_nbytes():
    # 11 scalar f32/i32 fields + default_left byte + cat_mask[max_bins]
    assert split_info_nbytes(63) == 11 * 4 + 1 + 63


# ---------------------------------------------------------------------------
# bit-parity: scatter vs the psum oracle, whole-model comparison

PARITY_CASES = {
    "plain-w8": {"objective": "regression", "num_leaves": 15,
                 "min_data_in_leaf": 5, "tree_learner": "data"},
    "plain-w4": {"objective": "regression", "num_leaves": 15,
                 "min_data_in_leaf": 5, "tree_learner": "data",
                 "tpu_num_shards": 4},
    "bagging-w2": {"objective": "binary", "num_leaves": 15,
                   "tree_learner": "data", "tpu_num_shards": 2,
                   "bagging_fraction": 0.7, "bagging_freq": 1, "seed": 5},
    "exact-grower-w8": {"objective": "binary", "num_leaves": 7,
                        "tree_learner": "data", "tpu_wave_max": 0},
    "quant-int8-w8": {"objective": "binary", "num_leaves": 15,
                      "tree_learner": "data", "use_quantized_grad": True},
    "voting-w8": {"objective": "binary", "num_leaves": 15,
                  "tree_learner": "voting", "top_k": 2},
    "feature-w8": {"objective": "regression", "num_leaves": 15,
                   "tree_learner": "feature", "tpu_wave_max": 0},
}


@pytest.mark.parametrize("case", sorted(PARITY_CASES))
def test_scatter_bit_parity(case):
    params = PARITY_CASES[case]
    make = make_binary if params["objective"] == "binary" else \
        make_regression
    X, y = make(512)
    assert _models_equal({**params, "tpu_hist_reduce": "psum"},
                         {**params, "tpu_hist_reduce": "scatter"}, X, y), \
        f"{case}: scatter model differs from the psum oracle"


def test_scatter_bit_parity_uneven_features():
    """F=13 over 8 shards: explicit scatter zero-pads the feature axis to
    16 and must still reproduce the oracle byte for byte."""
    X, y = make_binary(512, 13)
    params = {"objective": "binary", "num_leaves": 15,
              "tree_learner": "data"}
    assert _models_equal({**params, "tpu_hist_reduce": "psum"},
                         {**params, "tpu_hist_reduce": "scatter"}, X, y)
    # auto demotes the uneven count to psum instead of padding
    bst = lgb.Booster({**params, "verbosity": -1},
                      lgb.Dataset(X, label=y))
    assert bst._gbdt._hist_reduce == "psum"


def test_scatter_single_shard_degrades_to_psum():
    X, y = make_binary(256)
    bst = _train({"objective": "binary", "num_leaves": 7,
                  "tree_learner": "data", "tpu_num_shards": 1,
                  "tpu_hist_reduce": "scatter"}, X, y, rounds=2)
    assert bst.num_trees() == 2


# ---------------------------------------------------------------------------
# runtime collective counters: the wire payload actually shrinks

def _runtime_snapshot():
    return {t: dict(e) for t, e in global_health.runtime.items()}


def test_scatter_runtime_counters_data_learner():
    """The scatter program's histogram collective must carry exactly 1/W
    of the psum oracle's bytes, and the winner exchange must be
    O(W * sizeof(SplitInfo)) per record — not O(L * F * B)."""
    X, y = make_regression(512)
    # pallas impl: the psum oracle then also routes through the
    # instrumented shard_map builder (the GSPMD xla path's collectives
    # are partitioner-inserted and carry no runtime counters)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "tree_learner": "data",
              "tpu_hist_impl": "pallas"}
    global_health.reset()
    global_health.enable()
    try:
        _train({**params, "tpu_hist_reduce": "psum"}, X, y)
        psum_rt = _runtime_snapshot()
        global_health.reset()
        _train({**params, "tpu_hist_reduce": "scatter"}, X, y)
        scat_rt = _runtime_snapshot()
    finally:
        global_health.disable()
        global_health.reset()
    assert "hist/psum_wave" in psum_rt and "hist/psum_scatter" not in psum_rt
    assert "hist/psum_scatter" in scat_rt and "hist/psum_wave" not in scat_rt
    assert "split/allgather_best" in scat_rt
    pw, sc = psum_rt["hist/psum_wave"], scat_rt["hist/psum_scatter"]
    assert sc["op"] == "psum_scatter"
    # same wave schedule on both sides -> same issue count, W-fold bytes
    assert sc["calls"] == pw["calls"]
    assert sc["bytes"] * 8 == pw["bytes"]
    # winner exchange: O(W * sizeof(SplitInfo)) per searched record —
    # the analytic model and the runtime counter must agree exactly
    ag = scat_rt["split/allgather_best"]
    bst = lgb.Booster({**params, "verbosity": -1}, lgb.Dataset(X, label=y))
    shape = bst._gbdt._resolved_hist_shape()
    model = collective_traffic_model(
        num_features=8, max_bins=shape["max_bins"], num_leaves=15,
        wave_max=shape["wave_max"], width=8, reduction="scatter")
    assert ag["bytes"] == 3 * model["split_collective_bytes_per_iter"]
    # net win even at this tiny 1-feature-per-shard fixture: the winner
    # exchange rides on top of the 1/W hist slice but the total still
    # undercuts the full-histogram psum
    assert ag["bytes"] + sc["bytes"] < pw["bytes"]


def test_scatter_runtime_counters_voting():
    """Voting + scatter: the candidate-axis ReduceScatter replaces the
    candidate psum, and each winner combine gathers one SplitInfo per
    shard."""
    X, y = make_binary(512)
    params = {"objective": "binary", "num_leaves": 7,
              "tree_learner": "voting", "top_k": 2}
    global_health.reset()
    global_health.enable()
    try:
        _train({**params, "tpu_hist_reduce": "psum"}, X, y, rounds=2)
        psum_rt = _runtime_snapshot()
        global_health.reset()
        _train({**params, "tpu_hist_reduce": "scatter"}, X, y, rounds=2)
        scat_rt = _runtime_snapshot()
    finally:
        global_health.disable()
        global_health.reset()
    assert "vote/psum_hist" in psum_rt
    assert "vote/psum_hist" not in scat_rt
    assert scat_rt["hist/psum_scatter"]["bytes"] < \
        psum_rt["vote/psum_hist"]["bytes"]
    ag = scat_rt["split/allgather_best"]
    bst = lgb.Booster({**params, "verbosity": -1}, lgb.Dataset(X, label=y))
    max_bins = bst._gbdt._static["max_bins"]
    # gathered payload per issue: one SplitInfo from each of 8 shards
    assert ag["bytes"] == ag["calls"] * 8 * split_info_nbytes(max_bins)


# ---------------------------------------------------------------------------
# satellite: non-divisible rows pad + shard instead of replicating

def test_row_pad_keeps_rows_sharded():
    """N=1003 over 8 shards used to fall back to fully replicated row
    tensors; now the storage pads to 1008 and stays sharded."""
    X, y = make_regression(1003)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 7}
    with pytest.warns(UserWarning, match="padding row tensors"):
        bst = lgb.Booster({**params, "tree_learner": "data"},
                          lgb.Dataset(X, label=y))
    g = bst._gbdt
    assert g._row_pad == 5
    assert g.num_data == 1003
    assert g.bins_fm.shape[1] == 1008
    assert g.scores.shape[1] == 1008
    assert g.bins_fm.sharding.spec[1] is not None  # rows still sharded
    assert g._sample_mask.shape[0] == 1008
    for _ in range(8):
        bst.update()
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    np.testing.assert_allclose(bst.predict(X), serial.predict(X),
                               rtol=1e-3, atol=1e-3)
    # train-side score egress drops the padded tail
    ev = bst.eval_train()
    assert ev and np.isfinite(ev[0][2])


def test_row_pad_bagging_matches_serial():
    """Padded tail through the bagging draw: u pads with 2.0 (never
    sampled) and the real-row draws keep their bits."""
    X, y = make_regression(1003)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 11,
              "bagging_fraction": 0.8, "bagging_freq": 1}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    par = lgb.train({**params, "tree_learner": "data"},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    np.testing.assert_allclose(par.predict(X), serial.predict(X),
                               rtol=1e-3, atol=1e-3)


def test_row_pad_goss_quality():
    """GOSS over padded storage: the tail scores -1 (never top-k) and its
    keep-draw is 2.0 (never kept)."""
    X, y = make_binary(1003)
    bst = _train({"objective": "binary", "num_leaves": 15,
                  "tree_learner": "data",
                  "data_sample_strategy": "goss"}, X, y, rounds=10)
    assert bst._gbdt._row_pad == 5
    assert _auc(y, bst.predict(X)) > 0.9


# ---------------------------------------------------------------------------
# hierarchical ("dcn", "ici") reduction

def test_hierarchical_mesh_shapes():
    hm = mesh_lib.get_hierarchical_mesh(jax.devices(), num_groups=2)
    assert hm.axis_names == ("dcn", "ici")
    assert hm.shape["dcn"] == 2 and hm.shape["ici"] == 4
    with pytest.raises(ValueError):
        mesh_lib.get_hierarchical_mesh(jax.devices()[:6], num_groups=4)


def test_hierarchical_int8_scatter_exact():
    """2x4 mesh, int32 quantized histograms: ICI reduce-scatter + DCN
    psum of the owned slice must be EXACTLY the single-device integer
    result (integer accumulation commutes)."""
    from lightgbm_tpu.ops.pallas_histogram import hist_multi_int8_xla

    r = np.random.RandomState(4)
    n, f, b, slots = 1003, 8, 15, 8
    bins = jnp.asarray(r.randint(0, b, (f, n)), jnp.uint8)
    mask = (r.rand(n) < 0.8).astype(np.int8)
    ghT_i8 = jnp.asarray(np.stack([(r.randint(-3, 4, n) * mask),
                                   (r.randint(0, 5, n) * mask), mask],
                                  axis=1), jnp.int8)
    row_leaf = jnp.asarray(r.randint(0, slots, n), jnp.int32)
    ids = jnp.asarray(np.arange(slots, dtype=np.int32))
    hm = mesh_lib.get_hierarchical_mesh(jax.devices(), num_groups=2)
    sharded = _sharded_pallas_multi(hm, max_bins=b, precision="highest",
                                    int8=True, impl="xla",
                                    hist_reduce="scatter")
    out = np.asarray(sharded(bins, ghT_i8, row_leaf, ids))
    ref = np.asarray(hist_multi_int8_xla(bins, ghT_i8, row_leaf, ids,
                                         max_bins=b, num_slots=slots))
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, ref)


def test_hierarchical_f32_scatter_close():
    """f32 on the 2x4 mesh: hierarchical regrouping reorders the f32
    sums, so allclose (not bitwise) against the single-device build."""
    r = np.random.RandomState(1)
    n, f, b = 1024, 8, 15
    bins = jnp.asarray(r.randint(0, b, (f, n)), jnp.uint8)
    g = jnp.asarray(r.randn(n), jnp.float32)
    h = jnp.asarray(np.abs(r.randn(n)) + 0.1, jnp.float32)
    m = jnp.asarray((r.rand(n) < 0.8), jnp.float32)
    hm = mesh_lib.get_hierarchical_mesh(jax.devices(), num_groups=2)
    sharded = _sharded_pallas_build(hm, max_bins=b, dtype=jnp.float32,
                                    row_chunk=0, precision="highest",
                                    impl="xla", hist_reduce="scatter")
    out = np.asarray(sharded(bins, g, h, m))
    ref = np.asarray(hist_ops.build_histogram(
        bins, g, h, m, max_bins=b, dtype=jnp.float32, row_chunk=0,
        impl="xla"))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# published byte model

def test_booster_publishes_collective_meta():
    from lightgbm_tpu.obs.metrics import global_metrics

    X, y = make_regression(512)
    lgb.Booster({"objective": "regression", "num_leaves": 15,
                 "tree_learner": "data", "verbosity": -1},
                lgb.Dataset(X, label=y))
    ct = global_metrics.meta.get("collective_traffic")
    assert ct is not None
    assert ct["reduction"] == "scatter"  # auto picks scatter on 8 shards
    assert ct["width"] == 8
    oracle = global_metrics.meta["collective_traffic_psum"]
    assert oracle["reduction"] == "psum"
    red = global_metrics.meta["collective_reduction"]
    # published rounded for the bench JSON line
    assert red == pytest.approx(
        oracle["collective_bytes_per_iter"]
        / ct["collective_bytes_per_iter"], abs=5e-5)
    assert red > 1.8


def test_check_scatter_tool():
    """The standalone CI validator (quick tier, mirrors check_shap)."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import check_scatter
    assert check_scatter.main() == 0
