"""XLA program introspection: per-executable cost analysis, compile
wall-time, and recompile attribution.

PR 4/5 built *analytic* traffic and memory models (trace-time shape
arithmetic); this module captures what XLA itself says about the
programs it actually compiled — ``compiled.cost_analysis()`` (flops,
bytes accessed) and ``compiled.memory_analysis()`` (argument / output /
temp bytes) — so the analytic models can be cross-validated without
silicon (tools/check_perf_gate.py's XLA band) and every recompile is
attributable to a phase and shape bucket instead of a bare counter.

Mechanics: ``instrumented_jit(tag, fn, phase=...)`` replaces the bare
``jax.jit(global_metrics.wrap_traced(tag, fn))`` at a program boundary.

- **Disabled (default):** the wrapper forwards to the jitted callable
  after a single attribute check — the dispatch path, cache behavior
  and cost are exactly the uninstrumented ones.
- **Enabled:** calls route through an explicit AOT cache keyed by the
  abstract signature (treedef + leaf shape/dtype): a miss runs
  ``jitted.lower(...).compile()`` with the compile wall-clock timed,
  records the executable's cost/memory analysis into the global
  introspector, and every hit invokes the compiled executable
  directly. The compile is therefore measured exactly once per
  (tag, shape bucket) — it IS the program's real compile, not a
  duplicate — and tracing still runs through ``wrap_traced``, so the
  existing recompile counters keep counting.

Any lower/compile/AOT-call failure permanently falls the tag back to
the plain jitted path (recorded in ``aot_fallbacks``): introspection
must never take training down.

Enabled via ``LGBM_TPU_XLA_INTROSPECT=1``, ``global_xla.enable()``, or
implicitly with the metrics registry (``LGBM_TPU_TELEMETRY`` / the
telemetry callbacks).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .metrics import global_metrics


def executable_cost(compiled) -> Dict[str, float]:
    """Cost/memory facts of a compiled XLA executable, normalized.

    Returns whichever of ``flops`` / ``bytes_accessed`` (HLO cost
    analysis) and ``argument_bytes`` / ``output_bytes`` / ``temp_bytes``
    (buffer assignment) this backend exposes — an empty dict when it
    exposes neither (the perf-gate band then skips gracefully)."""
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if isinstance(ca.get("flops"), (int, float)):
                out["flops"] = float(ca["flops"])
            if isinstance(ca.get("bytes accessed"), (int, float)):
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for src, dst in (("argument_size_in_bytes", "argument_bytes"),
                         ("output_size_in_bytes", "output_bytes"),
                         ("temp_size_in_bytes", "temp_bytes")):
            v = getattr(ma, src, None)
            if isinstance(v, (int, float)):
                out[dst] = float(v)
    except Exception:
        pass
    return out


def aot_cost_summary(fn: Callable, *args, **kwargs
                     ) -> Optional[Dict[str, float]]:
    """jit → lower → compile `fn` on the given concrete args and return
    its cost dict (``executable_cost`` + ``compile_s``), or None when
    the backend exposes no cost analysis at all — the graceful-skip
    contract check_perf_gate.py's XLA band is built on."""
    import jax
    t0 = time.perf_counter()
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    dt = time.perf_counter() - t0
    cost = executable_cost(compiled)
    if not cost:
        return None
    cost["compile_s"] = dt
    return cost


_cache_hit_count = [0]  # process-wide persistent-compile-cache hits


def _on_monitoring_event(event: str, **kwargs) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _cache_hit_count[0] += 1


def _install_cache_hit_listener() -> bool:
    """Count persistent-compile-cache hits via jax.monitoring so a
    compile that was really a disk-cache LOAD can be attributed as one
    (``cache_load_s`` vs ``compile_s`` — the split bench.py --coldstart
    and perf-gate check 10 are built on). Best-effort: a jax without
    the event just leaves every compile counted as a compile."""
    try:
        import jax.monitoring as monitoring
        monitoring.register_event_listener(_on_monitoring_event)
        return True
    except Exception:
        return False


_install_cache_hit_listener()


def cache_hits() -> int:
    """Persistent-compile-cache hits observed in this process so far."""
    return _cache_hit_count[0]


def _sig_key(args, kwargs):
    """Hashable abstract signature of a call: pytree structure plus
    per-leaf (shape, dtype). Two calls with equal keys compile to the
    same program, so the key doubles as the shape-bucket identity."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = tuple(
        (tuple(getattr(x, "shape", ()) or ()),
         str(getattr(x, "dtype", type(x).__name__)))
        for x in leaves)
    return treedef, sig


def _shape_label(sig_key) -> str:
    """Compact human label for a shape bucket: the distinct non-scalar
    leaf shapes, largest first (enough to tell row buckets apart)."""
    shapes = sorted({s for s, _ in sig_key[1] if s},
                    key=lambda s: -int(__import__("math").prod(s)))
    return ",".join("x".join(map(str, s)) for s in shapes[:4]) or "scalar"


class XlaIntrospector:
    """Global registry of compiled-program facts (see module docstring).

    ``records()`` returns one dict per compiled executable:
    ``{tag, phase, shapes, compile_s, flops?, bytes_accessed?,
    argument_bytes?, output_bytes?, temp_bytes?}``. ``summary()``
    aggregates them into the bench-JSON shape (``compile_s_total``,
    ``n_recompiles_by_phase``, per-tag totals)."""

    def __init__(self) -> None:
        self.enabled = os.environ.get(
            "LGBM_TPU_XLA_INTROSPECT", "") not in ("", "0")
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        self._fallbacks: Dict[str, str] = {}  # tag -> first error

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._fallbacks.clear()

    def cache_hits(self) -> int:
        """Process-wide persistent-compile-cache hit count (module
        counter; here so boundary code holding the registry can diff
        it around a compile)."""
        return _cache_hit_count[0]

    # ------------------------------------------------------------------
    def note_compile(self, tag: str, phase: Optional[str], sig_label: str,
                     compile_s: float, compiled,
                     trace_s: float = 0.0,
                     cache_hit: bool = False) -> None:
        """Record one real compile of `tag` (the lowlat AOT path calls
        this directly — it already owns its lower/compile).

        `compile_s` is the BACKEND compile wall time (the
        ``lowered.compile()`` step); `trace_s` is the trace/lower time
        that precedes it (pure Python+jaxpr work no cache can skip).
        `cache_hit` marks a "compile" the persistent compilation cache
        actually served from disk — its wall time is attributed to
        ``cache_load_s_total`` instead of ``compile_s_total``, because a
        warm process LOADS, it does not compile. The split is what
        makes warm start measurable: a cache-warm rerun shows
        compile_s_total ~ 0 while trace/load totals stay honest."""
        rec: Dict[str, Any] = {"tag": tag, "phase": phase or tag,
                               "shapes": sig_label,
                               "compile_s": float(compile_s),
                               "trace_s": float(trace_s)}
        if cache_hit:
            rec["cache_hit"] = True
        rec.update(executable_cost(compiled))
        with self._lock:
            self._records.append(rec)
        # always-current through obs meta, so bench.py and the
        # OpenMetrics exporter read one place (compiles are rare —
        # re-summarizing per compile is noise-free); only the global
        # introspector publishes — test-local registries must not
        # overwrite the run's meta
        if self is globals().get("global_xla"):
            global_metrics.set_meta("xla_programs", self.summary())

    def note_fallback(self, tag: str, error: str) -> None:
        with self._lock:
            self._fallbacks.setdefault(tag, error)

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._records]

    @property
    def n_programs(self) -> int:
        return len(self._records)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            recs = [dict(r) for r in self._records]
            fallbacks = dict(self._fallbacks)
        by_phase: Dict[str, int] = {}
        by_tag: Dict[str, Dict[str, float]] = {}
        total = 0.0
        trace_total = 0.0
        load_total = 0.0
        n_hits = 0
        for r in recs:
            hit = bool(r.get("cache_hit"))
            if hit:
                load_total += r["compile_s"]
                n_hits += 1
            else:
                total += r["compile_s"]
            trace_total += r.get("trace_s", 0.0)
            by_phase[r["phase"]] = by_phase.get(r["phase"], 0) + 1
            t = by_tag.setdefault(r["tag"], {
                "programs": 0, "compile_s": 0.0})
            t["programs"] += 1
            if hit:
                t["cache_load_s"] = round(t.get("cache_load_s", 0.0)
                                          + r["compile_s"], 4)
            else:
                t["compile_s"] = round(t["compile_s"] + r["compile_s"], 4)
            if r.get("trace_s"):
                t["trace_s"] = round(t.get("trace_s", 0.0)
                                     + r["trace_s"], 4)
            for k in ("flops", "bytes_accessed"):
                if k in r:
                    t[k] = t.get(k, 0.0) + r[k]
        out: Dict[str, Any] = {
            "compile_s_total": round(total, 4),
            "trace_s_total": round(trace_total, 4),
            "cache_load_s_total": round(load_total, 4),
            "n_cache_hits": n_hits,
            "n_programs": len(recs),
            "n_recompiles_by_phase": by_phase,
            "by_tag": by_tag,
        }
        if fallbacks:
            out["aot_fallbacks"] = fallbacks
        return out


global_xla = XlaIntrospector()

# env-enabled telemetry (LGBM_TPU_TELEMETRY) arms the introspector too,
# matching obs/memory.py's watermark hook — metrics.enable() only runs
# for the programmatic path
if global_metrics.enabled:
    global_xla.enable()


def _persistent_cache_active() -> bool:
    """True when the XLA persistent compilation cache is configured.
    Thin delegate kept for callers/tests; the policy itself lives in
    ``compile_cache`` now (one module for every program boundary)."""
    from ..compile_cache import cache_active
    return cache_active()


def instrumented_jit(tag: str, fn: Callable, phase: Optional[str] = None,
                     registry: Optional[XlaIntrospector] = None,
                     **jit_kwargs) -> Callable:
    """``jax.jit(wrap_traced(tag, fn))`` plus, when the introspector is
    enabled, per-shape-bucket AOT routing that captures compile time and
    cost analysis. Drop-in for the existing program-boundary jits
    (grower, fused iteration, predict traversal)."""
    import jax
    from ..compile_cache import donation_allowed
    from .health import global_health
    from .profile import global_profile
    reg = registry if registry is not None else global_xla
    # device-time attribution (obs/profile.py): the jitted function name
    # is what the profiler trace shows, so map it back to the obs tag
    global_profile.register_tag(tag, phase, getattr(fn, "__name__", tag))
    if not donation_allowed():
        # One version-gated policy (compile_cache.donation_allowed):
        # buffer donation segfaults on executables deserialized from the
        # persistent compilation cache on jaxlib<=0.4.36; donation is a
        # memory optimisation only, so affected setups drop it.
        jit_kwargs.pop("donate_argnums", None)
    jitted = jax.jit(global_metrics.wrap_traced(tag, fn), **jit_kwargs)
    compiled_cache: Dict[Any, Any] = {}
    broken: List[str] = []  # non-empty => this tag fell back for good

    def _dispatch(*args, **kwargs):
        if not reg.enabled or broken:
            return jitted(*args, **kwargs)
        try:
            key = _sig_key(args, kwargs)
        except Exception as exc:  # unhashable pytree — don't retry
            broken.append(repr(exc))
            reg.note_fallback(tag, repr(exc))
            return jitted(*args, **kwargs)
        entry = compiled_cache.get(key)
        if entry is None:
            try:
                t0 = time.perf_counter()
                lowered = jitted.lower(*args, **kwargs)
                t1 = time.perf_counter()
                hits0 = _cache_hit_count[0]
                entry = lowered.compile()
                dt_compile = time.perf_counter() - t1
            except Exception as exc:
                broken.append(repr(exc))
                reg.note_fallback(tag, repr(exc))
                return jitted(*args, **kwargs)
            compiled_cache[key] = entry
            reg.note_compile(tag, phase, _shape_label(key), dt_compile,
                             entry, trace_s=t1 - t0,
                             cache_hit=_cache_hit_count[0] > hits0)
        if global_profile.capturing:
            # retain (executable, latest args) for the window-close
            # block_until_ready micro-reruns; dropped at stop_window
            global_profile.register_entry(tag, phase, entry, args, kwargs)
        try:
            return entry(*args, **kwargs)
        except Exception as exc:
            broken.append(repr(exc))
            reg.note_fallback(tag, repr(exc))
            return jitted(*args, **kwargs)

    def wrapper(*args, **kwargs):
        try:
            if global_profile.capturing:
                # open profile window: sync-timed dispatch attributes
                # this call's device time to the tag (values unchanged)
                return global_profile.timed_call(tag, phase, _dispatch,
                                                 args, kwargs)
            return _dispatch(*args, **kwargs)
        finally:
            # runtime collective attribution (obs/health.py): AFTER the
            # dispatch, so a first call's trace has already captured
            # this program's collective manifest. One attribute check
            # when health is disabled.
            if global_health.enabled:
                global_health.note_program_call(tag)

    wrapper.__name__ = getattr(fn, "__name__", tag)
    wrapper.__wrapped_jit__ = jitted  # escape hatch / tests
    wrapper.lower = jitted.lower  # AOT-shaped callers (tests) keep working
    return wrapper
