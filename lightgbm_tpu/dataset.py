"""Binned dataset core + metadata.

TPU-native analog of the reference Dataset / Metadata / CUDARowData
(ref: include/LightGBM/dataset.h:492,49; cuda/cuda_row_data.hpp:33).
Host side: per-feature BinMappers over (sampled) raw data, a dense
feature-major bin matrix, and label/weight/group metadata. Device side:
the bin matrix as a `[F, N]` uint8/uint16 array (optionally sharded over a
mesh axis for data-parallel training).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import binning
from .binning import BinMapper
from .config import Config


def is_sparse(data) -> bool:
    """True for any scipy sparse matrix (scipy optional: dense-only
    installs never import it)."""
    try:
        import scipy.sparse as sp
        return sp.issparse(data)
    except ImportError:
        return False


def sparse_row_batches(data, budget_cells: int = 1 << 25):
    """Yield dense float64 row batches of a scipy sparse matrix, sized
    so each batch stays under ~budget_cells values — the single batching
    policy shared by every sparse prediction path (ref: c_api.cpp
    LGBM_BoosterPredictForCSR row-chunking)."""
    csr = data.tocsr()
    batch = max(1, budget_cells // max(csr.shape[1], 1))
    for i in range(0, csr.shape[0], batch):
        yield np.asarray(csr[i:i + batch].toarray(), np.float64)


def _transform_all(data: np.ndarray, mappers: List[BinMapper],
                   used: Sequence[int], dtype) -> np.ndarray:
    """Bin all used columns -> [F_used, N]. Uses the native threaded
    transform for the numerical columns when the library is available
    (ref: the reference bins in C++; here native/src LGT_TransformMatrix)."""
    n = data.shape[0]
    bins_fm = np.empty((len(used), n), dtype=dtype)
    numeric = [j for j, m in enumerate(mappers) if not m.is_categorical
               and m.bin_upper_bound is not None]
    done = set()
    if len(numeric) > 1 and n * len(numeric) >= 65536:
        from . import native as _native
        cols = [used[j] for j in numeric]
        if cols == list(range(data.shape[1])) and (
                data.flags["C_CONTIGUOUS"] or data.flags["F_CONTIGUOUS"]):
            sub = data  # all columns numeric+used: zero-copy into the kernel
        else:
            sub = data[:, cols]  # C-order gather, original dtype
        out = _native.transform_matrix(sub, [mappers[j] for j in numeric],
                                       dtype)
        if out is not None:
            if len(numeric) == len(used):
                return out  # [F_used, N] already — skip the copy
            for k, j in enumerate(numeric):
                bins_fm[j] = out[k]
            done = set(numeric)
    for j, col in enumerate(used):
        if j not in done:
            bins_fm[j] = mappers[j].transform(data[:, col])
    return bins_fm


class Metadata:
    """Labels, weights, init scores, query boundaries
    (ref: include/LightGBM/dataset.h:49)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None       # [N] f32
        self.weight: Optional[np.ndarray] = None      # [N] f32
        self.init_score: Optional[np.ndarray] = None  # [N] or [N*K] f64
        self.query_boundaries: Optional[np.ndarray] = None  # [num_queries+1]
        self.positions: Optional[np.ndarray] = None

    def set_label(self, label) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        assert len(label) == self.num_data, "label length mismatch"
        self.label = label

    def set_weight(self, weight) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        assert len(weight) == self.num_data, "weight length mismatch"
        self.weight = weight

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64)

    def set_group(self, group) -> None:
        """group: per-query sizes (like the python-package) -> boundaries."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        bounds = np.zeros(len(group) + 1, dtype=np.int32)
        np.cumsum(group, out=bounds[1:])
        assert bounds[-1] == self.num_data, "sum(group) must equal num_data"
        self.query_boundaries = bounds

    def set_position(self, position) -> None:
        if position is None:
            self.positions = None
            return
        self.positions = np.asarray(position, dtype=np.int32).reshape(-1)

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class BinnedDataset:
    """Pre-binned dataset (host arrays; `.device_bins()` ships to TPU).

    Attributes:
      bins_fm: [F_used, N] feature-major bin ids (uint8 or uint16).
      mappers: BinMapper per used feature.
      used_features: original column index per used feature.
      num_total_features: raw feature count (incl. trivial/dropped).
    """

    def __init__(self, bins_fm: np.ndarray, mappers: List[BinMapper],
                 used_features: List[int], num_total_features: int,
                 metadata: Metadata, feature_names: Optional[List[str]] = None,
                 label_idx: int = 0):
        self.bins_fm = bins_fm
        self.mappers = mappers
        self.used_features = used_features
        self.num_total_features = num_total_features
        self.metadata = metadata
        self.feature_names = feature_names or [
            f"Column_{i}" for i in range(num_total_features)]
        self.label_idx = label_idx
        self._device_cache: Dict[Any, Any] = {}
        # raw feature values [N, F_total] (reference kept for linear-tree
        # leaf fits; None for binary-loaded datasets)
        self.raw_data: Optional[np.ndarray] = None
        # EFB: when set, bins_fm holds BUNDLED columns [G, N] and
        # bundle_info maps logical features into them (ref:
        # dataset.cpp:251 FastFeatureBundling; see bundling.py)
        self.bundle_info = None
        # sparse row-wise COO storage (ref: multi_val_sparse_bin.hpp:21):
        # when set to (rows, feats, bins, zero_bins) int32 arrays,
        # bins_fm is a [1, N] placeholder and histogram/partition paths
        # run on the COO triples (ops.partition.SparseBins)
        self.sparse_coo = None

    # ------------------------------------------------------------------
    @property
    def num_data(self) -> int:
        return self.bins_fm.shape[1]

    @property
    def num_features(self) -> int:
        return len(self.mappers)

    @property
    def max_bins(self) -> int:
        return max((m.num_bins for m in self.mappers), default=1)

    def feature_meta_arrays(self):
        """Host numpy arrays for ops.split.FeatureMeta."""
        f = len(self.mappers)
        num_bins = np.array([m.num_bins for m in self.mappers], np.int32)
        missing = np.array([m.missing_type for m in self.mappers], np.int32)
        default_bin = np.array([m.default_bin for m in self.mappers], np.int32)
        is_cat = np.array([m.is_categorical for m in self.mappers], bool)
        return num_bins, missing, default_bin, is_cat

    # ------------------------------------------------------------------
    @classmethod
    def from_matrix(cls, data: np.ndarray, config: Config,
                    metadata: Optional[Metadata] = None,
                    categorical_features: Sequence[int] = (),
                    feature_names: Optional[List[str]] = None,
                    reference: Optional["BinnedDataset"] = None,
                    forced_bins: Optional[Dict[int, List[float]]] = None,
                    ) -> "BinnedDataset":
        """Bin a dense [N, F] float matrix (ref: DatasetLoader::
        ConstructFromSampleData, src/io/dataset_loader.cpp:601)."""
        data = np.asarray(data)
        if data.ndim != 2:
            raise ValueError("data must be 2-D [num_data, num_features]")
        n, f = data.shape
        metadata = metadata or Metadata(n)

        if reference is not None:
            # align binning with a reference (train) dataset
            # (ref: dataset_loader.cpp:307 LoadFromFileAlignWithOtherDataset)
            mappers = reference.mappers
            used = reference.used_features
            logical_dtype = (np.uint8 if max(
                (m.num_bins for m in mappers), default=1) <= 256
                else np.uint16)
            bins_fm = _transform_all(data, mappers, used, logical_dtype)
            if reference.bundle_info is not None:
                from .bundling import build_bundled_matrix
                nb = np.array([m.num_bins for m in mappers], np.int64)
                bins_fm, _ = build_bundled_matrix(
                    bins_fm, nb, [list(b) for b in
                                  reference.bundle_info.bundles])
            ds = cls(bins_fm, mappers, used, reference.num_total_features,
                     metadata, reference.feature_names)
            ds.bundle_info = reference.bundle_info
            ds.raw_data = data
            return ds

        # sample rows for binning (ref: bin_construct_sample_cnt)
        sample_cnt = min(n, int(config.bin_construct_sample_cnt))
        if sample_cnt < n:
            rng = np.random.RandomState(config.data_random_seed)
            sample_idx = rng.choice(n, sample_cnt, replace=False)
            sample = data[np.sort(sample_idx)]
        else:
            sample = data

        cat_set = set(int(c) for c in categorical_features)
        mappers_all: List[BinMapper] = []
        max_bin_by_feature = config.max_bin_by_feature
        for col in range(f):
            mb = int(config.max_bin)
            if max_bin_by_feature is not None and len(max_bin_by_feature) == f:
                mb = int(max_bin_by_feature[col])
            forced = None
            if forced_bins and col in forced_bins:
                forced = forced_bins[col]
            m = BinMapper().fit(
                np.asarray(sample[:, col], dtype=np.float64),
                max_bin=mb,
                min_data_in_bin=int(config.min_data_in_bin),
                use_missing=bool(config.use_missing),
                zero_as_missing=bool(config.zero_as_missing),
                is_categorical=col in cat_set,
                forced_bounds=forced)
            mappers_all.append(m)

        used = [i for i, m in enumerate(mappers_all)
                if not (config.feature_pre_filter and m.is_trivial)]
        if not used:
            used = [0] if f else []
        mappers = [mappers_all[i] for i in used]
        max_bins = max((m.num_bins for m in mappers), default=1)
        dtype = np.uint8 if max_bins <= 256 else np.uint16
        bins_fm = _transform_all(data, mappers, used, dtype)
        ds = cls(bins_fm, mappers, used, f, metadata, feature_names)
        ds.raw_data = data
        if config.enable_bundle and len(mappers) > 1:
            ds._try_bundle(config)
        return ds

    @classmethod
    def from_sparse(cls, data, config: Config,
                    metadata: Optional[Metadata] = None,
                    categorical_features: Sequence[int] = (),
                    feature_names: Optional[List[str]] = None,
                    reference: Optional["BinnedDataset"] = None,
                    forced_bins: Optional[Dict[int, List[float]]] = None,
                    ) -> "BinnedDataset":
        """Bin a scipy CSR/CSC matrix WITHOUT densifying it (ref:
        LGBM_DatasetCreateFromCSR/CSC c_api.cpp:1311,1330 feeding
        SparseBin sparse_bin.hpp:74). Binning runs per CSC column on the
        explicit nonzeros + the implicit zero count; storage is emitted
        directly as the bundled [G, N] EFB matrix, so a 1M x 10k one-hot
        matrix ingests in O(nnz + G*N) host memory, never O(N*F)."""
        import scipy.sparse as sp
        from .bundling import build_bundled_from_csc, find_bundles_sparse
        if not sp.issparse(data):
            raise ValueError("from_sparse expects a scipy sparse matrix")
        if getattr(config, "linear_tree", False):
            raise ValueError(
                "linear_tree requires raw feature values; sparse input "
                "is not supported for linear trees")
        csc = data.tocsc()
        csc.sort_indices()
        n, f = csc.shape
        metadata = metadata or Metadata(n)

        if reference is not None:
            # valid set aligned with the (sparse-trained) train set
            mappers = reference.mappers
            used = reference.used_features
            nb = np.array([m.num_bins for m in mappers], np.int64)
            if reference.sparse_coo is not None:
                # mirror the COO storage layout
                zb = reference.sparse_coo[3]
                ds = cls._emit_coo(csc, mappers, used,
                                   reference.num_total_features, metadata,
                                   reference.feature_names, zb, n)
                return ds
            if reference.bundle_info is not None:
                bundles = [list(b) for b in reference.bundle_info.bundles]
            else:
                bundles = [[j] for j in range(len(mappers))]
            bins_fm, info = build_bundled_from_csc(csc, mappers, used,
                                                   bundles, nb)
            ds = cls(bins_fm, mappers, used, reference.num_total_features,
                     metadata, reference.feature_names)
            # mirror the reference dataset's storage layout exactly
            ds.bundle_info = (info if reference.bundle_info is not None
                              else None)
            ds.raw_data = csc.tocsr()
            return ds

        # --- sample rows for binning (ref: bin_construct_sample_cnt) ---
        sample_cnt = min(n, int(config.bin_construct_sample_cnt))
        if sample_cnt < n:
            rng = np.random.RandomState(config.data_random_seed)
            rows = np.sort(rng.choice(n, sample_cnt, replace=False))
            sample_csc = csc[rows, :].tocsc()
            sample_csc.sort_indices()
        else:
            sample_csc = csc

        cat_set = set(int(c) for c in categorical_features)
        max_bin_by_feature = config.max_bin_by_feature
        mappers_all: List[BinMapper] = []
        for col in range(f):
            mb = int(config.max_bin)
            if max_bin_by_feature is not None and len(max_bin_by_feature) == f:
                mb = int(max_bin_by_feature[col])
            forced = forced_bins.get(col) if forced_bins else None
            sl = slice(sample_csc.indptr[col], sample_csc.indptr[col + 1])
            nz_vals = np.asarray(sample_csc.data[sl], np.float64)
            m = BinMapper()
            if col in cat_set:
                # categorical needs exact per-category counts incl. the
                # implicit zero category: materialize ONE sampled column
                dense_col = np.zeros(sample_cnt)
                dense_col[sample_csc.indices[sl]] = nz_vals
                m.fit(dense_col, max_bin=mb,
                      min_data_in_bin=int(config.min_data_in_bin),
                      use_missing=bool(config.use_missing),
                      zero_as_missing=bool(config.zero_as_missing),
                      is_categorical=True)
            else:
                m.fit_sparse(nz_vals, sample_cnt, max_bin=mb,
                             min_data_in_bin=int(config.min_data_in_bin),
                             use_missing=bool(config.use_missing),
                             zero_as_missing=bool(config.zero_as_missing),
                             forced_bounds=forced)
            mappers_all.append(m)

        used = [i for i, m in enumerate(mappers_all)
                if not (config.feature_pre_filter and m.is_trivial)]
        if not used:
            used = [0] if f else []
        mappers = [mappers_all[i] for i in used]
        nb = np.array([m.num_bins for m in mappers], np.int64)

        # --- bundle structure from the SAMPLE's non-default rows ---
        # zero_bins[j] = the bin an implicit zero lands in (transform(0));
        # equals default_bin for numerical mappers but NOT for
        # categorical ones (category 0's bin vs the 'other' bin 0)
        zero_bins = np.array(
            [int(m.transform(np.zeros(1))[0]) for m in mappers], np.int64)
        nz_rows: List[np.ndarray] = []
        for j, col in enumerate(used):
            sl = slice(sample_csc.indptr[col], sample_csc.indptr[col + 1])
            fb = mappers[j].transform(
                np.asarray(sample_csc.data[sl], np.float64))
            nz_rows.append(sample_csc.indices[sl][fb != zero_bins[j]])
        max_bins = int(nb.max()) if len(nb) else 1
        # same learner guard as _try_bundle: the parallel growers index
        # LOGICAL [F, N] storage and have no bundle decode
        if (config.enable_bundle and len(mappers) > 1
                and config.tree_learner in ("serial",)):
            bundles = find_bundles_sparse(
                nz_rows, sample_cnt, nb,
                max_conflict_rate=float(config.max_conflict_rate),
                max_bundle_bins=max(max_bins, 256),
                bundleable=(zero_bins == 0))
        else:
            bundles = [[j] for j in range(len(mappers))]

        # --- sparse row-wise COO mode (ref: bin.h:482 MultiValBin sparse
        # variant): when bundling can't shrink the dense layout enough,
        # O(nnz) segment-sum histograms beat the O(G*N*B) dense passes.
        # Estimated from the sample's post-zero-bin-filter density.
        est_nnz = sum(len(r) for r in nz_rows) * (n / max(sample_cnt, 1))
        mode = getattr(config, "tpu_sparse_hist", "auto")
        coo_eligible = (config.tree_learner in ("serial",)
                        and not config.linear_tree and len(mappers) > 1)
        if mode == "force" and not coo_eligible:
            import warnings
            warnings.warn(
                "tpu_sparse_hist=force needs tree_learner=serial, "
                "linear_tree=false and >1 used feature; using the "
                "dense layout")
        use_coo = (coo_eligible
                   and (mode == "force"
                        or (mode == "auto"
                            # 48x compute-bias factor: a scatter-added
                            # COO element costs far more than an MXU
                            # one-hot lane; COO must be ~50x leaner
                            and 48.0 * est_nnz < len(bundles) * n)))
        if use_coo:
            ds = cls._emit_coo(csc, mappers, used, f, metadata,
                               feature_names,
                               zero_bins.astype(np.int32), n)
            return ds

        if len(bundles) == len(mappers):
            # nothing bundled: emit the plain [F, N] layout in FEATURE
            # order (find_bundles returns nnz-descending order) and skip
            # the bundle decode indirection entirely
            bundles = [[j] for j in range(len(mappers))]
        bins_fm, info = build_bundled_from_csc(csc, mappers, used,
                                               bundles, nb)
        ds = cls(bins_fm, mappers, used, f, metadata, feature_names)
        if len(bundles) < len(mappers):
            ds.bundle_info = info
        # the sparse matrix itself serves as raw_data: prediction paths
        # densify in batches, continued training fast-forwards through
        # predict_raw (linear trees are rejected above)
        ds.raw_data = csc.tocsr()
        return ds

    @classmethod
    def _emit_coo(cls, csc, mappers, used, num_total_features, metadata,
                  feature_names, zero_bins: np.ndarray,
                  n: int) -> "BinnedDataset":
        """Emit COO sparse storage: per used feature, bin the explicit
        nonzeros and keep only entries off the implicit-zero bin (their
        mass is recovered from leaf totals at histogram time)."""
        rows_l, feats_l, bins_l = [], [], []
        for j, col in enumerate(used):
            sl = slice(csc.indptr[col], csc.indptr[col + 1])
            fb = mappers[j].transform(
                np.asarray(csc.data[sl], np.float64)).astype(np.int32)
            keep = fb != zero_bins[j]
            rows_l.append(csc.indices[sl][keep].astype(np.int32))
            feats_l.append(np.full(int(keep.sum()), j, np.int32))
            bins_l.append(fb[keep])
        ds = cls(np.zeros((1, n), np.uint8), mappers, used,
                 num_total_features, metadata, feature_names)
        ds.sparse_coo = (
            np.concatenate(rows_l) if rows_l else np.zeros(0, np.int32),
            np.concatenate(feats_l) if feats_l else np.zeros(0, np.int32),
            np.concatenate(bins_l) if bins_l else np.zeros(0, np.int32),
            np.asarray(zero_bins, np.int32))
        ds.raw_data = csc.tocsr()
        return ds

    def _try_bundle(self, config: Config) -> None:
        """EFB: merge mutually exclusive features into bundled storage
        columns when that shrinks the bin matrix (ref: dataset.cpp:112
        FindGroups, :251 FastFeatureBundling). Logical semantics are
        unchanged — histograms/partitions decode through bundle_info."""
        from .bundling import (build_bundled_matrix, find_bundles,
                               should_bundle)
        if config.tree_learner not in ("serial",):
            return  # parallel learners shard logical features directly
        nb = np.array([m.num_bins for m in self.mappers], np.int64)
        default_bins = np.array([m.default_bin for m in self.mappers],
                                np.int64)
        # conflict detection on a row SAMPLE (ref: FindGroups samples too)
        # — a full scan would cost O(F*G*N) host time on exactly the
        # wide-sparse data EFB exists for
        n = self.bins_fm.shape[1]
        sample_cnt = min(n, int(config.bin_construct_sample_cnt))
        if sample_cnt < n:
            rng = np.random.RandomState(config.data_random_seed)
            rows = np.sort(rng.choice(n, sample_cnt, replace=False))
            sample = self.bins_fm[:, rows]
        else:
            sample = self.bins_fm
        nonzero = sample != default_bins[:, None].astype(self.bins_fm.dtype)
        # the offset encoding represents "default" as stored bin 0, so
        # only default-bin-0 features can share a bundle; others are
        # stored verbatim as singletons
        bundles = find_bundles(
            nonzero, nb,
            max_conflict_rate=float(config.max_conflict_rate),
            max_bundle_bins=max(int(self.max_bins), 256),
            bundleable=(default_bins == 0))
        if not should_bundle(bundles, len(self.mappers)):
            return
        bundled, info = build_bundled_matrix(self.bins_fm, nb, bundles)
        self.bins_fm = bundled
        self.bundle_info = info
        self._device_cache.clear()

    # ------------------------------------------------------------------
    def device_bins(self):
        """Bin matrix as a device array (cached). Bundled storage when
        bundle_info is set — pair with device_bundle(). COO SparseBins
        pytree when sparse_coo is set."""
        import jax.numpy as jnp
        key = "bins"
        if key not in self._device_cache:
            if self.sparse_coo is not None:
                from .ops.partition import SparseBins
                rows, feats, bins, zb = self.sparse_coo
                self._device_cache[key] = SparseBins(
                    jnp.asarray(rows), jnp.asarray(feats),
                    jnp.asarray(bins), jnp.asarray(zb))
            else:
                self._device_cache[key] = jnp.asarray(self.bins_fm)
        return self._device_cache[key]

    def host_feature_bins(self, j: int) -> np.ndarray:
        """One logical feature's [N] bin column on host (dense slice, or
        COO materialization for sparse storage). Bundled datasets decode
        through bundle_info."""
        if self.sparse_coo is not None:
            rows, feats, bins, zb = self.sparse_coo
            out = np.full(self.num_data, zb[j], np.int32)
            sel = feats == j
            out[rows[sel]] = bins[sel]
            return out
        if self.bundle_info is not None:
            from .bundling import decode_stored_host
            return decode_stored_host(
                self.bins_fm[self.bundle_info.group_of[j]].astype(np.int32),
                self.bundle_info.offset_of[j],
                self.mappers[j].num_bins - 1)
        return self.bins_fm[j].astype(np.int32)

    def device_bundle(self):
        """(group_of, offset_of, num_bins) device triple for EFB decode,
        or None for unbundled storage."""
        if self.bundle_info is None:
            return None
        import jax.numpy as jnp
        key = "bundle"
        if key not in self._device_cache:
            nb = np.array([m.num_bins for m in self.mappers], np.int32)
            self._device_cache[key] = (
                jnp.asarray(self.bundle_info.group_of),
                jnp.asarray(self.bundle_info.offset_of),
                jnp.asarray(nb))
        return self._device_cache[key]

    def feature_infos(self) -> List[str]:
        """Per raw feature info strings for the model header."""
        infos = []
        used_map = {c: j for j, c in enumerate(self.used_features)}
        for col in range(self.num_total_features):
            if col in used_map:
                infos.append(self.mappers[used_map[col]].feature_info_str())
            else:
                infos.append("none")
        return infos
