"""Distributed (data-parallel) training over the virtual 8-device CPU mesh
(ref strategy: tests/distributed/_test_distributed.py DistributedMockup —
there via N localhost CLI processes + sockets; here via jax.sharding over
a forced multi-device host platform, which exercises the same program the
TPU mesh runs)."""

import numpy as np
import jax
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc
from tests.conftest import make_binary, make_regression


@pytest.fixture(autouse=True)
def _require_multi_device():
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device (XLA_FLAGS host platform count)")


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_data_parallel_binary_quality():
    X, y = make_binary(2000)
    dtrain = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "tree_learner": "data",
                     "num_leaves": 15, "min_data_in_leaf": 5,
                     "verbosity": -1},
                    dtrain, num_boost_round=20)
    assert _auc(y, bst.predict(X)) > 0.9


def test_data_parallel_matches_serial():
    """Distributed vs single-device training must agree (ref:
    _test_distributed.py:168 accuracy + prediction agreement check)."""
    X, y = make_regression(1024)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 7}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    parallel = lgb.train({**params, "tree_learner": "data"},
                         lgb.Dataset(X, label=y), num_boost_round=10)
    ps = serial.predict(X)
    pp = parallel.predict(X)
    # identical math; tolerance covers cross-shard reduction order
    np.testing.assert_allclose(pp, ps, rtol=1e-3, atol=1e-3)


def test_data_parallel_sharded_arrays():
    X, y = make_binary(512)
    dtrain = lgb.Dataset(X, label=y)
    bst = lgb.Booster({"objective": "binary", "tree_learner": "data",
                       "num_leaves": 7, "verbosity": -1}, dtrain)
    gbdt = bst._gbdt
    assert gbdt.mesh.size == 8
    # bins sharded along rows (axis 1)
    sharding = gbdt.bins_fm.sharding
    spec = sharding.spec
    assert spec[1] == "data"
    bst.update()
    assert bst.current_iteration() == 1


def test_data_parallel_num_shards_param():
    X, y = make_binary(512)
    bst = lgb.Booster({"objective": "binary", "tpu_num_shards": 4,
                       "num_leaves": 7, "verbosity": -1},
                      lgb.Dataset(X, label=y))
    assert bst._gbdt.mesh.size == 4
    bst.update()


def test_voting_and_feature_learner_accepted():
    X, y = make_binary(512)
    for tl in ("voting", "feature"):
        bst = lgb.train({"objective": "binary", "tree_learner": tl,
                         "num_leaves": 7, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=3)
        assert bst.num_trees() == 3


def test_voting_parallel_matches_serial_when_topk_covers_features():
    """With top_k >= F the voting filter keeps every feature, so PV-tree
    must reproduce the serial learner exactly."""
    X, y = make_regression(1024, 8)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "top_k": 20,
              # the sharded learners grow exact leaf-wise; compare
              # against the serial EXACT grower, not the waved default
              "tpu_wave_max": 0}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    voting = lgb.train({**params, "tree_learner": "voting"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(voting.predict(X), serial.predict(X),
                               rtol=1e-3, atol=1e-3)


def test_voting_parallel_small_topk_still_learns():
    """top_k < F: the candidate filter is actually binding (ref: PV-tree
    accuracy claim — voting loses little quality)."""
    X, y = make_binary(2048, 12)
    bst = lgb.train({"objective": "binary", "tree_learner": "voting",
                     "top_k": 2, "num_leaves": 15, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=15)
    assert bst._gbdt.mesh.size == 8
    assert _auc(y, bst.predict(X)) > 0.9


def test_feature_parallel_matches_serial_exactly():
    """Feature-parallel is exact: same candidate set, sharded search."""
    X, y = make_regression(1024, 10)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 3,
              # serial baseline must be the EXACT grower (see above)
              "tpu_wave_max": 0}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    fpar = lgb.train({**params, "tree_learner": "feature"},
                     lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(fpar.predict(X), serial.predict(X),
                               rtol=1e-4, atol=1e-4)


def test_feature_parallel_uneven_feature_count():
    """F=13 not divisible by 8 shards: overlapping slices must stay
    correct."""
    X, y = make_binary(1024, 13)
    bst = lgb.train({"objective": "binary", "tree_learner": "feature",
                     "num_leaves": 15, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    assert _auc(y, bst.predict(X)) > 0.9


def test_voting_program_contains_collectives():
    """The compiled voting program must actually communicate: psum
    (all-reduce) for candidate histograms, all-gather for votes."""
    import jax.numpy as jnp
    from lightgbm_tpu.parallel.voting import make_sharded_voting_grow
    from lightgbm_tpu.parallel import mesh as mesh_lib

    X, y = make_binary(512, 8)
    bst = lgb.Booster({"objective": "binary", "tree_learner": "voting",
                       "num_leaves": 7, "verbosity": -1, "top_k": 2},
                      lgb.Dataset(X, label=y))
    g = bst._gbdt
    mesh = mesh_lib.get_mesh(8)
    grow = make_sharded_voting_grow(mesh, num_leaves=7,
                                    max_bins=g._static["max_bins"],
                                    top_k=2)
    hlo = grow.lower(
        g.bins_fm, jnp.zeros(512, jnp.float32), jnp.ones(512, jnp.float32),
        jnp.ones(512, jnp.float32), jnp.ones(8, bool), g.feature_meta,
        g.hp, jnp.int32(-1)).compile().as_text()
    assert "all-reduce" in hlo or "all-gather" in hlo, \
        "voting program lost its collectives"
