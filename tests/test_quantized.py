"""Quantized-gradient training (ref: gradient_discretizer.{hpp,cpp},
config.h use_quantized_grad / num_grad_quant_bins /
quant_train_renew_leaf / stochastic_rounding)."""

import numpy as np
import pytest

from conftest import make_binary, make_regression

import lightgbm_tpu as lgb


def _auc(label, prob):
    pos, neg = prob[label == 1], prob[label == 0]
    return float((pos[:, None] > neg[None, :]).mean()
                 + 0.5 * (pos[:, None] == neg[None, :]).mean())


class TestQuantizedTraining:
    def test_binary_accuracy_close_to_full_precision(self):
        X, y = make_binary(2000, 10)
        base = lgb.train({"objective": "binary", "verbosity": -1},
                         lgb.Dataset(X, label=y), num_boost_round=30)
        quant = lgb.train({"objective": "binary", "verbosity": -1,
                           "use_quantized_grad": True,
                           "num_grad_quant_bins": 4},
                          lgb.Dataset(X, label=y), num_boost_round=30)
        auc_full = _auc(y, base.predict(X))
        auc_q = _auc(y, quant.predict(X))
        assert auc_q > auc_full - 0.02, (auc_full, auc_q)

    def test_regression_with_renew_leaf(self):
        X, y = make_regression(1500, 8)
        quant = lgb.train({"objective": "regression", "verbosity": -1,
                           "use_quantized_grad": True,
                           "quant_train_renew_leaf": True,
                           "num_grad_quant_bins": 4},
                          lgb.Dataset(X, label=y), num_boost_round=30)
        pred = quant.predict(X)
        ss_res = ((y - pred) ** 2).sum()
        ss_tot = ((y - y.mean()) ** 2).sum()
        assert 1 - ss_res / ss_tot > 0.8

    @pytest.mark.slow
    def test_more_bins_is_closer_to_full(self):
        X, y = make_regression(1500, 8, seed=2)

        def mse(params, rounds=20):
            b = lgb.train({"objective": "regression", "verbosity": -1,
                           **params}, lgb.Dataset(X, label=y),
                          num_boost_round=rounds)
            return float(((y - b.predict(X)) ** 2).mean())

        full = mse({})
        q4 = mse({"use_quantized_grad": True, "num_grad_quant_bins": 4})
        q16 = mse({"use_quantized_grad": True, "num_grad_quant_bins": 16})
        # quantization shouldn't blow up the fit; more bins ≈ closer
        assert q16 < full * 1.5
        assert q4 < full * 2.5

    @pytest.mark.slow
    def test_deterministic_rounding_mode(self):
        X, y = make_regression(800, 6)
        p = {"objective": "regression", "verbosity": -1,
             "use_quantized_grad": True, "stochastic_rounding": False}
        b1 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
        b2 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
        np.testing.assert_allclose(b1.predict(X), b2.predict(X))

    def test_quantized_with_goss(self):
        X, y = make_binary(2000, 8)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "use_quantized_grad": True,
                         "data_sample_strategy": "goss"},
                        lgb.Dataset(X, label=y), num_boost_round=20)
        assert _auc(y, bst.predict(X)) > 0.8

    @pytest.mark.slow
    def test_quantized_multiclass(self):
        from conftest import make_multiclass
        X, y = make_multiclass(1200, 8, k=4)
        bst = lgb.train({"objective": "multiclass", "num_class": 4,
                         "verbosity": -1, "use_quantized_grad": True},
                        lgb.Dataset(X, label=y), num_boost_round=15)
        acc = (bst.predict(X).argmax(1) == y).mean()
        assert acc > 0.75
