"""Benchmark: boosting iterations/sec on Higgs-shaped data.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference CPU result on Higgs-10.5M — 500 iterations in
130.094 s => 3.843 iters/sec (docs/Experiments.rst:113; see BASELINE.md).
Config mirrors the reference GPU benchmark setup (max_bin=63,
num_leaves=255, lr=0.1, min_sum_hessian=100, objective=binary —
docs/GPU-Performance.rst:108-123).

The dataset is synthetic with Higgs shape (28 features, N rows; the real
Higgs is not redistributable and this environment has no egress). Row
count defaults to 10.5M (override with BENCH_ROWS) so iters/sec is
directly comparable to the published 3.843.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    n = int(os.environ.get("BENCH_ROWS", 10_500_000))
    f = 28
    iters = int(os.environ.get("BENCH_ITERS", 10))
    warmup = 2

    import jax
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    # Higgs-like: mix of informative and noise features, ~53% positive
    x = rng.randn(n, f).astype(np.float32)
    logit = (x[:, 0] + 0.6 * x[:, 1] ** 2 + 0.4 * x[:, 2] * x[:, 3]
             - 0.3 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0.2).astype(np.float32)
    n_test = 200_000
    xt = rng.randn(n_test, f).astype(np.float32)
    lt = (xt[:, 0] + 0.6 * xt[:, 1] ** 2 + 0.4 * xt[:, 2] * xt[:, 3]
          - 0.3 * np.abs(xt[:, 4]) + 0.5 * rng.randn(n_test))
    yt = (lt > 0.2).astype(np.float32)

    params = {
        "objective": "binary",
        "num_leaves": 255,
        "learning_rate": 0.1,
        "max_bin": 63,
        "min_sum_hessian_in_leaf": 100,
        "min_data_in_leaf": 0,
        "verbosity": -1,
    }
    t0 = time.time()
    ds = lgb.Dataset(x, label=y, params=params)
    ds.construct()
    bin_time = time.time() - t0

    bst = lgb.Booster(params, ds)
    t0 = time.time()
    for _ in range(warmup):
        bst.update()
    jax.block_until_ready(bst._gbdt.scores)
    warm_time = time.time() - t0

    t0 = time.time()
    for _ in range(iters):
        bst.update()
    # block via a host transfer: block_until_ready alone has proven
    # unreliable on the tunneled axon platform
    _ = np.asarray(bst._gbdt.scores[0, :8])
    dt = (time.time() - t0) / iters

    iters_per_sec = 1.0 / dt
    baseline = 500.0 / 130.094  # reference CPU Higgs iters/sec
    result = {
        "metric": "boosting_iters_per_sec_higgs_shape",
        "value": round(iters_per_sec, 4),
        "unit": "iters/sec (N=%d, 255 leaves, 63 bins)" % n,
        "vs_baseline": round(iters_per_sec / baseline, 4),
    }
    print(json.dumps(result))
    # quality sanity: held-out AUC after the benchmarked iterations — a
    # guard on the bf16-input histogram path (tpu_hist_precision default)
    try:
        pred = bst.predict(xt, raw_score=True)
        order = np.argsort(pred)
        ranks = np.empty(n_test)
        ranks[order] = np.arange(1, n_test + 1)
        pos = yt > 0.5
        auc = (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) / (
            pos.sum() * (~pos).sum())
        auc_line = f"test_auc@{warmup + iters}iters={auc:.4f}"
    except Exception as exc:  # never let the sanity check kill the bench
        auc_line = f"auc_check_failed={exc!r}"
    print(f"# bin={bin_time:.1f}s warmup+compile={warm_time:.1f}s "
          f"per_iter={dt:.3f}s {auc_line}", file=sys.stderr)


if __name__ == "__main__":
    main()
