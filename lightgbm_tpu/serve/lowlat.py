"""Dedicated low-latency predict path for small requests (B <= 64).

The streaming engine (ops/predict.py predict_raw_cached) is built for
throughput: packer token revalidation, chunk planning, double-buffered
staging. At B=1..64 that machinery costs more than the traversal, so
the server routes small requests here instead: per model, the traversal
program is AOT-compiled ONCE per (row-bucket, feature-width) via
``jax.jit(...).lower(...).compile()`` and then invoked directly as an
executable — no jit-cache lookup, no tracing, structurally zero
steady-state recompiles (the compiled handle cannot re-trace).

Rows pad up to a power-of-two bucket ({1, 2, 4, ..., max_rows}), so a
model serves any small request with at most ~7 compiled programs.
Padding rows are zeros and each row's traversal is independent, so the
sliced output is bit-identical to the batch engine's (and therefore to
``predict`` called directly) — asserted by tests/test_serve.py.

This is the AOT variant of ISSUE's low-latency options; the
``codegen.py`` tree-to-C route (now with an ``extern "C"`` batch ABI)
remains the off-process alternative for environments without jax.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..obs.metrics import global_metrics
from ..obs.xla import global_xla
from ..ops.predict import (_ARRAY_FIELDS, PackedEnsemble, _next_pow2,
                           pack_ensemble, predict_raw_multiclass)

# AOT warmup compiles are counted under this tag (the low-latency twin
# of PREDICT_TRACE_TAG); steady-state stability is asserted through
# global_metrics.recompiles(SERVE_LOWLAT_TAG)
SERVE_LOWLAT_TAG = "serve/lowlat"


class LowLatencyPredictor:
    """Per-model AOT-compiled small-batch predictor.

    Packs the ensemble once (exact shapes — a static serving model pays
    no capacity headroom) and compiles one executable per
    (row-bucket, feature-width) on first use. ``warm()`` precompiles
    the whole bucket ladder so the first real request doesn't pay it.
    """

    def __init__(self, trees: List, num_tree_per_iteration: int = 1,
                 max_rows: int = 64, average_output: bool = False):
        self._trees = trees
        self._k = max(int(num_tree_per_iteration), 1)
        self.max_rows = max(int(max_rows), 1)
        self._average_output = bool(average_output)
        self._iterations = max(len(trees) // self._k, 1)
        self._ens: PackedEnsemble = None
        self._arrs: Tuple[jax.Array, ...] = ()
        self._compiled: Dict[Tuple[int, int], object] = {}

    # ------------------------------------------------------------------
    def _ensure_packed(self) -> None:
        if self._ens is None:
            self._ens = pack_ensemble(self._trees, self._k)
            self._arrs = tuple(getattr(self._ens, f) for f in _ARRAY_FIELDS)

    @property
    def nbytes(self) -> int:
        """Device bytes held by the packed tensors (0 until first use)."""
        return sum(a.nbytes for a in self._arrs)

    def buckets(self) -> List[int]:
        """The power-of-two row-bucket ladder up to max_rows."""
        out = []
        b = 1
        while b < self.max_rows:
            out.append(b)
            b <<= 1
        out.append(self.max_rows)
        return out

    def bucket(self, rows: int) -> int:
        return min(_next_pow2(rows), self.max_rows) if rows else 1

    def _program(self, rows_bucket: int, num_features: int):
        key = (rows_bucket, num_features)
        prog = self._compiled.get(key)
        if prog is None:
            ens = self._ens

            def run(*args):
                e = PackedEnsemble(
                    *args[:-1], max_depth=ens.max_depth,
                    num_trees_per_class=ens.num_trees_per_class,
                    num_trees=ens.num_trees,
                    has_categorical=ens.has_categorical)
                return predict_raw_multiclass(e, args[-1])

            shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                      for a in self._arrs]
            shapes.append(jax.ShapeDtypeStruct(
                (rows_bucket, num_features), jnp.float32))
            t0 = time.perf_counter()
            prog = jax.jit(global_metrics.wrap_traced(SERVE_LOWLAT_TAG, run)
                           ).lower(*shapes).compile()
            if global_xla.enabled:
                # this path IS the lower/compile boundary — record the
                # executable's cost facts straight into the introspector
                global_xla.note_compile(
                    SERVE_LOWLAT_TAG, "serve",
                    f"{rows_bucket}x{num_features}",
                    time.perf_counter() - t0, prog)
            self._compiled[key] = prog
        return prog

    def warm(self, num_features: int) -> int:
        """Precompile every bucket for `num_features`-wide requests;
        returns the number of executables now resident."""
        self._ensure_packed()
        for b in self.buckets():
            self._program(b, num_features)
        return len(self._compiled)

    # ------------------------------------------------------------------
    def __call__(self, data: np.ndarray) -> np.ndarray:
        """Raw scores [B, K] float64 for B <= max_rows rows — the same
        values predict_raw_cached produces for the same rows."""
        x = np.asarray(data, np.float64)
        if x.ndim == 1:
            x = x.reshape(1, -1)
        rows, f = x.shape
        if rows > self.max_rows:
            raise ValueError(f"low-latency path takes <= {self.max_rows} "
                             f"rows, got {rows} (use the batched path)")
        self._ensure_packed()
        t0 = time.perf_counter()
        b = self.bucket(rows)
        xb = np.zeros((b, f), np.float32)
        xb[:rows] = x
        out = self._program(b, f)(*self._arrs, jnp.asarray(xb))
        out = np.asarray(out, np.float64)[:rows]
        if self._average_output:
            out /= self._iterations
        dt = time.perf_counter() - t0
        global_metrics.note_predict(rows, dt)
        global_metrics.note_latency(SERVE_LOWLAT_TAG, dt)
        return out
