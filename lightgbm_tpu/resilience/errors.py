"""Structured error taxonomy of the resilience layer.

Dependency-free on purpose: ``model_io`` (corrupt-model detection),
``serve/`` (degradation paths) and ``resilience/checkpoint.py`` all
raise these, and none of them can afford an import cycle through the
other. Every class carries machine-readable fields (byte offsets,
retry-after hints) so callers can react programmatically instead of
string-matching messages.
"""

from __future__ import annotations

from typing import Optional

# Exit code of a preemption-triggered shutdown: engine.train finished
# the in-flight iteration, wrote a checkpoint, and exited on purpose.
# 75 = BSD EX_TEMPFAIL ("temporary failure; retry") — a supervisor that
# sees it should re-run the same command, which resumes from the
# checkpoint. Distinct from 1 (crash) and 0 (done).
EXIT_PREEMPTED = 75


class CorruptModelError(ValueError):
    """A model file / string / checkpoint failed structural validation
    (truncation, garbage, digest mismatch). ``offset`` is the byte
    offset at which the content stopped making sense — for a truncated
    file that is where the missing bytes should have started.
    A ``ValueError`` so the CLI's fatal handler (and callers catching
    bad-input errors generically) see it without importing this
    module."""

    def __init__(self, message: str, offset: Optional[int] = None,
                 path: Optional[str] = None):
        self.offset = offset
        self.path = path
        where = ""
        if path:
            where += f" [{path}]"
        if offset is not None:
            where += f" (byte offset {offset})"
        super().__init__(message + where)


class CorruptCheckpointError(CorruptModelError):
    """A training checkpoint's content digest (or container structure)
    did not verify — resuming from it would silently train on torn
    state, so the loader refuses."""


class ResumeMismatchError(ValueError):
    """A checkpoint exists but was written by an incompatible run
    (different objective / tree counts / dataset shape). Mesh-shape
    drift alone is tolerated when ``tpu_elastic_resume`` is on
    (resilience/elastic.py); everything else always refuses."""


class ElasticResumeError(RuntimeError):
    """An elastic (mesh-resized) resume failed its rejoin validation:
    the drift digests of the restored state did not agree across the
    rebuilt mesh, so letting the rejoined replicas vote would fork the
    model. ``shards`` names the diverged shard ordinals."""

    def __init__(self, message: str, shards: Optional[list] = None):
        self.shards = list(shards or [])
        super().__init__(message)


class DeadlineExceeded(RuntimeError):
    """A serve request's deadline expired before (or while) it could be
    dispatched; the request failed fast instead of occupying the
    batcher. ``elapsed_s`` is how long it had been queued."""

    def __init__(self, message: str, elapsed_s: float = 0.0):
        self.elapsed_s = float(elapsed_s)
        super().__init__(message)


class ServerOverloaded(RuntimeError):
    """Admission control shed this request: the pending queue already
    holds more than ``serve_max_queue_rows`` rows. ``retry_after_s`` is
    the server's estimate of when capacity frees up (retry-after
    semantics for an HTTP front to surface as a 429/503 header)."""

    def __init__(self, message: str, retry_after_s: float = 0.05):
        self.retry_after_s = float(retry_after_s)
        super().__init__(message)


class CircuitOpenError(RuntimeError):
    """The per-model circuit breaker is open after repeated predict
    faults; requests fail fast until the half-open probe succeeds.
    ``retry_after_s`` is the time until the breaker half-opens."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        self.retry_after_s = float(retry_after_s)
        super().__init__(message)


class TransientServeError(RuntimeError):
    """A retryable serving fault (registry pack / compile hiccup, an
    injected fault-plan failure). The server's dispatch retries these
    with exponential backoff; anything else counts against the circuit
    breaker immediately."""


class DistributedInitError(RuntimeError):
    """Joining the jax.distributed runtime failed after the configured
    retry schedule (coordinator down, wrong address, handshake
    timeout). ``attempts`` is how many connection attempts were made;
    ``last_error`` carries the final underlying failure so a supervisor
    can distinguish a dead coordinator from a misconfigured rank."""

    def __init__(self, message: str, attempts: int = 1,
                 last_error: Optional[BaseException] = None):
        self.attempts = int(attempts)
        self.last_error = last_error
        super().__init__(message)


class PeerLostError(RuntimeError):
    """The training watchdog's heartbeat collective did not complete
    within ``tpu_watchdog_deadline_s`` — a peer process is hung or dead
    and every further collective would stall with it. ``deadline_s`` is
    the deadline that expired, ``iteration`` the boundary at which the
    heartbeat was attempted, and ``phase`` names the watched step.
    engine.train escalates this to checkpoint + ``EXIT_PREEMPTED`` so a
    supervisor restarts the survivors on a shrunk mesh (elastic
    resume)."""

    def __init__(self, message: str, deadline_s: float = 0.0,
                 iteration: Optional[int] = None, phase: str = "heartbeat"):
        self.deadline_s = float(deadline_s)
        self.iteration = iteration
        self.phase = str(phase)
        super().__init__(message)
