"""Multi-process training orchestration (the Dask-module analog).

The reference ships dask.py (1749 LoC) to place data partitions on
workers, build the machine list, and run socket-collective training
(ref: python-package/lightgbm/dask.py:196 _train_part, :398
_machines_to_worker_map). This module is the same orchestration story
for the TPU build's jax.distributed backend — without requiring dask in
the image: `train_distributed` spawns one worker process per data
partition on this host (or joins an existing cluster when ranks are
launched externally, e.g. one process per TPU host), wires the
coordinator/rank env, syncs binning from rank 0, trains
`tree_learner=data` across all processes, and returns the model.

For real pods, launch one process per host with LGBM_TPU_RANK set and
call `worker_train` directly — exactly how dask.py's _train_part runs
inside each dask worker.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_train(params: Dict[str, Any], X: np.ndarray, y: np.ndarray,
                 *, coordinator: str, num_workers: int, rank: int,
                 weight=None, group=None, num_boost_round: int = 100,
                 out_model: Optional[str] = None) -> Optional[str]:
    """One worker's training step (the _train_part analog,
    ref: dask.py:196): join the runtime, sync bins with rank 0, train
    data-parallel, rank 0 returns/saves the model text."""
    from . import Booster, Dataset
    from .parallel import distributed as dist

    dist.init_distributed(coordinator_address=coordinator,
                          num_processes=num_workers, process_id=rank)
    params = dict(params)
    params.setdefault("tree_learner", "data")
    params.setdefault("enable_bundle", False)  # not yet multi-host safe
    ds = Dataset(X, label=y, weight=weight, group=group,
                 params=dict(params))
    ds.construct()
    dist.sync_dataset(ds)
    bst = Booster(params, ds)
    for _ in range(num_boost_round):
        if bst.update():
            break
    if rank == 0:
        text = bst.model_to_string()
        if out_model:
            Path(out_model).write_text(text)
        return text
    return None


_WORKER_MAIN = """
import os, pickle, sys
payload = pickle.load(open(sys.argv[1], "rb"))
# env (JAX_PLATFORMS=cpu, device count, no axon plugin) is prepared by
# the parent via hostenv.cpu_child_env — one copy of that recipe
import jax
jax.config.update("jax_platforms", "cpu")
sys.path[:0] = payload["sys_path"]
from lightgbm_tpu.cluster import worker_train
rank = int(sys.argv[2])
part = payload["parts"][rank]
text = worker_train(payload["params"], part["X"], part["y"],
                    coordinator=payload["coordinator"],
                    num_workers=len(payload["parts"]), rank=rank,
                    weight=part.get("weight"), group=part.get("group"),
                    num_boost_round=payload["num_boost_round"],
                    out_model=payload["out_model"] if rank == 0 else None)
print(f"worker {rank} finished", flush=True)
"""


def train_distributed(params: Dict[str, Any], parts: List[Dict[str, Any]],
                      num_boost_round: int = 100,
                      devices_per_worker: int = 1,
                      timeout: float = 1200.0):
    """Train one model over data partitions, one local worker process
    per partition (the LocalCluster shape of the reference's dask
    tests; on real multi-host TPU, launch workers yourself and call
    `worker_train`).

    parts: list of {"X": [n_i, F], "y": [n_i], optional "weight",
    optional "group" (per-partition query sizes, for ranking)} dicts.
    Returns a Booster loaded from the distributed model.
    """
    from . import Booster

    if not parts:
        raise ValueError("no partitions")
    sizes = [np.asarray(p["X"]).shape[0] for p in parts]
    if len(set(sizes)) > 1:
        # the multi-host assembly requires equal shards per process
        # (parallel/distributed.make_global_array; the reference's
        # distributed tests pre-partition equally too)
        raise ValueError(
            f"distributed training requires equal-size partitions, got "
            f"{sizes}; repartition the input (for ranking, choose a "
            "partition count that splits the queries into equal row "
            "blocks)")
    for n in sizes:
        if n % devices_per_worker != 0:
            raise ValueError(
                f"partition of {n} rows not divisible by "
                f"{devices_per_worker} devices per worker")

    port = _free_port()
    with tempfile.TemporaryDirectory() as td:
        out_model = os.path.join(td, "model.txt")
        payload = {
            "params": dict(params),
            "parts": [{k: np.asarray(v) for k, v in p.items()
                       if v is not None}
                      for p in parts],
            "coordinator": f"127.0.0.1:{port}",
            "num_boost_round": int(num_boost_round),
            "devices_per_worker": int(devices_per_worker),
            "out_model": out_model,
            "sys_path": [str(Path(__file__).resolve().parent.parent)],
        }
        blob = os.path.join(td, "payload.pkl")
        with open(blob, "wb") as fh:
            pickle.dump(payload, fh)
        main_py = os.path.join(td, "worker_main.py")
        Path(main_py).write_text(_WORKER_MAIN)

        # per-rank log files, not PIPEs: a worker that fills a ~64KB
        # pipe buffer blocks on write inside a collective and stalls
        # the whole gang until the timeout reaps it
        logs = [open(os.path.join(td, f"worker{rank}.log"), "w+")
                for rank in range(len(parts))]
        from .hostenv import cpu_child_env
        worker_env = cpu_child_env(int(devices_per_worker))
        procs = [subprocess.Popen(
            [sys.executable, main_py, blob, str(rank)],
            stdout=log, stderr=subprocess.STDOUT, text=True,
            env=worker_env)
            for rank, log in enumerate(logs)]
        try:
            deadline = time.monotonic() + timeout
            for proc in procs:
                proc.wait(timeout=max(1.0, deadline - time.monotonic()))
            failed = [r for r, proc in enumerate(procs)
                      if proc.returncode != 0]
            if failed:
                r = failed[0]
                logs[r].seek(0)
                out = logs[r].read()
                raise RuntimeError(
                    f"distributed worker {r} failed:\n{out[-4000:]}")
        finally:
            # a crashed/timed-out rank leaves siblings blocked inside a
            # collective: always reap the whole gang
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
            for log in logs:
                log.close()
        return Booster(model_file=out_model)
