"""Named-phase wall-clock timers (ref: Common::Timer / FunctionTimer,
include/LightGBM/utils/common.h:980,1044; global_timer printed at exit
under USE_TIMETAG, src/boosting/gbdt.cpp:29).

Compatibility facade over ``obs.trace.Tracer`` — the structured span
tracer that now owns all phase timing. ``timed`` phases nest via the
tracer's span stack, so self-time is attributable (``summary()``
exposes it as ``self_seconds``). Enabled by ``LGBM_TPU_TIMETAG=1`` in
the environment or ``global_timer.enable()``; when enabled, a summary
prints at interpreter exit exactly like the reference's atexit dump.
jax device work is asynchronous — phases that must charge device time
to themselves should pass ``block=`` the arrays to wait on.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .obs.trace import Tracer, global_tracer


class Timer:
    """Thin facade: every method delegates to the span tracer."""

    def __init__(self, tracer: Optional[Tracer] = None) -> None:
        self._tracer = tracer if tracer is not None else global_tracer

    @property
    def enabled(self) -> bool:
        return self._tracer.enabled

    def enable(self) -> None:
        self._tracer.enable(print_at_exit=True)

    def reset(self) -> None:
        self._tracer.reset()

    def timed(self, name: str, block: Optional[Any] = None):
        """Time a phase (a tracer span). ``block`` (optional pytree of
        jax arrays, or a zero-arg callable returning one) is waited on
        before the clock stops, so asynchronously-dispatched device work
        is charged to the phase that launched it."""
        return self._tracer.span(name, block=block)

    def summary(self) -> Dict[str, Dict[str, float]]:
        return self._tracer.summary()

    def report(self) -> str:
        return self._tracer.report()

    def print_at_exit(self) -> None:
        # kept for API compat: print-only, like the pre-facade Timer (a
        # mid-run call must not trigger the trace export and truncate it)
        self._tracer.print_summary_once()


global_timer = Timer()
