#!/usr/bin/env python
"""Chaos validator for the serving fleet (ISSUE 17): kill a replica
under load, lose zero requests.

Spawns a REAL 3-replica subprocess fleet (``python -m
lightgbm_tpu.serve.fleet --replica ...``, each process its own
ModelServer + HTTP front), fronts it with a live ``FleetRouter``, and
drives the failure drills end-to-end:

1. **SIGKILL under load** — open-loop traffic through the router;
   one replica is SIGKILLed mid-run. Every request must still be
   served (availability >= 99.9% — the perf-gate floor — which at
   this request count means zero lost), every answer BIT-identical
   to a direct in-process predict (the pack contract that makes
   failover retries safe), and the kill must be visible in the live
   fleet ``/metrics``: the dead replica's quarantined gauge raised,
   the failover counter nonzero.
2. **SIGSTOP / SIGCONT quarantine cycle** — a stopped (not dead)
   replica times out its probes and is quarantined; after SIGCONT
   the probe loop reinstates it without operator action — both
   transitions observed in a real ``/metrics`` scrape, and the
   reinstated replica answers with the same bits again.
3. **Replica scrape aggregation** — the surviving replicas' own
   ``/metrics`` documents merge into fleet-wide totals
   (``aggregate_counter_totals``) that account for every request the
   fleet served.
4. **SIGTERM drain contract** — a surviving replica, SIGTERMed,
   drains and exits ``EXIT_PREEMPTED`` (75): the single-replica half
   of the fleet shutdown story.

Exit 0 = all steps passed. Wired into the quick verification tier via
tests/test_fleet.py.
"""

import asyncio
import os
import signal
import subprocess
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

N_REPLICAS = 3
N_REQUESTS = 80
KILL_AT = 0.4  # fraction of the trace after which the SIGKILL lands


def _fixture(n=400, f=6, seed=7):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.2 * r.randn(n) > 0.4)
    return X, y.astype(np.float32)


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
        return resp.read().decode()


def _family(text: str, name: str, labels: str = "") -> float:
    """Sum of a family's samples; `labels` filters on a substring of
    the label block (e.g. 'replica="r0"')."""
    total = 0.0
    for line in text.splitlines():
        if line.startswith("#") or not line.startswith(name):
            continue
        head, _, value = line.rpartition(" ")
        if head != name and not head.startswith(name + "{"):
            continue
        if labels and labels not in head:
            continue
        try:
            total += float(value)
        except ValueError:
            pass
    return total


def _spawn_replicas(model_path: str, n: int):
    """n subprocess replicas; returns [(proc, port)] after every one
    printed its READY rendezvous line."""
    procs = []
    for _ in range(n):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu.serve.fleet",
             "--replica", f"model={model_path}", "port=0",
             "verbosity=-1"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            cwd=REPO, env=dict(os.environ), text=True))
    out = []
    for proc in procs:
        deadline = time.time() + 120
        port = None
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("READY "):
                port = int(line.split()[1])
                break
        if port is None:
            raise AssertionError(
                f"replica pid {proc.pid} never printed READY "
                f"(rc={proc.poll()})")
        out.append((proc, port))
    return out


def _wait_for(cond, timeout_s: float, what: str) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def main() -> int:
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience.errors import EXIT_PREEMPTED
    from lightgbm_tpu.serve import (FleetRouter, HTTPReplica,
                                    ModelRegistry,
                                    aggregate_counter_totals)

    X, y = _fixture()
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, y),
                    num_boost_round=5)

    with tempfile.TemporaryDirectory() as tmpdir:
        model_path = os.path.join(tmpdir, "model.txt")
        bst.save_model(model_path)
        # the parity oracle: the same file packed in THIS process
        registry = ModelRegistry()
        registry.load("default", model_file=model_path)
        oracle = registry.get("default").model

        procs = _spawn_replicas(model_path, N_REPLICAS)
        print(f"# spawned {N_REPLICAS} subprocess replicas: "
              + " ".join(f"pid={p.pid}:port={port}"
                         for p, port in procs))
        fleet = FleetRouter(
            [HTTPReplica(f"r{i}", f"http://127.0.0.1:{port}")
             for i, (_, port) in enumerate(procs)],
            probe_interval_ms=40.0, breaker_reset_s=0.25).start()
        ep = fleet.start_metrics_endpoint(0)
        try:
            _run_drills(fleet, ep, procs, oracle, X, EXIT_PREEMPTED,
                        aggregate_counter_totals)
        finally:
            fleet.stop()
            for proc, _ in procs:
                if proc.poll() is None:
                    proc.kill()
                proc.wait()
                proc.stdout.close()
    print("# fleet chaos validator OK (4/4 steps)")
    return 0


def _run_drills(fleet, ep, procs, oracle, X, EXIT_PREEMPTED,
                aggregate_counter_totals) -> None:
    rng = np.random.RandomState(0)
    _wait_for(lambda: len(fleet.healthy_replicas()) == N_REPLICAS,
              30, "all replicas in rotation")

    # ---- step 1: SIGKILL one replica under open-loop load -----------
    sizes = rng.randint(1, 48, size=N_REQUESTS)
    starts = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    kill_idx = int(KILL_AT * N_REQUESTS)
    answers = [None] * N_REQUESTS
    failed = []

    async def one(i: int) -> None:
        await asyncio.sleep(i * 0.01)  # open loop: ~100 req/s offered
        if i == kill_idx:
            procs[0][0].kill()  # SIGKILL, not a graceful drain
        lo = int(starts[i]) % (len(X) - 48)
        try:
            answers[i] = await fleet.predict(
                "default", X[lo:lo + int(sizes[i])])
        except Exception as exc:
            failed.append((i, type(exc).__name__))

    async def load_phase() -> None:
        await asyncio.gather(*[one(i) for i in range(N_REQUESTS)])

    asyncio.run(load_phase())
    served = N_REQUESTS - len(failed)
    availability = served / N_REQUESTS
    assert availability >= 0.999, (
        f"lost {len(failed)}/{N_REQUESTS} requests across the SIGKILL "
        f"(availability {availability:.4%}): {failed[:5]}")
    for i in range(N_REQUESTS):
        lo = int(starts[i]) % (len(X) - 48)
        expect = oracle.predict(X[lo:lo + int(sizes[i])])
        assert np.array_equal(np.asarray(answers[i]),
                              np.asarray(expect)), (
            f"request {i} served across the kill is NOT bit-identical "
            "to a direct predict")
    _wait_for(lambda: fleet.stats()["replicas"]["r0"]["quarantined"],
              10, "the killed replica's quarantine")
    text = _scrape(ep.port)
    assert _family(text, "lgbmtpu_fleet_replica_quarantined",
                   'replica="r0"') == 1, \
        "killed replica not quarantined in the live /metrics scrape"
    failovers = _family(text, "lgbmtpu_fleet_failovers_total")
    quarantines = _family(text, "lgbmtpu_fleet_quarantines_total")
    assert failovers >= 1, "SIGKILL produced no failover counter"
    assert quarantines >= 1, "SIGKILL produced no quarantine counter"
    print(f"# step 1 OK: SIGKILL@{kill_idx}/{N_REQUESTS} -> "
          f"{served}/{N_REQUESTS} served ({availability:.4%}), all "
          f"bit-identical; /metrics shows r0 quarantined, "
          f"{failovers:.0f} failover(s)")

    # ---- step 2: SIGSTOP/SIGCONT quarantine + reinstate cycle -------
    os.kill(procs[1][0].pid, signal.SIGSTOP)
    try:
        _wait_for(
            lambda: fleet.stats()["replicas"]["r1"]["quarantined"],
            15, "the stopped replica's quarantine")
        assert _family(_scrape(ep.port),
                       "lgbmtpu_fleet_replica_quarantined",
                       'replica="r1"') == 1, \
            "stopped replica not quarantined in the live scrape"
        out = asyncio.run(fleet.predict("default", X[:8]))
        assert np.array_equal(np.asarray(out),
                              np.asarray(oracle.predict(X[:8]))), \
            "predict during the SIGSTOP window lost bit parity"
    finally:
        os.kill(procs[1][0].pid, signal.SIGCONT)
    _wait_for(
        lambda: not fleet.stats()["replicas"]["r1"]["quarantined"],
        15, "the resumed replica's reinstatement")
    text = _scrape(ep.port)
    assert _family(text, "lgbmtpu_fleet_replica_quarantined",
                   'replica="r1"') == 0, \
        "resumed replica still quarantined in the live scrape"
    assert _family(text, "lgbmtpu_fleet_reinstates_total") >= 1, \
        "SIGCONT produced no reinstate counter"
    out = asyncio.run(fleet.predict("default", X[:8]))
    assert np.array_equal(np.asarray(out),
                          np.asarray(oracle.predict(X[:8]))), \
        "predict after reinstatement lost bit parity"
    print("# step 2 OK: SIGSTOP -> quarantined, SIGCONT -> reinstated "
          "(both in live /metrics), bits unchanged throughout")

    # ---- step 3: replica scrape aggregation -------------------------
    totals = aggregate_counter_totals(fleet.scrape_replicas())
    served_by_replicas = totals.get("lgbmtpu_serve_requests_total", 0.0)
    assert served_by_replicas >= N_REQUESTS - kill_idx, (
        f"survivor replicas account for only {served_by_replicas:.0f} "
        "served requests in their own /metrics")
    print(f"# step 3 OK: surviving replicas' scrapes aggregate to "
          f"{served_by_replicas:.0f} lgbmtpu_serve_requests_total")

    # ---- step 4: SIGTERM drain contract on a survivor ---------------
    survivor = procs[2][0]
    survivor.terminate()  # SIGTERM: drain, deregister, exit 75
    rc = survivor.wait(timeout=60)
    assert rc == EXIT_PREEMPTED, (
        f"SIGTERMed replica exited {rc}, expected EXIT_PREEMPTED "
        f"({EXIT_PREEMPTED})")
    print(f"# step 4 OK: SIGTERM -> graceful drain -> exit "
          f"{EXIT_PREEMPTED}")


if __name__ == "__main__":
    sys.exit(main())
