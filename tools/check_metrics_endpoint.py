#!/usr/bin/env python
"""CI smoke for the serving observability endpoints (obs/export.py).

Trains a small model, starts the in-process async server plus its
``/metrics``+``/healthz``+``/readyz`` HTTP endpoint, then asserts:

1. ``/healthz`` answers 200 from the moment the listener is up and
   stays 200 throughout (liveness is the listener, nothing else);
2. ``/readyz`` flips to 503 while ``warm()`` is in flight (readiness
   gates traffic on the warmed program set) and back to 200 after;
3. after serving mixed-size concurrent requests, ``/metrics`` is
   valid Prometheus text format LINE BY LINE (every sample parses,
   every family has a TYPE header, summary quantile labels present)
   and exposes the request-latency quantiles, the serve/registry
   counters, and the predict throughput series;
4. the exposition is ``# EOF``-terminated (OpenMetrics 1.0 — the
   terminator parses as a comment under Prometheus 0.0.4, so one body
   serves both) and the endpoint negotiates the content type off the
   Accept header: ``application/openmetrics-text`` requests get the
   OpenMetrics media type, everything else the 0.0.4 text type.

Exit 0 = pass. Usage: python tools/check_metrics_endpoint.py
"""

from __future__ import annotations

import asyncio
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# one Prometheus text-format sample:  name{labels} value [timestamp]
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[+-]?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|[Nn]a[Nn]"
    r"|[+-]?[Ii]nf))"
    r"(?: [0-9]+)?$")
_LABEL = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')
_TYPES = {"counter", "gauge", "summary", "histogram", "untyped"}


def validate_exposition(text: str) -> Tuple[List[str], Dict[str, str]]:
    """-> (errors, {family: type}) for a Prometheus text document.
    Importable for tests; validates line by line."""
    errors: List[str] = []
    families: Dict[str, str] = {}
    for i, line in enumerate(text.splitlines()):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in _TYPES:
                errors.append(f"line {i}: malformed TYPE header: {line!r}")
            else:
                families[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = _SAMPLE.match(line)
        if not m:
            errors.append(f"line {i}: unparseable sample: {line!r}")
            continue
        labels = m.group("labels")
        if labels:
            for pair in _split_labels(labels[1:-1]):
                if pair and not _LABEL.match(pair):
                    errors.append(f"line {i}: bad label pair {pair!r}")
        name = m.group("name")
        base = re.sub(r"_(sum|count|bucket)$", "", name)
        if name not in families and base not in families:
            # every sample must belong to a TYPE-declared family
            errors.append(f"line {i}: sample {name!r} has no TYPE header")
    return errors, families


def _split_labels(body: str) -> List[str]:
    """Split `a="x",b="y,z"` on commas outside quotes."""
    out, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            cur.append(ch)
            esc = True
        elif ch == '"':
            cur.append(ch)
            in_q = not in_q
        elif ch == "," and not in_q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _get(port: int, path: str,
         accept: str = None) -> Tuple[int, str, str]:
    """-> (status, body, content-type); `accept` rides the Accept
    header so the negotiation checks can ask for OpenMetrics."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        headers={"Accept": accept} if accept else {})
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return (resp.status, resp.read().decode(),
                    resp.headers.get("Content-Type", ""))
    except urllib.error.HTTPError as exc:
        return (exc.code, exc.read().decode(),
                exc.headers.get("Content-Type", ""))


def main() -> int:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve import ModelRegistry, ModelServer
    from lightgbm_tpu.serve.server import replay

    rng = np.random.RandomState(0)
    n, f = 600, 8
    x = rng.randn(n, f)
    y = ((x[:, 2] + x[:, 4]) > 0.3).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                    num_boost_round=5)

    registry = ModelRegistry()
    registry.load("smoke", booster=bst)
    server = ModelServer(registry, max_batch_rows=1024, max_wait_ms=1.0)
    endpoint = server.start_metrics_endpoint(port=0)
    failures = 0

    code, _, _ = _get(endpoint.port, "/healthz")
    if code != 200:
        print(f"FAIL: /healthz returned {code} before warm")
        failures += 1

    # readiness must flip 503 while warm() is in flight. warm() on a
    # tiny CPU model can be near-instant, so inject a deterministic
    # delay into the lowlat ladder it compiles through.
    entry = registry.get("smoke")
    lowlat = entry.lowlat
    orig_warm = lowlat.warm

    def slow_warm(num_features: int) -> int:
        time.sleep(0.3)
        return orig_warm(num_features)

    lowlat.warm = slow_warm
    warm_thread = threading.Thread(target=server.warm, args=("smoke", f))
    warm_thread.start()
    saw_unready = False
    deadline = time.time() + 10
    while warm_thread.is_alive() and time.time() < deadline:
        code, _, _ = _get(endpoint.port, "/readyz")
        if code == 503:
            saw_unready = True
        code_h, _, _ = _get(endpoint.port, "/healthz")
        if code_h != 200:
            print(f"FAIL: /healthz returned {code_h} during warm")
            failures += 1
            break
        time.sleep(0.01)
    warm_thread.join()
    lowlat.warm = orig_warm
    if not saw_unready:
        print("FAIL: /readyz never returned 503 during warm()")
        failures += 1
    code, _, _ = _get(endpoint.port, "/readyz")
    if code != 200:
        print(f"FAIL: /readyz returned {code} after warm completed")
        failures += 1

    # drive mixed traffic so the latency reservoirs and counters fill
    sizes = [1, 8, 130, 3, 64, 300, 16, 2]
    xt = rng.randn(sum(sizes), f)

    async def run():
        return await replay(server, "smoke", xt, sizes, raw_score=True)

    asyncio.run(run())

    code, body, ctype = _get(endpoint.port, "/metrics")
    if code != 200:
        print(f"FAIL: /metrics returned {code}")
        failures += 1
        body = ""
    errors, families = validate_exposition(body)
    for e in errors[:10]:
        print(f"FAIL: {e}")
    failures += len(errors)

    required = [
        'lgbmtpu_latency_seconds{name="serve/request",quantile="0.99"}',
        'lgbmtpu_latency_seconds_count{name="serve/request"}',
        "lgbmtpu_serve_requests_total",
        "lgbmtpu_serve_registry_hit_total",
        "lgbmtpu_predict_rows_total",
        "lgbmtpu_host_info",
        "lgbmtpu_serve_pack_bytes",
    ]
    for needle in required:
        if needle not in body:
            print(f"FAIL: /metrics is missing {needle!r}")
            failures += 1

    # OpenMetrics terminator + Accept negotiation (obs/export.py)
    if body and body.splitlines()[-1].strip() != "# EOF":
        print("FAIL: /metrics exposition is not '# EOF'-terminated")
        failures += 1
    if not ctype.startswith("text/plain"):
        print(f"FAIL: default /metrics content type {ctype!r} is not "
              "the Prometheus 0.0.4 text type")
        failures += 1
    code, om_body, om_ctype = _get(
        endpoint.port, "/metrics",
        accept="application/openmetrics-text; version=1.0.0")
    if code != 200 or not om_ctype.startswith(
            "application/openmetrics-text"):
        print(f"FAIL: OpenMetrics Accept negotiated {code}/{om_ctype!r}")
        failures += 1
    if om_body and om_body.splitlines()[-1].strip() != "# EOF":
        print("FAIL: OpenMetrics body is not '# EOF'-terminated")
        failures += 1

    asyncio.run(server.close())
    if failures:
        print(f"check_metrics_endpoint: {failures} failure(s)")
        return 1
    print(f"check_metrics_endpoint: OK ({len(body.splitlines())} lines, "
          f"{len(families)} metric families, readiness flipped around "
          f"warm())")
    return 0


if __name__ == "__main__":
    sys.exit(main())
