"""Device-time attribution (obs/profile.py), the crash flight
recorder (obs/flightrec.py), the ``lgbmtpu_profile_*`` egress, the
Chrome-trace device lane, perf-gate check 11, concurrent /metrics
scrapes under live training, and the bench trend report."""

import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.export import (MetricsHTTPEndpoint,
                                     OPENMETRICS_CONTENT_TYPE,
                                     negotiate_content_type,
                                     render_openmetrics)
from lightgbm_tpu.obs.flightrec import (FORMAT, FlightRecorder,
                                        global_flightrec, validate_dump)
from lightgbm_tpu.obs.metrics import global_metrics
from lightgbm_tpu.obs.profile import (DEVICE_LANE_NAME, global_profile,
                                      parse_trace_events)
from lightgbm_tpu.obs.xla import global_xla, instrumented_jit

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from check_metrics_endpoint import validate_exposition  # noqa: E402

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def _clean_obs_state():
    yield
    # global_metrics.enable() cascades to the tracer / watermarks / xla
    # / health registries but disable() does not — restore the whole
    # fan-out or the next test file inherits an armed tracer
    from lightgbm_tpu.obs.health import global_health
    from lightgbm_tpu.obs.memory import global_watermarks
    from lightgbm_tpu.obs.trace import global_tracer
    global_profile.reset()
    global_flightrec.reset()
    global_metrics.reset()
    global_metrics.disable()
    global_xla.reset()
    global_xla.disable()
    global_tracer.disable()
    global_tracer.reset()
    global_watermarks.disable()
    global_health.reset()
    global_health.disable()


def _binary_fixture(n=400, f=6, seed=0):
    r = np.random.RandomState(seed)
    x = r.randn(n, f)
    y = ((x[:, 1] + x[:, 3]) > 0.2).astype(np.float64)
    return x, y


# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_bound_and_dropped_count(self, tmp_path):
        rec = FlightRecorder(capacity=16)
        rec.enable(path=str(tmp_path / "fr.json"))
        for i in range(40):
            rec.record("iteration", iteration=i, trees=i)
        assert len(rec.events()) == 16
        path = rec.dump(reason="test")
        doc = json.load(open(path))
        assert validate_dump(doc) == []
        assert doc["format"] == FORMAT
        assert doc["n_recorded"] == 40
        assert doc["n_dropped"] == 24
        # the ring kept the NEWEST events — a black box records the end
        assert doc["events"][-1]["iteration"] == 39

    def test_record_accepts_any_payload(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        rec.enable(path=str(tmp_path / "fr.json"))
        rec.record("serve_request", model="m",
                   weird=object(), arr=np.arange(3), nested={"a": (1, 2)})
        doc = json.load(open(rec.dump(reason="test")))
        assert validate_dump(doc) == []

    def test_disarmed_records_nothing(self):
        rec = FlightRecorder(capacity=8)
        rec.record("iteration", iteration=0)
        assert rec.events() == []
        assert rec.maybe_dump(reason="x") is None

    def test_maybe_dump_needs_events(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        rec.enable(path=str(tmp_path / "fr.json"))
        assert rec.maybe_dump(reason="empty") is None
        rec.record("checkpoint", iteration=3, path="/x")
        assert rec.maybe_dump(reason="full") is not None

    def test_validate_dump_flags_violations(self):
        assert validate_dump([]) != []
        assert any("format" in e for e in validate_dump(
            {"format": "bogus", "reason": "r", "dumped_at_unix": 1.0,
             "n_recorded": 0, "n_dropped": 0, "events": []}))
        bad_seq = {"format": FORMAT, "reason": "r",
                   "dumped_at_unix": 1.0, "n_recorded": 2,
                   "n_dropped": 0,
                   "events": [{"seq": 5, "ts_unix": 1.0, "kind": "a"},
                              {"seq": 4, "ts_unix": 1.0, "kind": "b"}]}
        assert any("not increasing" in e for e in validate_dump(bad_seq))

    def test_train_records_iterations_and_checkpoints(self, tmp_path):
        x, y = _binary_fixture()
        ckpt = str(tmp_path / "t.ckpt")
        global_flightrec.enable(path=str(tmp_path / "fr.json"))
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "tpu_checkpoint_path": ckpt,
                  "tpu_checkpoint_every": 2}
        lgb.train(params, lgb.Dataset(x, label=y, params=params),
                  num_boost_round=4)
        kinds = [e["kind"] for e in global_flightrec.events()]
        assert kinds.count("iteration") == 4
        assert "checkpoint" in kinds


# ---------------------------------------------------------------------------
class TestParseTraceEvents:
    def test_device_pid_filter_and_name_attribution(self):
        events = [
            {"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "python host"}},
            {"ph": "X", "name": "jit__fused_iter_impl.33", "pid": 7,
             "ts": 100.0, "dur": 2000.0},
            {"ph": "X", "name": "jit__fused_iter_impl.33", "pid": 1,
             "ts": 100.0, "dur": 9000.0},  # host copy: ignored
            {"ph": "X", "name": "unrelated_kernel", "pid": 7,
             "ts": 200.0, "dur": 500.0},
        ]
        secs, slices = parse_trace_events(
            events, {"_fused_iter_impl": "boosting/fused_iter"})
        assert secs == {"boosting/fused_iter": pytest.approx(0.002)}
        assert slices == [("boosting/fused_iter", 100.0, 2000.0)]

    def test_no_device_pid_counts_every_pid(self):
        events = [{"ph": "X", "name": "jit_foo", "pid": 1,
                   "ts": 0.0, "dur": 1000.0}]
        secs, _ = parse_trace_events(events, {"foo": "t/foo"})
        assert secs == {"t/foo": pytest.approx(0.001)}

    def test_longest_registered_name_wins(self):
        events = [{"ph": "X", "name": "jit__grow_wave_impl", "pid": 1,
                   "ts": 0.0, "dur": 1000.0}]
        secs, _ = parse_trace_events(
            events, {"_grow": "short/tag",
                     "_grow_wave_impl": "long/tag"})
        assert list(secs) == ["long/tag"]


# ---------------------------------------------------------------------------
class TestProfileWindow:
    def test_window_lifecycle_idempotent(self):
        global_profile.reset()
        global_profile.start_window()
        global_profile.start_window()  # no nested window
        s = global_profile.stop_window()
        assert s["n_windows"] == 1
        s2 = global_profile.stop_window()  # idempotent
        assert s2["n_windows"] == 1
        assert s2["window_wall_s"] == pytest.approx(
            s["window_wall_s"], abs=1e-3)

    def test_timed_dispatch_attribution_and_bit_identity(self):
        import jax.numpy as jnp
        global_xla.enable()  # AOT entries are what stop_window reruns

        def _sq(v):
            return jnp.sum(v * v)

        fn = instrumented_jit("test/profile_sq", _sq, phase="train")
        v = jnp.arange(128, dtype=jnp.float32)
        off = fn(v)  # compile + run outside any window
        global_profile.reset()
        global_profile.start_window()
        on = fn(v)
        on2 = fn(v)
        s = global_profile.stop_window()
        assert float(on) == float(off) == float(on2)  # sync, no values
        assert s["device_seconds_by_tag"]["test/profile_sq"] > 0.0
        assert s["calls_by_tag"]["test/profile_sq"] == 2
        assert s["phase_by_tag"]["test/profile_sq"] == "train"
        assert s["source"] == "fallback"
        # the retained executable was micro-rerun at window close
        assert s["rerun_seconds_by_tag"]["test/profile_sq"] >= 0.0

    def test_no_capture_outside_window(self):
        import jax.numpy as jnp
        fn = instrumented_jit("test/profile_idle", lambda v: v + 1)
        global_profile.reset()
        fn(jnp.arange(8))
        s = global_profile.summary()
        assert "test/profile_idle" not in s["device_seconds_by_tag"]

    def test_summary_live_while_capturing(self):
        global_profile.reset()
        global_profile.start_window()
        s = global_profile.summary()
        assert s["window_wall_s"] >= 0.0
        assert global_profile.capturing
        global_profile.stop_window()


# ---------------------------------------------------------------------------
class TestRoofline:
    def test_platform_peaks_table_and_env_override(self, monkeypatch):
        from lightgbm_tpu.hostenv import platform_peaks
        cpu, tpu = platform_peaks("cpu"), platform_peaks("tpu")
        assert 0 < cpu["flops_per_s"] < tpu["flops_per_s"]
        assert 0 < cpu["bytes_per_s"] < tpu["bytes_per_s"]
        assert platform_peaks("unknown") == tpu  # conservative default
        monkeypatch.setenv("LGBM_TPU_PEAK_FLOPS", "1e9")
        monkeypatch.setenv("LGBM_TPU_PEAK_BYTES_PER_S", "2e9")
        over = platform_peaks("cpu")
        assert over["flops_per_s"] == pytest.approx(1e9)
        assert over["bytes_per_s"] == pytest.approx(2e9)

    def test_join_with_cost_analysis(self):
        import jax.numpy as jnp
        global_xla.enable()

        def _mm(a):
            return a @ a

        fn = instrumented_jit("test/roofline_mm", _mm, phase="train")
        a = jnp.ones((64, 64), dtype=jnp.float32)
        global_profile.reset()
        global_profile.start_window()
        fn(a)
        global_profile.stop_window()
        rl = global_profile.roofline(
            platform="cpu",
            peaks={"bytes_per_s": 1e10, "flops_per_s": 1e11})
        row = rl["by_tag"]["test/roofline_mm"]
        assert row["device_s"] > 0 and row["calls"] == 1
        assert rl["peaks"]["bytes_per_s"] == 1e10
        assert rl["ridge_flops_per_byte"] == pytest.approx(10.0)
        if "bytes_per_call" in row:  # backend exposed cost analysis
            assert row["achieved_bytes_per_s"] > 0
            assert row["bytes_utilization"] > 0
            assert row["verdict"] in ("memory-bound", "compute-bound")
        else:
            assert row["verdict"] == "unknown"

    def test_fields_absent_when_unattributable(self):
        global_profile.reset()
        global_profile.start_window()
        rl_empty = global_profile.roofline(
            platform="cpu", peaks={"bytes_per_s": 1.0,
                                   "flops_per_s": 1.0})
        global_profile.stop_window()
        assert rl_empty["by_tag"] == {}


# ---------------------------------------------------------------------------
class TestTrainKnob:
    def test_window_knob_attributes_and_preserves_model(self):
        x, y = _binary_fixture()
        base = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
        params = dict(base, tpu_profile="window", tpu_profile_window=2)
        global_profile.reset()
        bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                        num_boost_round=5)
        s = global_profile.stop_window()
        assert any(t.startswith("boosting/")
                   for t in s["device_seconds_by_tag"])
        assert s["mode"] == "window"
        assert 0.0 < s["coverage"] <= 1.5
        global_profile.reset()
        bst_off = lgb.train(base,
                            lgb.Dataset(x, label=y, params=base),
                            num_boost_round=5)

        def strip(m):
            return "\n".join(l for l in m.splitlines()
                             if not l.startswith("[tpu_profile"))

        assert strip(bst.model_to_string()) == \
            strip(bst_off.model_to_string())

    def test_bench_knob_leaves_window_open(self):
        x, y = _binary_fixture(n=200)
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "tpu_profile": "bench"}
        global_profile.reset()
        lgb.train(params, lgb.Dataset(x, label=y, params=params),
                  num_boost_round=2)
        assert global_profile.capturing  # bench mode: caller closes
        s = global_profile.stop_window()
        assert s["mode"] == "bench"
        # bench windows open at iteration 0: both iterations attributed
        assert sum(s["calls_by_tag"].values()) >= 2

    def test_bad_knob_rejected(self):
        x, y = _binary_fixture(n=120)
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "tpu_profile": "sometimes"}
        with pytest.raises(ValueError, match="tpu_profile"):
            lgb.train(params, lgb.Dataset(x, label=y, params=params),
                      num_boost_round=1)


# ---------------------------------------------------------------------------
class TestExport:
    def test_negotiation(self):
        assert negotiate_content_type(
            "application/openmetrics-text; version=1.0.0"
        ) == OPENMETRICS_CONTENT_TYPE
        assert negotiate_content_type("text/plain").startswith(
            "text/plain")
        assert negotiate_content_type(None).startswith("text/plain")

    def test_document_is_eof_terminated(self):
        text = render_openmetrics()
        assert text.splitlines()[-1] == "# EOF"
        assert validate_exposition(text)[0] == []

    def test_profile_families_present_after_capture(self):
        import jax.numpy as jnp
        fn = instrumented_jit("test/export_prof", lambda v: v * 2)
        global_profile.reset()
        global_profile.start_window()
        fn(jnp.arange(16))
        global_profile.stop_window()
        text = render_openmetrics()
        errors, families = validate_exposition(text)
        assert errors == []
        for fam in ("lgbmtpu_profile_window_seconds",
                    "lgbmtpu_profile_coverage",
                    "lgbmtpu_profile_device_seconds_total",
                    "lgbmtpu_profile_calls_total"):
            assert fam in families, fam
        assert 'tag="test/export_prof"' in text

    def test_no_capture_no_profile_families(self):
        global_profile.reset()
        assert "lgbmtpu_profile_" not in render_openmetrics()


# ---------------------------------------------------------------------------
class TestChromeDeviceLane:
    def test_device_lane_merged_and_trace_valid(self, tmp_path):
        from lightgbm_tpu.obs.trace import Tracer, global_tracer
        from check_trace import check_trace
        x, y = _binary_fixture()
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "tpu_profile": "window",
                  "tpu_profile_window": 2}
        global_tracer.enable()
        try:
            global_profile.reset()
            lgb.train(params, lgb.Dataset(x, label=y, params=params),
                      num_boost_round=4)
            global_profile.stop_window()
            path = str(tmp_path / "trace.json")
            global_tracer.export_chrome(path)
        finally:
            global_tracer.disable()
            global_tracer.reset()
        ok, msg = check_trace(path)
        assert ok, msg
        assert "device-lane slice" in msg
        doc = json.load(open(path))
        lane_pids = {e["pid"] for e in doc["traceEvents"]
                     if e.get("ph") == "M"
                     and e.get("name") == "process_name"
                     and e["args"]["name"] == DEVICE_LANE_NAME}
        assert len(lane_pids) == 1
        spans = [e for e in doc["traceEvents"]
                 if e.get("ph") == "X" and e["pid"] in lane_pids]
        assert spans and all(e["args"]["source"] == "fallback"
                             for e in spans)

    def test_no_slices_no_lane(self):
        global_profile.reset()
        assert global_profile.device_lane_events(pid=99) == []


# ---------------------------------------------------------------------------
class TestConcurrentScrapes:
    def test_scrapes_stay_valid_during_live_training(self):
        """Satellite 3: a ThreadingHTTPServer scrape racing live
        train_one_iter counter updates must never return a torn or
        invalid exposition — every body lints line-by-line and stays
        EOF-terminated."""
        global_metrics.enable()
        endpoint = MetricsHTTPEndpoint(render_openmetrics, port=0)
        stop = threading.Event()
        bodies, errors = [], []

        def scrape_loop():
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{endpoint.port}/metrics",
                            timeout=5) as resp:
                        body = resp.read().decode()
                except Exception as exc:  # pragma: no cover
                    errors.append(f"scrape failed: {exc}")
                    return
                lint, _ = validate_exposition(body)
                if lint:
                    errors.append(f"torn exposition: {lint[:3]}")
                if body.splitlines()[-1] != "# EOF":
                    errors.append("missing # EOF terminator")
                bodies.append(body)

        threads = [threading.Thread(target=scrape_loop)
                   for _ in range(4)]
        for t in threads:
            t.start()
        try:
            x, y = _binary_fixture(n=600)
            params = {"objective": "binary", "num_leaves": 15,
                      "verbosity": -1}
            lgb.train(params, lgb.Dataset(x, label=y, params=params),
                      num_boost_round=8)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            endpoint.close()
        assert errors == []
        assert len(bodies) >= 8  # the race actually ran


# ---------------------------------------------------------------------------
class TestPerfGateCheck11:
    def _floor(self):
        return {"profile": {"min_coverage": 0.2, "max_coverage": 1.5,
                            "min_utilization": 1e-6}}

    def _candidate(self, tmp_path, coverage=0.6, util=0.01):
        # vs_baseline matches the best recorded cpu round so the
        # trajectory check (gate check 3) stays green for this
        # synthetic candidate whatever the repo's bench history holds
        import check_perf_gate as gate
        best = max([r.get("vs_baseline", 0.0) or 0.0
                    for _, r in gate._load_bench_lines()
                    if gate._platform_of(r.get("unit", "")) == "cpu"],
                   default=1.0)
        rec = {"metric": "boosting_iters_per_sec_higgs_shape",
               "value": 1.0, "vs_baseline": best or 1.0,
               "unit": "iters/sec (platform=cpu)",
               "device_seconds_by_tag": {"boosting/fused_iter": 0.5},
               "roofline": {
                   "platform": "cpu", "coverage": coverage,
                   "peaks": {"bytes_per_s": 1e10, "flops_per_s": 1e11},
                   "by_tag": {"boosting/fused_iter": {
                       "device_s": 0.5, "calls": 3, "phase": "train",
                       "bytes_utilization": util,
                       "verdict": "memory-bound"}}}}
        path = tmp_path / "CAND.json"
        path.write_text(json.dumps(rec))
        return str(path)

    def test_pass_fail_and_skip(self, tmp_path):
        from check_perf_gate import check_profile_roofline
        floor = self._floor()
        failures = []
        check_profile_roofline(floor, failures,
                               self._candidate(tmp_path))
        assert failures == []
        check_profile_roofline(floor, failures,
                               self._candidate(tmp_path, coverage=0.01))
        assert len(failures) == 1 and "coverage" in failures[0]
        failures = []
        check_profile_roofline(floor, failures,
                               self._candidate(tmp_path, util=1e-9))
        assert len(failures) == 1 and "utilization" in failures[0]
        failures = []
        check_profile_roofline({}, failures,
                               self._candidate(tmp_path))
        assert failures == []  # no floor section -> skip

    def test_gate_main_passes_on_repo_state(self, tmp_path):
        from check_perf_gate import main as gate_main
        assert gate_main([self._candidate(tmp_path)]) == 0


# ---------------------------------------------------------------------------
class TestBenchReport:
    def test_collect_fishes_both_shapes(self, tmp_path):
        import bench_report
        bare = {"metric": "m", "value": 1.0,
                "unit": "iters/sec (platform=cpu)"}
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(bare))
        line = json.dumps(dict(bare, value=2.0))
        wrapper = {"n": 2, "cmd": "bench", "rc": 0,
                   "tail": f"noise\n{line}\n"}
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(wrapper))
        (tmp_path / "MULTICHIP_r01.json").write_text(
            json.dumps({"rc": 1, "skipped": True, "tail": "no line"}))
        recs = bench_report.collect(repo=str(tmp_path))
        assert [(f, r["value"]) for f, r in recs] == [
            ("BENCH_r01.json", 1.0), ("BENCH_r02.json", 2.0)]

    def test_regression_flagged_across_trajectory(self):
        import bench_report
        recs = [("BENCH_r01.json", {"metric": "m", "value": 1.0,
                                    "unit": "u (platform=cpu)"}),
                ("BENCH_r02.json", {"metric": "m", "value": 0.5,
                                    "unit": "u (platform=cpu)"})]
        report = bench_report.build_report(recs, max_drop=0.10)
        assert len(report["regressions"]) == 1
        assert "BENCH_r02.json" in report["regressions"][0]
        md = bench_report.render_markdown(report)
        assert "REGRESSION" in md
        clean = bench_report.build_report(recs[:1], max_drop=0.10)
        assert clean["regressions"] == []
        assert "No rounds below" in bench_report.render_markdown(clean)

    def test_report_on_repo_records_runs(self):
        import bench_report
        report = bench_report.build_report(bench_report.collect(), 0.10)
        bench_report.render_markdown(report)  # must not raise


# ---------------------------------------------------------------------------
class TestCheckProfileTool:
    def test_validator_passes(self):
        """The quick-tier wiring for tools/check_profile.py: the full
        fallback-attribution + roofline + egress + bit-identity +
        flight-recorder pipeline on the CPU fixture."""
        import check_profile
        assert check_profile.main() == 0
