"""Multi-tenant model registry with LRU pack eviction.

A serving process holds many named models, but the expensive part of a
resident model is not the host ``Tree`` list — it is the packed
``[T, ...]`` ensemble tensors (host numpy in the ``EnsemblePacker`` +
their device mirrors) and the AOT low-latency executables. The registry
therefore evicts PACKS, not models: over the ``max_pack_bytes`` budget
the least-recently-used model's packed tensors and compiled small-batch
programs are dropped, while the host model stays loaded. The next
request against an evicted model transparently re-packs (re-importing
its low-latency executables from the serialized artifact store when an
``artifact_dir`` is configured — serve/artifacts.py — instead of paying
the warmup compiles again) and — because packing is deterministic and the
``(tree, pack_version)`` identity tokens are revalidated on every
``EnsemblePacker.update`` — produces bit-identical predictions
(asserted by tests/test_serve.py).

Hit / miss / eviction counts are exported through the always-on
``obs.metrics.global_metrics`` counters:

- ``serve/registry_hit`` / ``serve/registry_miss``
- ``serve/pack_evictions`` / ``serve/evicted_bytes``
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as np

from ..obs.metrics import global_metrics
from .lowlat import LowLatencyExplainer, LowLatencyPredictor


class ServedModel:
    """One registry entry: a loaded model plus its serving state (the
    lazily-built low-latency predictor + explainer). Create via
    ModelRegistry.load."""

    def __init__(self, name: str, model, lowlat_max_rows: int = 64,
                 artifact_dir: str = ""):
        self.name = name
        self.model = model  # model_io.LoadedModel
        self.lowlat_max_rows = int(lowlat_max_rows)
        # serialized-AOT artifact directory (serve/artifacts.py): the
        # low-latency predictor writes its compiled executables through
        # to disk and re-creation (LRU re-admission, replica restart)
        # loads them back instead of recompiling
        self.artifact_dir = str(artifact_dir or "")
        self._lowlat: Optional[LowLatencyPredictor] = None
        self._explainer: Optional[LowLatencyExplainer] = None
        # linear-tree leaves predict on host (the engine has no linear
        # path) — such models always route through predict_raw; they
        # can't explain at all (pred_contrib raises the reference's
        # linear-tree restriction), so the explain route shares the gate
        self.supports_lowlat = not any(
            getattr(t, "is_linear", False) for t in model.trees)
        self.supports_explain = self.supports_lowlat

    # -- prediction entries (raw [B, K] float64) -----------------------
    def predict_raw(self, data: np.ndarray) -> np.ndarray:
        """Full-model raw scores through the streaming engine — the
        micro-batcher's dispatch function."""
        return self.model.predict_raw(data)

    def lowlat_predict(self, data: np.ndarray) -> np.ndarray:
        """Raw scores through the AOT small-batch path (B <= 64-ish)."""
        return self.lowlat(data)

    def explain_raw(self, data: np.ndarray) -> np.ndarray:
        """[B, K * (F + 1)] SHAP contributions through the streaming
        device kernel — the explain micro-batcher's dispatch function.
        Bit-identical to Booster.predict(pred_contrib=True)."""
        return self.model.predict_contrib(data)

    # -- serve dispatch twins: the ModelServer routes through these so
    # the deterministic fault plan (resilience/faults.py) can inject
    # transient pack/compile failures and executor-occupying slowness
    # at the exact point real ones surface. Bound methods on purpose:
    # the batcher re-binds on entry identity via __self__.
    def dispatch_raw(self, data: np.ndarray) -> np.ndarray:
        from ..resilience import faults as faults_mod
        if faults_mod.global_faults.armed:
            faults_mod.global_faults.check_serve_dispatch(self.name)
        return self.model.predict_raw(data)

    def dispatch_lowlat(self, data: np.ndarray) -> np.ndarray:
        from ..resilience import faults as faults_mod
        if faults_mod.global_faults.armed:
            faults_mod.global_faults.check_serve_dispatch(self.name)
        return self.lowlat(data)

    def dispatch_explain(self, data: np.ndarray) -> np.ndarray:
        from ..resilience import faults as faults_mod
        if faults_mod.global_faults.armed:
            faults_mod.global_faults.check_serve_dispatch(self.name)
        return self.model.predict_contrib(data)

    def dispatch_lowlat_explain(self, data: np.ndarray) -> np.ndarray:
        from ..resilience import faults as faults_mod
        if faults_mod.global_faults.armed:
            faults_mod.global_faults.check_serve_dispatch(self.name)
        return self.explainer(data)

    @property
    def lowlat(self) -> LowLatencyPredictor:
        if self._lowlat is None:
            self._lowlat = LowLatencyPredictor(
                self.model.trees,
                num_tree_per_iteration=self.model.num_tree_per_iteration,
                max_rows=self.lowlat_max_rows,
                average_output=self.model.average_output,
                artifact_dir=self.artifact_dir)
        return self._lowlat

    @property
    def explainer(self) -> LowLatencyExplainer:
        if self._explainer is None:
            self._explainer = LowLatencyExplainer(
                self.model.trees,
                num_tree_per_iteration=self.model.num_tree_per_iteration,
                max_rows=self.lowlat_max_rows,
                artifact_dir=self.artifact_dir,
                # same effective row chunk as model.predict_contrib ->
                # same path-chunk layout -> bit-identical contributions
                pack_chunk_rows=int(self.model.predict_chunk or 0))
        return self._explainer

    # -- pack accounting / eviction ------------------------------------
    def pack_bytes(self) -> int:
        """Resident packed-ensemble bytes for this model: host packer
        arrays x2 (device tensors mirror the host shapes), the TreeSHAP
        path tables x2 (same host/device mirror story), plus the
        low-latency paths' device packs."""
        total = 0
        for packer in getattr(self.model, "_packers", {}).values():
            total += 2 * packer.nbytes + 2 * packer.shap_nbytes
        if self._lowlat is not None:
            total += self._lowlat.nbytes
        if self._explainer is not None:
            total += self._explainer.nbytes
        return total

    def drop_packs(self) -> int:
        """Evict this model's packed tensors + AOT executables (the
        model itself stays loaded). Returns the bytes released."""
        released = self.pack_bytes()
        self.model._packers = {}
        self.model._packed = None
        self.model._packed_key = None
        self._lowlat = None
        self._explainer = None
        return released


class ModelRegistry:
    """Named-model store with LRU pack eviction under a byte budget.

    ``get`` bumps the entry to most-recently-used; ``evict_to_budget``
    walks from the LRU end dropping packs until the total is back under
    ``max_pack_bytes`` (0 = unbounded). The most-recently-used entry is
    never evicted — dropping the pack of the model a request just used
    would re-pack it on every call.
    """

    def __init__(self, max_pack_bytes: int = 1 << 30,
                 lowlat_max_rows: int = 64,
                 predict_chunk_rows: int = 1 << 20,
                 artifact_dir: str = "",
                 compile_cache: str = "auto"):
        self.max_pack_bytes = int(max_pack_bytes)
        self.lowlat_max_rows = int(lowlat_max_rows)
        # serving chunk size (tpu_predict_chunk) — what the memory
        # preflight sizes the per-dispatch working set with
        self.predict_chunk_rows = int(predict_chunk_rows)
        # serialized-AOT artifacts for every model this registry serves
        # (serve_artifact_dir knob; "" = off)
        self.artifact_dir = str(artifact_dir or "")
        self._entries: "OrderedDict[str, ServedModel]" = OrderedDict()
        # the serve-side program boundary arms the persistent compile
        # cache too (tpu_compile_cache policy — the engine shape buckets
        # warmed through predict_raw ride the XLA disk cache, the
        # lowlat ladder rides the artifact store)
        from ..compile_cache import configure as _configure_compile_cache
        _configure_compile_cache(compile_cache)

    # ------------------------------------------------------------------
    def load(self, name: str, model=None, model_file: Optional[str] = None,
             model_str: Optional[str] = None, booster=None,
             validate: bool = False) -> ServedModel:
        """Register a model under `name` from exactly one source: an
        already-parsed LoadedModel, a text-format file, a model string,
        or a live Booster (snapshotted through its text serialization,
        so later training on the booster can't mutate the served trees).
        Re-loading an existing name replaces it (and frees its packs).

        Registration is TRANSACTIONAL: parsing, entry construction and
        (with ``validate=True``) a one-row pack/predict smoke all run
        BEFORE the registry is touched, so a failure mid-load — a
        corrupt file, an injected registry fault, a pack explosion —
        leaves the previous entry fully served and never a
        partially-registered name (tests/test_resilience.py)."""
        from ..model_io import load_model_from_string
        from ..resilience import faults as faults_mod
        sources = [s is not None for s in (model, model_file, model_str,
                                           booster)]
        if sum(sources) != 1:
            raise ValueError("load() needs exactly one of model=, "
                             "model_file=, model_str=, booster=")
        if model_file is not None:
            with open(model_file) as fh:
                model = load_model_from_string(fh.read())
        elif model_str is not None:
            model = load_model_from_string(model_str)
        elif booster is not None:
            model = load_model_from_string(booster.model_to_string())
        entry = ServedModel(name, model, self.lowlat_max_rows,
                            artifact_dir=self.artifact_dir)
        if faults_mod.global_faults.armed:
            faults_mod.global_faults.check_registry_load(name)
        if validate and model.trees:
            # prove the entry can actually pack + predict before it
            # replaces a working one (the "pack succeeds" gate; warm()
            # extends this to the full bucket ladder server-side)
            entry.predict_raw(
                np.zeros((1, model.max_feature_idx + 1)))
        # ---- commit point: nothing above mutated the registry -------
        old = self._entries.pop(name, None)
        if old is not None:
            old.drop_packs()
        self._entries[name] = entry
        self._preflight(entry)
        return entry

    def _preflight(self, entry: ServedModel) -> None:
        """Serving-side memory preflight (obs/memory.py): predicted
        pack + chunk working set vs device capacity, counting the packs
        OTHER models already hold resident. Warn-only — a registry must
        keep serving its existing tenants even if a new load looks too
        big (the LRU budget will evict before the device OOMs)."""
        try:
            from ..obs import memory as obs_memory
            model = entry.model
            trees = model.trees
            if not trees:
                return
            report = obs_memory.preflight_predict(
                num_rows=self.predict_chunk_rows,
                num_features=int(model.max_feature_idx) + 1,
                num_trees=len(trees),
                num_leaves=max(int(t.num_leaves) for t in trees),
                num_class=int(model.num_tree_per_iteration),
                chunk_rows=self.predict_chunk_rows,
                resident_pack_bytes=sum(
                    e.pack_bytes() for e in self._entries.values()
                    if e is not entry))
            if report.fits is False:
                from .. import log
                log.warning(f"serve memory preflight for model "
                            f"'{entry.name}': " + report.render())
        except Exception:
            pass  # preflight must never block a model load

    def get(self, name: str) -> ServedModel:
        """Look up a model (counts a registry hit/miss, bumps to MRU)."""
        entry = self._entries.get(name)
        if entry is None:
            global_metrics.inc_counter("serve/registry_miss")
            raise KeyError(f"model '{name}' is not registered "
                           f"(have: {sorted(self._entries)})")
        global_metrics.inc_counter("serve/registry_hit")
        self._entries.move_to_end(name)
        return entry

    def retire(self, name: str) -> bool:
        """Unregister `name`, releasing its packs. False if unknown."""
        entry = self._entries.pop(name, None)
        if entry is None:
            return False
        entry.drop_packs()
        return True

    def names(self) -> List[str]:
        return list(self._entries)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    # ------------------------------------------------------------------
    def pack_bytes(self) -> int:
        """Total resident packed bytes across every registered model."""
        return sum(e.pack_bytes() for e in self._entries.values())

    def evict_to_budget(self) -> int:
        """Drop LRU packs until under budget; returns models evicted.
        O(models) when under budget — cheap enough to run per request."""
        if self.max_pack_bytes <= 0:
            return 0
        total = self.pack_bytes()
        evicted = 0
        # LRU -> MRU order; the MRU entry is exempt (see class docstring)
        for name in list(self._entries)[:-1]:
            if total <= self.max_pack_bytes:
                break
            released = self._entries[name].drop_packs()
            if released <= 0:
                continue
            total -= released
            evicted += 1
            global_metrics.inc_counter("serve/pack_evictions")
            global_metrics.inc_counter("serve/evicted_bytes", released)
        return evicted
