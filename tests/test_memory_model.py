"""Analytic peak-HBM model, per-phase watermarks, and the preflight
capacity planner (obs/memory.py) — plus the booster/registry hooks and
the perf-gate memory-ceiling wiring (ISSUE 8)."""

import json
import os
import sys

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import memory as obs_memory
from lightgbm_tpu.obs.memory import (PhaseWatermarks, PreflightError,
                                     predict_memory_model, preflight,
                                     preflight_predict, train_memory_model)
from lightgbm_tpu.obs.metrics import global_metrics

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from conftest import make_binary  # noqa: E402

BASE = dict(num_data=1_000_000, num_features=28, max_bins=63,
            num_leaves=255, num_class=1, num_iterations=10,
            pack_vpb=1, quantized=False, fused_grad=False,
            kernel_fused=False, waved=True, wave_max=42, num_shards=1)


# ---------------------------------------------------------------------------
class TestTrainModel:
    def test_peak_is_max_phase_and_covers_persistent(self):
        m = train_memory_model(**BASE)
        assert m["peak_bytes"] == max(m["phases"].values())
        assert m["phases"][m["peak_phase"]] == m["peak_bytes"]
        assert m["peak_bytes"] >= m["persistent_bytes"]
        assert all(v >= 0 for v in m["components"].values())

    def test_bin_packing_shrinks_bin_component(self):
        # 4-bit packing halves (modulo PACK_ALIGN padding), 2-bit quarters
        unpacked = train_memory_model(**{**BASE, "max_bins": 15})
        packed = train_memory_model(**{**BASE, "max_bins": 15,
                                       "pack_vpb": 2})
        assert packed["components"]["bins"] < \
            0.55 * unpacked["components"]["bins"]
        quarter = train_memory_model(**{**BASE, "max_bins": 3,
                                        "pack_vpb": 4})
        assert quarter["components"]["bins"] < \
            0.30 * unpacked["components"]["bins"]

    def test_uint16_storage_above_256_bins(self):
        wide = train_memory_model(**{**BASE, "max_bins": 300})
        base = train_memory_model(**BASE)
        assert wide["components"]["bins"] == 2 * base["components"]["bins"]

    def test_fused_grad_drops_gradient_buffers(self):
        mat = train_memory_model(**BASE)
        fused = train_memory_model(**{**BASE, "fused_grad": True})
        assert mat["components"]["gradients"] == \
            2 * BASE["num_data"] * 4  # grad + hess f32
        assert fused["components"]["gradients"] == 0
        assert fused["peak_bytes"] < mat["peak_bytes"]
        # kernel-level fusion additionally never materializes ghT
        kf = train_memory_model(**{**BASE, "fused_grad": True,
                                   "kernel_fused": True})
        assert kf["components"]["ght"] == 0
        assert kf["peak_bytes"] < fused["peak_bytes"]

    def test_quantized_ght_is_int8(self):
        f32 = train_memory_model(**BASE)
        q = train_memory_model(**{**BASE, "quantized": True})
        assert q["components"]["ght"] * 4 == f32["components"]["ght"]

    def test_shards_divide_row_state_not_replicated_state(self):
        one = train_memory_model(**BASE)
        four = train_memory_model(**{**BASE, "num_shards": 4})
        for row_comp in ("bins", "scores", "ght", "row_leaf"):
            assert four["components"][row_comp] <= \
                -(-one["components"][row_comp] // 4) + 64
        # histogram pool and records are replicated per shard
        assert four["components"]["hist_pool"] == \
            one["components"]["hist_pool"]
        assert four["components"]["records"] == one["components"]["records"]
        assert four["peak_bytes"] < one["peak_bytes"]

    def test_monotone_in_shape(self):
        base = train_memory_model(**BASE)
        assert train_memory_model(
            **{**BASE, "num_data": 2 * BASE["num_data"]})["peak_bytes"] \
            > base["peak_bytes"]
        assert train_memory_model(
            **{**BASE, "num_leaves": 511})["components"]["hist_pool"] \
            > base["components"]["hist_pool"]
        assert train_memory_model(
            **{**BASE, "max_bins": 127})["components"]["hist_wave"] \
            > base["components"]["hist_wave"]

    def test_valid_sets_add_bytes(self):
        v = train_memory_model(**{**BASE, "valid_rows": [500_000]})
        assert v["components"]["valid"] > 0
        assert v["peak_bytes"] >= train_memory_model(**BASE)["peak_bytes"]

    def test_params_echoed(self):
        m = train_memory_model(**BASE)
        assert m["params"]["num_data"] == BASE["num_data"]
        assert m["kind"] == "train"


# ---------------------------------------------------------------------------
class TestKnobResolution:
    """preflight resolves config -> model knobs the way the booster
    itself does (pack factor, fused/quantized/waved state)."""

    def _model_params(self, params, shape=(100_000, 10), **kw):
        return preflight(params, shape=shape,
                         capacity_bytes=1 << 50, **kw).model["params"]

    def test_binary_default_is_fused_and_waved(self):
        p = self._model_params({"objective": "binary"})
        assert p["fused_grad"] and p["waved"]

    def test_multiclass_softmax_is_exact_and_unfused(self):
        p = self._model_params({"objective": "multiclass", "num_class": 4})
        assert not p["waved"] and not p["fused_grad"]
        assert p["num_class"] == 4

    def test_goss_keeps_materialized_gradients(self):
        p = self._model_params({"objective": "binary", "boosting": "goss"})
        assert not p["fused_grad"]

    def test_pack_factor_follows_max_bin_and_knob(self):
        assert self._model_params({"max_bin": 15})["pack_vpb"] == 2
        assert self._model_params({"max_bin": 3})["pack_vpb"] == 4
        assert self._model_params({"max_bin": 63})["pack_vpb"] == 1
        assert self._model_params({"max_bin": 15,
                                   "tpu_bin_pack": "off"})["pack_vpb"] == 1
        # _maybe_pack_bins refuses whenever tpu_num_shards > 1 is set,
        # even on the serial learner — the resolver must match
        assert self._model_params({"max_bin": 15,
                                   "tpu_num_shards": 4})["pack_vpb"] == 1

    def test_quantized_resolution(self):
        p = self._model_params({"objective": "binary",
                                "use_quantized_grad": True})
        assert p["quantized"] and not p["fused_grad"]


# ---------------------------------------------------------------------------
class TestPreflight:
    def test_requires_shape(self):
        with pytest.raises(ValueError):
            preflight({"objective": "binary"})

    def test_no_capacity_no_verdict(self, monkeypatch):
        monkeypatch.delenv("LGBM_TPU_HBM_BYTES", raising=False)
        r = preflight({"objective": "binary"}, shape=(10_000, 8))
        if obs_memory.device_capacity_bytes() is None:  # CPU backend
            assert r.fits is None and r.recommendations == []

    def test_fits_with_huge_capacity(self):
        r = preflight({"objective": "binary"}, shape=(10_000, 8),
                      capacity_bytes=1 << 50)
        assert r.fits is True and r.headroom_bytes > 0
        assert r.recommendations == []

    def test_rejects_with_actionable_recommendation(self):
        r = preflight({"objective": "binary", "num_leaves": 255,
                       "max_bin": 63}, shape=(10_500_000, 28),
                      capacity_bytes=int(0.5e9))
        assert r.fits is False
        assert r.recommendations, "a non-fit must carry recommendations"
        known_knobs = {"tpu_bin_pack", "max_bin", "use_quantized_grad",
                       "tpu_stream",
                       "tpu_fused_grad", "tpu_num_shards"}
        for rec in r.recommendations:
            assert rec["knob"] in known_knobs
            assert rec["saves_bytes"] > 0
            assert rec["peak_bytes"] < r.peak_bytes
            assert rec["reason"]
        # sorted by saving, biggest first
        saves = [rec["saves_bytes"] for rec in r.recommendations]
        assert saves == sorted(saves, reverse=True)
        text = r.render()
        assert "DOES NOT FIT" in text
        assert r.recommendations[0]["knob"] in text

    def test_bin_pack_recommended_when_knob_off(self):
        r = preflight({"objective": "binary", "max_bin": 15,
                       "tpu_bin_pack": "off"}, shape=(10_500_000, 28),
                      capacity_bytes=int(0.4e9))
        assert r.fits is False
        assert any(rec["knob"] == "tpu_bin_pack"
                   for rec in r.recommendations)

    def test_env_capacity_override(self, monkeypatch):
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES", str(1 << 50))
        assert obs_memory.device_capacity_bytes() == 1 << 50
        r = preflight({"objective": "binary"}, shape=(10_000, 8))
        assert r.fits is True


# ---------------------------------------------------------------------------
class TestPredictModel:
    def test_chunk_capped_by_request_rows(self):
        small = predict_memory_model(num_rows=1000, num_features=28,
                                     num_trees=100, num_leaves=255)
        assert small["chunk_rows"] <= 1024
        big = predict_memory_model(num_rows=1 << 22, num_features=28,
                                   num_trees=100, num_leaves=255)
        assert big["chunk_rows"] == 1 << 20

    def test_measured_pack_bytes_override(self):
        m = predict_memory_model(num_rows=1000, num_features=28,
                                 num_trees=10, num_leaves=31,
                                 pack_nbytes=12345)
        assert m["components"]["pack"] == 2 * 12345

    def test_preflight_predict_recommends_smaller_chunk(self):
        r = preflight_predict(num_rows=1 << 20, num_features=28,
                              num_trees=100, num_leaves=255,
                              capacity_bytes=int(100e6))
        assert r.fits is False
        assert any(rec["knob"] == "tpu_predict_chunk"
                   for rec in r.recommendations)
        chunk_rec = [rec for rec in r.recommendations
                     if rec["knob"] == "tpu_predict_chunk"][0]
        assert chunk_rec["setting"] < 1 << 20

    def test_resident_packs_counted_and_evictable(self):
        r = preflight_predict(num_rows=1 << 16, num_features=28,
                              num_trees=50, num_leaves=255,
                              resident_pack_bytes=int(1e9),
                              capacity_bytes=int(1e9))
        assert r.fits is False
        assert any(rec["knob"] == "serve_cache_bytes"
                   for rec in r.recommendations)


# ---------------------------------------------------------------------------
class TestBoosterHook:
    def _ds(self):
        X, y = make_binary(400, 6)
        return lgb.Dataset(X, label=y)

    def test_meta_published_always_on(self):
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, self._ds(), num_boost_round=1)
        mm = global_metrics.meta.get("mem_model")
        assert mm is not None
        assert global_metrics.meta["mem_peak_model_bytes"] == \
            mm["peak_bytes"]
        assert mm["params"]["num_leaves"] == 7

    def test_error_mode_fails_fast(self, monkeypatch):
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES", "1000")
        with pytest.raises(PreflightError) as exc:
            lgb.train({"objective": "binary", "num_leaves": 7,
                       "verbosity": -1, "tpu_preflight": "error"},
                      self._ds(), num_boost_round=1)
        assert "DOES NOT FIT" in str(exc.value)

    def test_warn_mode_trains_anyway(self, monkeypatch, capsys):
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES", "1000")
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": 0}, self._ds(), num_boost_round=1)
        assert bst.current_iteration() == 1
        assert "memory preflight" in capsys.readouterr().out

    def test_off_mode_is_silent(self, monkeypatch, capsys):
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES", "1000")
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": 0, "tpu_preflight": "off"},
                  self._ds(), num_boost_round=1)
        assert "memory preflight" not in capsys.readouterr().out
        # model still published for the driver even with judging off
        assert "mem_model" in global_metrics.meta

    def test_booster_model_matches_standalone(self):
        X, y = make_binary(600, 8)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=1)
        kw = bst._gbdt._memory_model_kwargs()
        assert global_metrics.meta["mem_peak_model_bytes"] == \
            train_memory_model(**kw)["peak_bytes"]


# ---------------------------------------------------------------------------
class TestRegistryHook:
    def test_load_warns_but_serves_when_over_capacity(self, monkeypatch,
                                                      capsys):
        X, y = make_binary(400, 6)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=2)
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES", "1000")
        from lightgbm_tpu import log
        log.set_verbosity(0)  # verbosity=-1 above silenced warnings
        from lightgbm_tpu.serve import ModelRegistry
        reg = ModelRegistry()
        entry = reg.load("m", booster=bst)
        out = capsys.readouterr().out
        assert "serve memory preflight" in out
        # warn-only: the model is registered and predicts
        pred = entry.predict_raw(X[:4])
        assert pred.shape[0] == 4


# ---------------------------------------------------------------------------
class TestWatermarks:
    def _stats(self, peaks):
        it = iter(peaks)

        def fn():
            v = next(it, None)
            if v is None:
                return None
            return [{"peak_bytes_in_use": v, "bytes_in_use": v // 2,
                     "device": 0}]
        return fn

    def test_attributes_peak_growth_to_closing_phase(self):
        wm = PhaseWatermarks(stats_fn=self._stats([100, 300, 300, 900]))
        assert wm.enable()
        wm.sink("a", 0.0, 0.0)   # baseline sample: no prior => no delta
        wm.sink("b", 0.0, 0.0)   # +200 attributed to b
        wm.sink("b", 0.0, 0.0)   # flat
        wm.sink("c", 0.0, 0.0)   # +600 attributed to c
        s = wm.summary()
        assert s["a"]["delta_bytes"] == 0
        assert s["b"]["delta_bytes"] == 200 and s["b"]["samples"] == 2
        assert s["c"]["delta_bytes"] == 600
        assert s["c"]["peak_bytes"] == 900

    def test_multi_device_takes_max_peak(self):
        def fn():
            return [{"peak_bytes_in_use": 100, "bytes_in_use": 50},
                    {"peak_bytes_in_use": 700, "bytes_in_use": 60}]
        wm = PhaseWatermarks(stats_fn=fn)
        wm.enable()
        wm.sink("x", 0.0, 0.0)
        s = wm.summary()
        assert s["x"]["peak_bytes"] == 700
        assert s["x"]["bytes_in_use"] == 110  # fleet sum

    def test_unsupported_backend_disarms_after_one_probe(self):
        calls = []

        def fn():
            calls.append(1)
            return None
        wm = PhaseWatermarks(stats_fn=fn)
        wm.enable()
        wm.sink("a", 0.0, 0.0)
        assert not wm.enabled  # disarmed for good
        wm.sink("a", 0.0, 0.0)
        assert len(calls) == 1  # later spans are the O(1) flag check
        assert not wm.enable()  # re-enable refuses on a probed-off backend

    def test_disabled_sink_is_noop(self):
        wm = PhaseWatermarks(stats_fn=lambda: [{"peak_bytes_in_use": 1}])
        wm.sink("a", 0.0, 0.0)
        assert wm.summary() == {}

    def test_global_watermarks_registered_on_tracer(self):
        from lightgbm_tpu.obs.memory import global_watermarks
        from lightgbm_tpu.obs.trace import global_tracer
        assert global_watermarks.sink in global_tracer._sinks


# ---------------------------------------------------------------------------
class TestGateWiring:
    def _gate(self):
        import check_perf_gate
        return check_perf_gate

    def test_memory_ceiling_passes_on_repo_floor(self, capsys):
        gate = self._gate()
        with open(gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        failures = []
        gate.check_memory_model(floor, failures)
        assert failures == []
        assert "memory model" in capsys.readouterr().out

    def test_memory_ceiling_trips_on_regression(self):
        gate = self._gate()
        with open(gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        floor["memory"]["max_peak_model_bytes"] //= 2
        failures = []
        gate.check_memory_model(floor, failures)
        assert failures and "peak-memory model regressed" in failures[0]

    def test_model_vs_measured_band(self):
        gate = self._gate()
        with open(gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        rec = {"metric": "boosting_iters_per_sec_higgs_shape",
               "value": 1.0, "vs_baseline": 1.0, "unit": "iters/sec",
               "mem_peak_model_bytes": int(1e9),
               "mem_peak_measured_bytes": int(4e9)}  # 0.25x: out of band
        failures = []
        gate.check_memory_model(floor, failures, rec)
        assert failures and "band" in failures[0]
        # inside the band passes
        rec["mem_peak_measured_bytes"] = int(1.2e9)
        failures = []
        gate.check_memory_model(floor, failures, rec)
        assert failures == []

    def test_gate_main_accepts_accelerator_candidate(self, tmp_path):
        """End-to-end through main(): a candidate carrying an in-band
        model/measured pair passes; an out-of-band pair fails."""
        gate = self._gate()
        rec = {"metric": "boosting_iters_per_sec_higgs_shape",
               "value": 50.0, "vs_baseline": 13.0,
               "unit": "iters/sec (N=10500000)",
               "hist_bytes_reduction": 1.35,
               "mem_peak_model_bytes": int(1e9),
               "mem_peak_measured_bytes": int(1.2e9)}
        cand = tmp_path / "BENCH_candidate.json"
        cand.write_text(json.dumps(rec))
        assert gate.main([str(cand)]) == 0
        rec["mem_peak_measured_bytes"] = int(9e9)
        cand.write_text(json.dumps(rec))
        assert gate.main([str(cand)]) == 1


# ---------------------------------------------------------------------------
@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="memory_stats() is None on CPU; model-vs-measured needs HBM")
def test_model_within_band_of_measured_on_accelerator():
    """Acceptance: on TPU/GPU the analytic model is within 1.5x of the
    measured peak for the fixture shape."""
    X, y = make_binary(200_000, 28)
    bst = lgb.train({"objective": "binary", "num_leaves": 63,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=3)
    jax.block_until_ready(bst._gbdt.scores)
    modeled = global_metrics.meta["mem_peak_model_bytes"]
    measured = obs_memory.measured_peak_bytes()
    assert measured is not None
    ratio = modeled / measured
    assert 1 / 1.5 <= ratio <= 1.5, (modeled, measured)
