"""Distributed (data-parallel) training over the virtual 8-device CPU mesh
(ref strategy: tests/distributed/_test_distributed.py DistributedMockup —
there via N localhost CLI processes + sockets; here via jax.sharding over
a forced multi-device host platform, which exercises the same program the
TPU mesh runs)."""

import numpy as np
import jax
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc
from tests.conftest import make_binary, make_regression


@pytest.fixture(autouse=True)
def _require_multi_device():
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device (XLA_FLAGS host platform count)")


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_data_parallel_binary_quality():
    X, y = make_binary(2000)
    dtrain = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "tree_learner": "data",
                     "num_leaves": 15, "min_data_in_leaf": 5,
                     "verbosity": -1},
                    dtrain, num_boost_round=20)
    assert _auc(y, bst.predict(X)) > 0.9


def test_data_parallel_matches_serial():
    """Distributed vs single-device training must agree (ref:
    _test_distributed.py:168 accuracy + prediction agreement check)."""
    X, y = make_regression(1024)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 7}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    parallel = lgb.train({**params, "tree_learner": "data"},
                         lgb.Dataset(X, label=y), num_boost_round=10)
    ps = serial.predict(X)
    pp = parallel.predict(X)
    # identical math; tolerance covers cross-shard reduction order
    np.testing.assert_allclose(pp, ps, rtol=1e-3, atol=1e-3)


def test_data_parallel_sharded_arrays():
    X, y = make_binary(512)
    dtrain = lgb.Dataset(X, label=y)
    bst = lgb.Booster({"objective": "binary", "tree_learner": "data",
                       "num_leaves": 7, "verbosity": -1}, dtrain)
    gbdt = bst._gbdt
    assert gbdt.mesh.size == 8
    # bins sharded along rows (axis 1)
    sharding = gbdt.bins_fm.sharding
    spec = sharding.spec
    assert spec[1] == "data"
    bst.update()
    assert bst.current_iteration() == 1


def test_data_parallel_num_shards_param():
    X, y = make_binary(512)
    bst = lgb.Booster({"objective": "binary", "tpu_num_shards": 4,
                       "num_leaves": 7, "verbosity": -1},
                      lgb.Dataset(X, label=y))
    assert bst._gbdt.mesh.size == 4
    bst.update()


def test_voting_and_feature_learner_accepted():
    X, y = make_binary(512)
    for tl in ("voting", "feature"):
        bst = lgb.train({"objective": "binary", "tree_learner": tl,
                         "num_leaves": 7, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=3)
        assert bst.num_trees() == 3


def test_voting_parallel_matches_serial_when_topk_covers_features():
    """With top_k >= F the voting filter keeps every feature, so PV-tree
    must reproduce the serial learner exactly."""
    X, y = make_regression(1024, 8)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "top_k": 20,
              # the sharded learners grow exact leaf-wise; compare
              # against the serial EXACT grower, not the waved default
              "tpu_wave_max": 0}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    voting = lgb.train({**params, "tree_learner": "voting"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(voting.predict(X), serial.predict(X),
                               rtol=1e-3, atol=1e-3)


def test_voting_parallel_small_topk_still_learns():
    """top_k < F: the candidate filter is actually binding (ref: PV-tree
    accuracy claim — voting loses little quality)."""
    X, y = make_binary(2048, 12)
    bst = lgb.train({"objective": "binary", "tree_learner": "voting",
                     "top_k": 2, "num_leaves": 15, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=15)
    assert bst._gbdt.mesh.size == 8
    assert _auc(y, bst.predict(X)) > 0.9


def test_feature_parallel_matches_serial_exactly():
    """Feature-parallel is exact: same candidate set, sharded search."""
    X, y = make_regression(1024, 10)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 3,
              # serial baseline must be the EXACT grower (see above)
              "tpu_wave_max": 0}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    fpar = lgb.train({**params, "tree_learner": "feature"},
                     lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(fpar.predict(X), serial.predict(X),
                               rtol=1e-4, atol=1e-4)


def test_feature_parallel_uneven_feature_count():
    """F=13 not divisible by 8 shards: overlapping slices must stay
    correct."""
    X, y = make_binary(1024, 13)
    bst = lgb.train({"objective": "binary", "tree_learner": "feature",
                     "num_leaves": 15, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    assert _auc(y, bst.predict(X)) > 0.9


def test_voting_program_contains_collectives():
    """The compiled voting program must actually communicate: psum
    (all-reduce) for candidate histograms, all-gather for votes."""
    import jax.numpy as jnp
    from lightgbm_tpu.parallel.voting import make_sharded_voting_grow
    from lightgbm_tpu.parallel import mesh as mesh_lib

    X, y = make_binary(512, 8)
    bst = lgb.Booster({"objective": "binary", "tree_learner": "voting",
                       "num_leaves": 7, "verbosity": -1, "top_k": 2},
                      lgb.Dataset(X, label=y))
    g = bst._gbdt
    mesh = mesh_lib.get_mesh(8)
    grow = make_sharded_voting_grow(mesh, num_leaves=7,
                                    max_bins=g._static["max_bins"],
                                    top_k=2)
    hlo = grow.lower(
        g.bins_fm, jnp.zeros(512, jnp.float32), jnp.ones(512, jnp.float32),
        jnp.ones(512, jnp.float32), jnp.ones(8, bool), g.feature_meta,
        g.hp, jnp.int32(-1)).compile().as_text()
    assert "all-reduce" in hlo or "all-gather" in hlo, \
        "voting program lost its collectives"


def test_mesh_pallas_hist_matches_serial():
    """tpu_hist_impl=pallas under the 8-device mesh (interpret mode on
    CPU): the shard_map per-shard kernel + psum wrapper must reproduce
    single-device training (VERDICT r4 #5 — the flagship kernel on the
    flagship multi-chip configuration). N=1003 exercises the row padding
    to a mesh multiple."""
    X, y = make_regression(1003)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 7,
              "tpu_hist_precision": "highest"}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    mesh_pallas = lgb.train({**params, "tree_learner": "data",
                             "tpu_hist_impl": "pallas"},
                            lgb.Dataset(X, label=y), num_boost_round=8)
    np.testing.assert_allclose(mesh_pallas.predict(X), serial.predict(X),
                               rtol=1e-3, atol=1e-3)


def test_mesh_pallas_exact_grower_matches_serial():
    """Same check for the exact (per-split) grower path."""
    X, y = make_binary(1003)
    params = {"objective": "binary", "num_leaves": 15, "tpu_wave_max": 0,
              "min_data_in_leaf": 5, "verbosity": -1,
              "tpu_hist_precision": "highest"}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    mesh_pallas = lgb.train({**params, "tree_learner": "data",
                             "tpu_hist_impl": "pallas"},
                            lgb.Dataset(X, label=y), num_boost_round=8)
    np.testing.assert_allclose(mesh_pallas.predict(X), serial.predict(X),
                               rtol=1e-3, atol=1e-3)


def test_mesh_quantized_int8_psum_matches_serial():
    """use_quantized_grad on the mesh: the int8 kernel runs per shard and
    the psum reduces INT32 histograms (exact integer accumulation across
    shards — ref: data_parallel_tree_learner.cpp:290-297 reduces integer
    bins). Same quantization RNG on both sides -> near-identical models."""
    X, y = make_binary(1003)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1,
              "use_quantized_grad": True, "tpu_hist_impl": "pallas",
              "tpu_hist_precision": "highest"}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    meshq = lgb.train({**params, "tree_learner": "data"},
                      lgb.Dataset(X, label=y), num_boost_round=8)
    np.testing.assert_allclose(meshq.predict(X), serial.predict(X),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("reduce,collective", [
    ("psum", "all_reduce"), ("scatter", "reduce_scatter")])
def test_mesh_quantized_reduce_is_integer_typed(reduce, collective):
    """Compiled-program proof that the quantized mesh reduction moves
    int32 histograms, not dequantized f32 (VERDICT r4 #8): the program's
    cross-shard collective — all_reduce for the psum oracle,
    reduce_scatter for the feature-sharded default — must carry s32
    operands."""
    import functools
    import jax.numpy as jnp

    X, y = make_binary(1024)
    bst = lgb.Booster({"objective": "binary", "tree_learner": "data",
                       "num_leaves": 7, "verbosity": -1,
                       "use_quantized_grad": True,
                       "tpu_hist_impl": "pallas",
                       "tpu_hist_reduce": reduce},
                      lgb.Dataset(X, label=y))
    g = bst._gbdt
    assert g._hist_reduce == reduce
    n = g.num_data
    grow = g._grow_partial()
    quant = (jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32),
             jnp.float32(0.5), jnp.float32(0.5))
    lowered = jax.jit(functools.partial(grow, quant=quant)).lower(
        g.bins_fm, jnp.zeros(n, jnp.float32), jnp.ones(n, jnp.float32),
        jnp.ones(n, jnp.float32), jnp.ones(X.shape[1], bool),
        g.feature_meta, g.hp, jnp.int32(-1), None, None)
    # assert on the lowered program (CPU backend optimizations may later
    # rewrite the collective): the histogram collective must consume the
    # int8 kernel's output and reduce i32 tensors, with the f32
    # dequantize AFTER it
    shlo = lowered.as_text()
    assert collective in shlo, \
        f"quantized mesh grower lost its {collective}"
    assert "hist_pallas_multi_int8" in shlo, \
        "quantized mesh grower dropped the int8 pallas kernel"
    import re
    ar_types = []
    for chunk in shlo.split('stablehlo.' + collective)[1:]:
        m = re.search(r'\^bb0\(%\w+: tensor<(\w+)>', chunk)
        if m:
            ar_types.append(m.group(1))
    assert ar_types and all(t == "i32" for t in ar_types), \
        f"expected i32 {collective} reductions, got {ar_types}"


@pytest.mark.slow
def test_mono_pairwise_parallel_learners_match_serial():
    """monotone_constraints_method=advanced under all three parallel
    learners (VERDICT r4 #7): the pairwise leaf-box state is replicated
    and deterministic, so each learner must reproduce its serial-strategy
    result; previously these downgraded to the basic method with a
    warning. Ref: monotone_constraints.hpp:330 (the reference's factory
    is learner-agnostic too)."""
    X, y = make_regression(1024)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1,
              "monotone_constraints": "1,-1,0,0,0,0,0,0",
              "monotone_constraints_method": "advanced",
              # the sharded voting/feature learners grow exact leaf-wise;
              # compare against the serial EXACT grower
              "tpu_wave_max": 0}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    ps = serial.predict(X)
    # data-parallel: identical grower math under GSPMD
    dp = lgb.train({**params, "tree_learner": "data"},
                   lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(dp.predict(X), ps, rtol=1e-3, atol=1e-3)
    # feature-parallel: exact same split sequence
    fp = lgb.train({**params, "tree_learner": "feature"},
                   lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(fp.predict(X), ps, rtol=1e-4, atol=1e-4)
    # voting with top_k covering all features degenerates to data-parallel
    vp = lgb.train({**params, "tree_learner": "voting", "top_k": 8},
                   lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(vp.predict(X), ps, rtol=1e-3, atol=1e-3)
    # and no downgrade warning fires
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        lgb.Booster({**params, "tree_learner": "voting", "top_k": 8,
                     "verbosity": -1}, lgb.Dataset(X, label=y))


def test_int8_mesh_psum_exact_parity():
    """The promoted-to-default int8 histogram path on the mesh: the
    per-shard int8 kernel + INT32 psum must reproduce the single-device
    int8 kernel EXACTLY — integer accumulation commutes across shards,
    unlike f32 (the point of reducing quantized histograms, ref:
    data_parallel_tree_learner.cpp:290-297)."""
    import jax.numpy as jnp
    from lightgbm_tpu.learner import _sharded_pallas_multi
    from lightgbm_tpu.ops.pallas_histogram import (hist_multi_int8_xla,
                                                   hist_pallas_multi_int8)
    from lightgbm_tpu.parallel import mesh as mesh_lib

    r = np.random.RandomState(4)
    n, f, b, slots = 1003, 5, 15, 8  # n not a mesh multiple: pads rows
    bins = jnp.asarray(r.randint(0, b, (f, n)), jnp.uint8)
    mask = (r.rand(n) < 0.8).astype(np.int8)
    ghT_i8 = jnp.asarray(np.stack([(r.randint(-3, 4, n) * mask),
                                   (r.randint(0, 5, n) * mask), mask],
                                  axis=1), jnp.int8)
    row_leaf = jnp.asarray(r.randint(0, slots, n), jnp.int32)
    ids = jnp.asarray(np.arange(slots, dtype=np.int32))

    mesh = mesh_lib.get_mesh(8)
    sharded = _sharded_pallas_multi(mesh, max_bins=b, precision="highest",
                                    int8=True)
    out_mesh = sharded(bins, ghT_i8, row_leaf, ids)
    out_single = hist_pallas_multi_int8(bins, ghT_i8, row_leaf, ids,
                                        max_bins=b, num_slots=slots,
                                        interpret=True)
    out_xla = hist_multi_int8_xla(bins, ghT_i8, row_leaf, ids,
                                  max_bins=b, num_slots=slots)
    assert out_mesh.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out_mesh),
                                  np.asarray(out_single))
    np.testing.assert_array_equal(np.asarray(out_mesh),
                                  np.asarray(out_xla))


def test_deterministic_hist_under_sharding():
    """deterministic_hist (Kahan fixed-chunk accumulation) must make
    data-parallel training track serial training TIGHTER than the
    plain-f32 1e-3 gate above — the reorders-safely-under-sharding
    property at the training level."""
    X, y = make_regression(1024)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 7,
              "deterministic_hist": True}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    parallel = lgb.train({**params, "tree_learner": "data"},
                         lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(parallel.predict(X), serial.predict(X),
                               rtol=1e-4, atol=1e-4)


class TestInitDistributedRetry:
    """init_distributed's connect retry/backoff (ISSUE 17 satellite):
    the fleet-restart race — every worker execs at once, the
    coordinator binds last — must be absorbed by retries, and a dead
    coordinator must surface as a structured DistributedInitError a
    supervisor can match on."""

    @pytest.fixture(autouse=True)
    def _fresh_module_state(self, monkeypatch):
        from lightgbm_tpu.parallel import distributed as dist
        monkeypatch.setattr(dist, "_initialized", False)
        import time
        monkeypatch.setattr(time, "sleep", lambda s: None)
        yield
        dist._initialized = False

    def _patch_initialize(self, monkeypatch, fn):
        monkeypatch.setattr(jax.distributed, "initialize", fn)

    def test_retries_until_coordinator_comes_up(self, monkeypatch):
        from lightgbm_tpu.parallel import distributed as dist
        calls = []

        def flaky(**kwargs):
            calls.append(kwargs)
            if len(calls) < 3:
                raise RuntimeError("connection refused")

        self._patch_initialize(monkeypatch, flaky)
        dist.init_distributed("10.0.0.1:1234", num_processes=2,
                              process_id=0, connect_retries=4,
                              connect_backoff_s=0.0)
        assert len(calls) == 3
        assert dist.is_initialized()
        assert calls[0]["coordinator_address"] == "10.0.0.1:1234"

    def test_exhausted_retries_raise_structured_error(self,
                                                      monkeypatch):
        from lightgbm_tpu.parallel import distributed as dist
        from lightgbm_tpu.resilience.errors import DistributedInitError

        def dead(**kwargs):
            raise RuntimeError("connection refused")

        self._patch_initialize(monkeypatch, dead)
        with pytest.raises(DistributedInitError) as ei:
            dist.init_distributed("10.0.0.1:1234", num_processes=2,
                                  process_id=0, connect_retries=2,
                                  connect_backoff_s=0.0)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last_error, RuntimeError)
        assert not dist.is_initialized()

    def test_misconfiguration_is_not_retried(self, monkeypatch):
        from lightgbm_tpu.parallel import distributed as dist
        calls = []

        def misconfigured(**kwargs):
            calls.append(kwargs)
            raise ValueError("bad coordinator address")

        self._patch_initialize(monkeypatch, misconfigured)
        with pytest.raises(ValueError, match="bad coordinator"):
            dist.init_distributed("nonsense", num_processes=2,
                                  process_id=0, connect_retries=5)
        assert len(calls) == 1  # retrying cannot fix a config error

    def test_already_initialized_runtime_is_adopted(self, monkeypatch):
        from lightgbm_tpu.parallel import distributed as dist

        def already(**kwargs):
            raise RuntimeError(
                "Distributed runtime is already initialized")

        self._patch_initialize(monkeypatch, already)
        dist.init_distributed("10.0.0.1:1234", num_processes=2,
                              process_id=0)
        assert dist.is_initialized()

    def test_second_call_is_idempotent(self, monkeypatch):
        from lightgbm_tpu.parallel import distributed as dist
        calls = []
        self._patch_initialize(
            monkeypatch, lambda **kw: calls.append(kw))
        dist.init_distributed("10.0.0.1:1234", num_processes=2,
                              process_id=0)
        dist.init_distributed("10.0.0.1:1234", num_processes=2,
                              process_id=0)
        assert len(calls) == 1

    def test_backoff_schedule_is_deterministic_and_capped(self):
        from lightgbm_tpu.resilience.degrade import backoff_delays
        assert backoff_delays(4, 0.5, cap_s=10.0) == \
            [0.5, 1.0, 2.0, 4.0]
        assert backoff_delays(6, 0.5, cap_s=2.0) == \
            [0.5, 1.0, 2.0, 2.0, 2.0, 2.0]
