"""Model text/JSON serialization, LightGBM-format compatible.

(ref: src/boosting/gbdt_model_text.cpp:315 SaveModelToString, :425
LoadModelFromString). The emitted format round-trips through this module
and follows the reference layout (`tree_sizes=` byte index, per-tree
blocks, `end of trees`, feature importances, parameters block) so models
can be inspected / consumed by reference tooling.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from .config import Config
from .resilience.errors import CorruptModelError
from .tree import Tree


def _objective_string(config: Config) -> str:
    obj = config.objective
    if obj == "binary":
        return f"binary sigmoid:{config.sigmoid:g}"
    if obj in ("multiclass", "multiclassova"):
        return f"{obj} num_class:{config.num_class}"
    if obj in ("lambdarank", "rank_xendcg"):
        return obj
    if obj == "quantile":
        return f"quantile alpha:{config.alpha:g}"
    if obj == "huber":
        return f"huber alpha:{config.alpha:g}"
    if obj == "fair":
        return f"fair c:{config.fair_c:g}"
    if obj == "tweedie":
        return f"tweedie tweedie_variance_power:{config.tweedie_variance_power:g}"
    return obj or "custom"


def save_model_to_string(booster, num_iteration: int = -1,
                         start_iteration: int = 0,
                         importance_type: str = "split") -> str:
    """booster: boosting.GBDT."""
    cfg = booster.config
    ds = booster.train_set
    end = len(booster.models) if num_iteration < 0 else min(
        len(booster.models), start_iteration + num_iteration)

    header = ["tree", "version=v4"]
    header.append(f"num_class={max(cfg.num_class, 1)}")
    header.append(f"num_tree_per_iteration={booster.num_tree_per_iteration}")
    header.append(f"label_index={ds.label_idx}")
    header.append(f"max_feature_idx={ds.num_total_features - 1}")
    header.append(f"objective={_objective_string(cfg)}")
    if getattr(booster, "_average_output", False) or \
            booster.boosting_type == "rf":
        header.append("average_output")
    header.append("feature_names=" + " ".join(ds.feature_names))
    header.append("feature_infos=" + " ".join(ds.feature_infos()))

    tree_blocks: List[str] = []
    idx = 0
    for it in range(start_iteration, end):
        for tree in booster.models[it]:
            tree_blocks.append(tree.to_string(idx) + "\n")
            idx += 1
    tree_sizes = " ".join(str(len(b.encode())) for b in tree_blocks)
    header.append(f"tree_sizes={tree_sizes}")
    header.append("")

    out = "\n".join(header) + "\n" + "".join(tree_blocks)
    out += "end of trees\n\n"

    imp = booster.feature_importance(importance_type)
    order = np.argsort(-imp, kind="stable")
    lines = ["feature_importances:"]
    for i in order:
        if imp[i] > 0:
            lines.append(f"{ds.feature_names[i]}={imp[i]:g}")
    out += "\n".join(lines) + "\n\n"

    out += "parameters:\n"
    for key, value in cfg.to_params().items():
        if isinstance(value, list):
            value = ",".join(str(v) for v in value)
        out += f"[{key}: {value}]\n"
    out += "end of parameters\n\n"
    out += "pandas_categorical:null\n"
    return out


def transform_raw(objective_str: str, raw: np.ndarray) -> np.ndarray:
    """Raw scores -> output space for a serialized objective string
    (the prediction-side ConvertOutput of gbdt_prediction.cpp). Shared
    by LoadedModel.predict and the serve/ request path, so a served
    probability is bit-identical to a direct `predict` call."""
    obj = objective_str.split()[0] if objective_str else ""
    if obj == "binary":
        sig = 1.0
        for tok in objective_str.split()[1:]:
            if tok.startswith("sigmoid:"):
                sig = float(tok.split(":")[1])
        return 1.0 / (1.0 + np.exp(-sig * raw))
    if obj == "multiclass":
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)
    if obj == "multiclassova":
        return 1.0 / (1.0 + np.exp(-raw))
    if obj in ("poisson", "gamma", "tweedie"):
        return np.exp(raw)
    if obj == "cross_entropy":
        return 1.0 / (1.0 + np.exp(-raw))
    return raw


class LoadedModel:
    """A model parsed from text — enough state to predict and continue
    inspection (ref: GBDT::LoadModelFromString gbdt_model_text.cpp:425)."""

    def __init__(self):
        self.trees: List[Tree] = []
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.objective_str = "regression"
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.max_feature_idx = 0
        self.average_output = False
        self.params = {}
        self.label_index = 0
        # serving knobs (plumbed from the loading Booster's params;
        # ops/predict.py streaming engine)
        self.predict_chunk: Optional[int] = None
        self.predict_shards: int = 0

    @property
    def num_iterations(self) -> int:
        if self.num_tree_per_iteration <= 0:
            return len(self.trees)
        return len(self.trees) // self.num_tree_per_iteration

    def predict_raw(self, data: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1,
                    predict_chunk: Optional[int] = None) -> np.ndarray:
        data = np.asarray(data, np.float64)
        n = data.shape[0]
        k = self.num_tree_per_iteration
        end = self.num_iterations if num_iteration < 0 else min(
            self.num_iterations, start_iteration + num_iteration)
        trees = self.trees[start_iteration * k:end * k]
        if not trees or any(t.is_linear for t in trees):
            # host fallback: linear-tree leaf models live on host
            out = np.zeros((n, k))
            for i, tree in enumerate(trees):
                out[:, i % k] += tree.predict(data)
        else:
            from .ops.predict import predict_raw_cached
            key = (start_iteration, end, len(self.trees))
            chunk = int(predict_chunk or self.predict_chunk or (1 << 20))
            out = predict_raw_cached(self, trees, k, data, key, chunk,
                                     num_shards=self.predict_shards)
        if self.average_output and end > start_iteration:
            out /= (end - start_iteration)
        return out

    def predict_leaf(self, data: np.ndarray, start_iteration: int = 0,
                     num_iteration: int = -1) -> np.ndarray:
        """[N, num_trees] leaf indices (ref: PredictLeafIndex tree.h:138)."""
        data = np.asarray(data, np.float64)
        k = self.num_tree_per_iteration
        end = self.num_iterations if num_iteration < 0 else min(
            self.num_iterations, start_iteration + num_iteration)
        cols = [self.trees[it * k + ki].predict_leaf(data)
                for it in range(start_iteration, end) for ki in range(k)]
        return np.stack(cols, axis=1) if cols else \
            np.zeros((data.shape[0], 0), np.int32)

    def predict_contrib(self, data: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1,
                        predict_chunk: Optional[int] = None) -> np.ndarray:
        """[N, K * (F + 1)] SHAP contributions (last slot per class is
        the expected value). Dispatches to the batched device kernel
        (ops/shap.py) via shap.py; the serve `explain` route calls
        this, so served explanations and direct pred_contrib run the
        identical program and return bit-equal outputs."""
        from .shap import loaded_pred_contrib
        return loaded_pred_contrib(self, data, start_iteration,
                                   num_iteration,
                                   predict_chunk=predict_chunk)

    def predict(self, data: np.ndarray, raw_score: bool = False,
                **kwargs) -> np.ndarray:
        raw = self.predict_raw(data, **kwargs)
        if raw.shape[1] == 1:
            raw = raw[:, 0]
        if raw_score:
            return raw
        return transform_raw(self.objective_str, raw)


def loaded_model_to_string(model: LoadedModel, num_iteration: int = -1,
                           start_iteration: int = 0,
                           importance_type: str = "split") -> str:
    """Serialize a LoadedModel back to the text format (used by refit /
    model surgery on models loaded from file)."""
    k = max(model.num_tree_per_iteration, 1)
    end = model.num_iterations if num_iteration < 0 else min(
        model.num_iterations, start_iteration + num_iteration)
    trees = model.trees[start_iteration * k:end * k]

    header = ["tree", "version=v4"]
    header.append(f"num_class={model.num_class}")
    header.append(f"num_tree_per_iteration={model.num_tree_per_iteration}")
    header.append(f"label_index={model.label_index}")
    header.append(f"max_feature_idx={model.max_feature_idx}")
    header.append(f"objective={model.objective_str}")
    if model.average_output:
        header.append("average_output")
    header.append("feature_names=" + " ".join(model.feature_names))
    header.append("feature_infos=" + " ".join(model.feature_infos))

    blocks = [tree.to_string(i) + "\n" for i, tree in enumerate(trees)]
    header.append("tree_sizes=" + " ".join(
        str(len(b.encode())) for b in blocks))
    header.append("")
    out = "\n".join(header) + "\n" + "".join(blocks)
    out += "end of trees\n\n"

    imp: dict = {}
    for tree in trees:
        for s in range(tree.num_internal):
            feat = int(tree.split_feature[s])
            add = float(tree.split_gain[s]) if importance_type == "gain" \
                else 1
            imp[feat] = imp.get(feat, 0) + add
    lines = ["feature_importances:"]
    for feat in sorted(imp, key=lambda i: -imp[i]):
        name = (model.feature_names[feat]
                if feat < len(model.feature_names) else f"Column_{feat}")
        lines.append(f"{name}={imp[feat]:g}")
    out += "\n".join(lines) + "\n\n"

    out += "parameters:\n"
    for key, value in model.params.items():
        out += f"[{key}: {value}]\n"
    out += "end of parameters\n\npandas_categorical:null\n"
    return out


def load_model_from_string(text: str) -> LoadedModel:
    """Parse the reference text format into a LoadedModel.

    Structural validation (resilience satellite): a truncated or
    garbage model raises a structured ``CorruptModelError`` naming the
    byte offset where the content stopped making sense, instead of
    silently producing a partial ensemble or a bare parse exception —
    the failure modes of a half-written model file or a torn download.
    Checks: the ``tree`` header magic, per-tree block parse errors, the
    ``end of trees`` terminator, and the header's declared
    ``tree_sizes`` count against the trees actually parsed."""
    model = LoadedModel()
    if not text.lstrip().startswith("tree"):
        raise CorruptModelError(
            "not a LightGBM model: missing 'tree' header magic",
            offset=0)
    lines = text.split("\n")

    def _offset(line_no: int) -> int:
        """Byte offset of the start of line `line_no` — computed only
        on the error paths, so a healthy load (the serve registry's
        hot path) never pays a per-line encode pass."""
        return len("\n".join(lines[:line_no]).encode()) + \
            (1 if line_no > 0 else 0)

    declared_trees: Optional[int] = None
    i = 0
    # header
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if line.startswith("Tree=") or line == "end of trees":
            i -= 1
            break
        if "=" in line:
            key, value = line.split("=", 1)
            if key == "num_class":
                model.num_class = int(value)
            elif key == "num_tree_per_iteration":
                model.num_tree_per_iteration = int(value)
            elif key == "label_index":
                model.label_index = int(value)
            elif key == "max_feature_idx":
                model.max_feature_idx = int(value)
            elif key == "objective":
                model.objective_str = value
            elif key == "feature_names":
                model.feature_names = value.split()
            elif key == "feature_infos":
                model.feature_infos = value.split()
            elif key == "tree_sizes":
                declared_trees = len(value.split())
        elif line == "average_output":
            model.average_output = True

    def parse_block(block_lines: List[str], start_line: int) -> None:
        try:
            model.trees.append(Tree.from_string("\n".join(block_lines)))
        except Exception as exc:
            raise CorruptModelError(
                f"tree block {len(model.trees)} failed to parse "
                f"({exc!r}) — truncated or corrupted model",
                offset=_offset(start_line))

    # tree blocks
    block: List[str] = []
    block_start = i
    saw_end = False
    while i < len(lines):
        line = lines[i]
        i += 1
        stripped = line.strip()
        if stripped.startswith("Tree=") and block:
            parse_block(block, block_start)
            block = [stripped]
            block_start = i - 1
        elif stripped == "end of trees":
            if block:
                parse_block(block, block_start)
                block = []
            saw_end = True
            break
        elif stripped:
            if not block:
                block_start = i - 1
            block.append(stripped)
    if block:
        parse_block(block, block_start)
    if not saw_end:
        # no terminator is a truncation, with or without parsed trees:
        # a file torn mid-ensemble may carry an incomplete trailing
        # block, and one torn in the HEADER (before tree_sizes) would
        # otherwise load as a 0-tree model that silently serves
        # constants — refuse both rather than serve a partial model
        raise CorruptModelError(
            "model truncated: 'end of trees' terminator missing",
            offset=_offset(min(i, len(lines))))
    if declared_trees is not None and len(model.trees) != declared_trees:
        raise CorruptModelError(
            f"model declares tree_sizes for {declared_trees} trees but "
            f"{len(model.trees)} parsed — truncated mid-ensemble",
            offset=_offset(min(i, len(lines))))

    # parameters block
    in_params = False
    for j in range(i, len(lines)):
        s = lines[j].strip()
        if s == "parameters:":
            in_params = True
        elif s == "end of parameters":
            in_params = False
        elif in_params and s.startswith("[") and s.endswith("]"):
            inner = s[1:-1]
            if ": " in inner:
                k, v = inner.split(": ", 1)
                model.params[k] = v
    return model


def dump_model_to_json(booster, num_iteration: int = -1,
                       start_iteration: int = 0) -> dict:
    """(ref: GBDT::DumpModel)"""
    cfg = booster.config
    ds = booster.train_set
    end = len(booster.models) if num_iteration < 0 else min(
        len(booster.models), start_iteration + num_iteration)
    trees = []
    idx = 0
    for it in range(start_iteration, end):
        for tree in booster.models[it]:
            trees.append(tree.to_json(idx))
            idx += 1
    return {
        "name": "tree",
        "version": "v4",
        "num_class": max(cfg.num_class, 1),
        "num_tree_per_iteration": booster.num_tree_per_iteration,
        "label_index": ds.label_idx,
        "max_feature_idx": ds.num_total_features - 1,
        "objective": _objective_string(cfg),
        "average_output": booster.boosting_type == "rf",
        "feature_names": ds.feature_names,
        "feature_infos": {n: i for n, i in zip(ds.feature_names,
                                               ds.feature_infos())},
        "tree_info": trees,
        "feature_importances": {
            ds.feature_names[i]: float(v)
            for i, v in enumerate(booster.feature_importance("split"))
            if v > 0},
    }
