// Sanitizer self-test for the native host runtime: exercises the
// parser, bound finding, and every transform entry point (threaded, all
// dtype/order combinations) so an address/UB-sanitized build has real
// traffic to check. The reference ships the analogous tier as
// USE_SANITIZER + cpp_tests (ref: CMakeLists.txt:11-19,
// cmake/Sanitizer.cmake); here: `make -C native check-sanitize`.
//
// Exit code 0 = all assertions passed and no sanitizer report fired.

#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* LGT_ParseFile(const char* path, int label_idx, int has_header);
int64_t LGT_ParseNumRows(void* h);
int32_t LGT_ParseNumCols(void* h);
const char* LGT_ParseError(void* h);
void LGT_ParseCopy(void* h, double* data_out, double* label_out);
void LGT_ParseFree(void* h);
int32_t LGT_FindNumericalBounds(const double* values, int64_t n,
                                int max_bin, int min_data_in_bin,
                                int missing_type, int zero_as_missing,
                                double* bounds_out);
void LGT_TransformColumn(const double* values, int64_t n,
                         const double* bounds, int32_t num_bounds,
                         int missing_type, int32_t default_bin,
                         int32_t num_bins, int32_t* bins_out);
void LGT_TransformMatrix(const double* data_cm, int64_t n, int32_t f,
                         const double* bounds_flat,
                         const int64_t* bounds_offsets,
                         const int32_t* missing_types,
                         const int32_t* default_bins,
                         const int32_t* num_bins, int elem_size,
                         void* bins_out_fm);
void LGT_TransformMatrix2(const void* data, int32_t is_f32,
                          int32_t row_major, int64_t n, int32_t f,
                          const double* bounds_flat,
                          const int64_t* bounds_offsets,
                          const int32_t* missing_types,
                          const int32_t* default_bins,
                          const int32_t* num_bins, int elem_size,
                          void* bins_out_fm);
int32_t LGT_Version();
}

namespace {

double Rand01(uint64_t* s) {
  *s = *s * 6364136223846793005ULL + 1442695040888963407ULL;
  return static_cast<double>((*s >> 11) & ((1ULL << 53) - 1)) /
         static_cast<double>(1ULL << 53);
}

void TestParser() {
  const char* path = "/tmp/lgt_selftest.csv";
  FILE* fp = std::fopen(path, "w");
  std::fprintf(fp, "1,0.5,2.25,-1\n0,1.5,,3\n1,-0.25,7.5,0\n");
  std::fclose(fp);
  void* h = LGT_ParseFile(path, 0, 0);
  assert(LGT_ParseError(h) == nullptr);
  assert(LGT_ParseNumRows(h) == 3);
  assert(LGT_ParseNumCols(h) == 3);
  std::vector<double> data(9), label(3);
  LGT_ParseCopy(h, data.data(), label.data());
  LGT_ParseFree(h);
  assert(label[0] == 1 && label[1] == 0 && label[2] == 1);
  assert(data[0] == 0.5 && std::isnan(data[4]));
  std::remove(path);
}

void TestBinning() {
  const int64_t n = 200000;
  const int32_t f = 7;
  uint64_t seed = 7;
  std::vector<double> col(n);
  for (int64_t i = 0; i < n; ++i) {
    col[i] = Rand01(&seed) * 10.0 - 5.0;
    if (i % 97 == 0) col[i] = NAN;
    if (i % 31 == 0) col[i] = 0.0;
  }
  std::vector<double> bounds(66);
  int32_t nb = LGT_FindNumericalBounds(col.data(), n, 63, 3,
                                       /*kMissingNan=*/2, 0, bounds.data());
  assert(nb > 1 && nb <= 65);
  std::vector<int32_t> bins(n);
  LGT_TransformColumn(col.data(), n, bounds.data(), nb, 2, 0, nb + 1,
                      bins.data());
  for (int64_t i = 0; i < n; ++i) assert(bins[i] >= 0 && bins[i] <= nb);

  // matrix paths: v1 (f64 col-major) and v2 (all dtype/order combos)
  // must agree bin-for-bin
  // f32-representable values: a real float32 caller's data widens to
  // these exact doubles, so every dtype/order combination must agree
  // bin-for-bin
  std::vector<double> mat_rm(n * f);
  for (int64_t i = 0; i < n * f; ++i) {
    mat_rm[i] = static_cast<float>(Rand01(&seed) * 8.0 - 4.0);
    if (i % 113 == 0) mat_rm[i] = NAN;
  }
  std::vector<double> mat_cm(n * f);
  std::vector<float> mat_rm32(n * f), mat_cm32(n * f);
  for (int64_t i = 0; i < n; ++i) {
    for (int32_t j = 0; j < f; ++j) {
      double v = mat_rm[i * f + j];
      mat_cm[j * n + i] = v;
      mat_rm32[i * f + j] = static_cast<float>(v);
      mat_cm32[j * n + i] = static_cast<float>(v);
    }
  }
  std::vector<int64_t> offs(f + 1, 0);
  std::vector<double> bflat;
  std::vector<int32_t> miss(f), defb(f), nbins(f);
  for (int32_t j = 0; j < f; ++j) {
    std::vector<double> b(66);
    int32_t cnt = LGT_FindNumericalBounds(mat_cm.data() + j * n, n, 63, 3,
                                          2, 0, b.data());
    assert(cnt > 0);
    bflat.insert(bflat.end(), b.begin(), b.begin() + cnt);
    offs[j + 1] = offs[j] + cnt;
    miss[j] = 2;
    defb[j] = 0;
    nbins[j] = cnt + 1;
  }
  std::vector<uint8_t> out_v1(f * n), out(f * n);
  LGT_TransformMatrix(mat_cm.data(), n, f, bflat.data(), offs.data(),
                      miss.data(), defb.data(), nbins.data(), 1,
                      out_v1.data());
  struct Case {
    const void* data;
    int32_t is_f32, row_major;
  } cases[] = {{mat_rm.data(), 0, 1},
               {mat_cm.data(), 0, 0},
               {mat_rm32.data(), 1, 1},
               {mat_cm32.data(), 1, 0}};
  for (const Case& c : cases) {
    std::memset(out.data(), 0xFF, out.size());
    LGT_TransformMatrix2(c.data, c.is_f32, c.row_major, n, f, bflat.data(),
                         offs.data(), miss.data(), defb.data(),
                         nbins.data(), 1, out.data());
    assert(std::memcmp(out.data(), out_v1.data(), out.size()) == 0);
  }
  // empty input must be a no-op, not a crash
  LGT_TransformMatrix2(mat_rm.data(), 0, 1, 0, f, bflat.data(), offs.data(),
                       miss.data(), defb.data(), nbins.data(), 1,
                       out.data());
}

}  // namespace

int main() {
  assert(LGT_Version() >= 2);
  TestParser();
  TestBinning();
  std::printf("native selftest OK\n");
  return 0;
}
