"""serve/ subsystem: micro-batching, low-latency path, registry
eviction, async server routing, CLI entry.

Correctness tiers:
- COALESCED results must be bit-identical to calling `predict`
  directly on each request's rows (row traversal is independent and
  the per-row f32 class-sum order never depends on batch size).
- LOW-LATENCY (AOT) results must be bit-identical to the same direct
  call (same packed tensors, same traversal program, pad rows are
  inert).
- An EVICTED-then-reloaded model must reproduce its pre-eviction bytes
  (packing is deterministic; the (tree, pack_version) tokens are
  revalidated through the registry cache).
- Steady-state traffic after `warm()` triggers ZERO recompiles on both
  the engine traversal tag and the lowlat tag.
"""

import asyncio

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import main as cli_main
from lightgbm_tpu.obs.metrics import LatencyReservoir, global_metrics
from lightgbm_tpu.ops.predict import PREDICT_TRACE_TAG
from lightgbm_tpu.serve import (MicroBatcher, ModelRegistry, ModelServer,
                                SERVE_LOWLAT_TAG)
from lightgbm_tpu.serve.server import replay, request_sizes

pytestmark = pytest.mark.quick


def _data(n=500, f=8, seed=0, nans=True):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    if nans:
        x[::7, 2] = np.nan
    y = ((np.nan_to_num(x[:, 2]) + x[:, 4]) > 0.5).astype(np.float64)
    return x, y


def _model_str(x, y, extra=None, rounds=6):
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    params.update(extra or {})
    ds = lgb.Dataset(x, label=y, params=params)
    return lgb.train(params, ds, num_boost_round=rounds).model_to_string()


def _serve_setup(model_str, max_batch_rows=1024, max_wait_ms=1.0,
                 max_pack_bytes=1 << 30):
    registry = ModelRegistry(max_pack_bytes=max_pack_bytes)
    registry.load("m", model_str=model_str)
    server = ModelServer(registry, max_batch_rows=max_batch_rows,
                         max_wait_ms=max_wait_ms)
    return registry, server


# ----------------------------------------------------------------------
class TestLatencyReservoir:
    def test_quantiles_exact_when_under_capacity(self):
        res = LatencyReservoir(capacity=1000)
        for ms in range(1, 101):  # 1..100 ms
            res.note(ms / 1e3)
        s = res.summary()
        assert s["count"] == 100
        assert s["p50_ms"] == 51.0  # nearest-rank over 1..100
        assert s["p95_ms"] == 96.0
        assert s["p99_ms"] == 100.0
        assert s["max_ms"] == 100.0

    def test_bounded_memory_and_sane_quantiles_over_capacity(self):
        res = LatencyReservoir(capacity=64)
        for i in range(10_000):
            res.note(0.001 if i % 2 else 0.009)
        assert len(res._samples) == 64
        assert res.count == 10_000
        p50, p99 = res.quantiles((0.5, 0.99))
        assert 0.001 <= p50 <= 0.009 and p99 == 0.009

    def test_note_predict_feeds_reservoir(self):
        before = global_metrics.latency("predict").count
        x, y = _data(n=200)
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
        bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                        num_boost_round=2)
        bst.predict(x)
        assert global_metrics.latency("predict").count > before


# ----------------------------------------------------------------------
class TestMicroBatcher:
    def test_coalesces_and_results_match_slices(self):
        calls = []

        def predict_fn(xcat):
            calls.append(xcat.shape[0])
            return xcat.sum(axis=1, keepdims=True)

        async def run():
            b = MicroBatcher(predict_fn, max_batch_rows=1000,
                             max_wait_s=0.02)
            xs = [np.full((n, 3), float(i)) for i, n in
                  enumerate((5, 7, 11))]
            outs = await asyncio.gather(*[b.submit(x) for x in xs])
            return xs, outs

        xs, outs = asyncio.run(run())
        assert calls == [23]  # ONE coalesced dispatch
        for x, out in zip(xs, outs):
            np.testing.assert_array_equal(out, x.sum(1, keepdims=True))

    def test_size_trigger_flushes_before_deadline(self):
        calls = []

        def predict_fn(xcat):
            calls.append(xcat.shape[0])
            return xcat

        async def run():
            # huge deadline: only the size trigger (or the final
            # explicit flush) can dispatch
            b = MicroBatcher(predict_fn, max_batch_rows=16, max_wait_s=60.0)
            futs = [b.submit(np.zeros((6, 2))) for _ in range(5)]
            b.flush()  # the 6-row tail would otherwise wait out 60s
            await asyncio.gather(*futs)

        asyncio.run(run())
        # 6+6 pending, +6 would overshoot the 16-row cap -> flush(12),
        # twice; the explicit flush drains the tail
        assert calls == [12, 12, 6]

    def test_oversized_request_dispatches_alone(self):
        calls = []

        def predict_fn(xcat):
            calls.append(xcat.shape[0])
            return xcat

        async def run():
            b = MicroBatcher(predict_fn, max_batch_rows=8, max_wait_s=60.0)
            out = await b.submit(np.arange(40.0).reshape(20, 2))
            return out

        out = asyncio.run(run())
        assert calls == [20]
        np.testing.assert_array_equal(out,
                                      np.arange(40.0).reshape(20, 2))

    def test_predict_error_propagates_to_every_waiter(self):
        def predict_fn(xcat):
            raise RuntimeError("device fell over")

        async def run():
            b = MicroBatcher(predict_fn, max_batch_rows=4, max_wait_s=60.0)
            futs = [b.submit(np.zeros((2, 2))), b.submit(np.zeros((2, 2)))]
            return await asyncio.gather(*futs, return_exceptions=True)

        results = asyncio.run(run())
        assert len(results) == 2
        assert all(isinstance(r, RuntimeError) for r in results)


# ----------------------------------------------------------------------
class TestServerParity:
    def test_mixed_concurrent_requests_bit_identical_to_direct(self):
        x, y = _data(n=800)
        ms = _model_str(x, y)
        registry, server = _serve_setup(ms, max_wait_ms=2.0)
        direct = registry.get("m").model
        rng = np.random.RandomState(5)
        xt = rng.randn(4000, x.shape[1])
        xt[::9, 2] = np.nan
        sizes = [1, 3, 17, 64, 65, 128, 300, 7, 31, 700, 2, 1024] * 2

        async def run():
            try:
                return await replay(server, "m", xt, sizes, raw_score=True)
            finally:
                await server.close()

        outs = asyncio.run(run())
        lo = 0
        for s, out in zip(sizes, outs):
            hi = min(lo + s, len(xt))
            np.testing.assert_array_equal(
                out, direct.predict(xt[lo:hi], raw_score=True),
                err_msg=f"request of {s} rows diverged from direct predict")
            lo = hi
        # both paths exercised
        assert global_metrics.counter("serve/lowlat_requests") > 0
        assert global_metrics.counter("serve/batched_requests") > 0

    def test_transformed_output_matches_model_predict(self):
        x, y = _data()
        ms = _model_str(x, y)
        registry, server = _serve_setup(ms)
        direct = registry.get("m").model

        async def run():
            small = await server.predict("m", x[:5])           # lowlat
            big = await server.predict("m", x[:300])           # batched
            await server.close()
            return small, big

        small, big = asyncio.run(run())
        np.testing.assert_array_equal(small, direct.predict(x[:5]))
        np.testing.assert_array_equal(big, direct.predict(x[:300]))

    def test_zero_steady_state_recompiles_after_warm(self):
        x, y = _data(n=600)
        ms = _model_str(x, y)
        registry, server = _serve_setup(ms, max_batch_rows=512,
                                        max_wait_ms=0.5)
        server.warm("m", x.shape[1])
        warm_lo = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        warm_tr = global_metrics.recompiles(PREDICT_TRACE_TAG)
        rng = np.random.RandomState(7)
        xt = rng.randn(3000, x.shape[1])
        sizes = [1, 2, 5, 17, 64, 65, 100, 257, 400, 511, 7, 23, 40, 300]

        async def run():
            try:
                await replay(server, "m", xt, sizes, raw_score=True)
            finally:
                await server.close()

        asyncio.run(run())
        assert global_metrics.recompiles(SERVE_LOWLAT_TAG) == warm_lo, \
            "steady-state lowlat request recompiled an AOT program"
        assert global_metrics.recompiles(PREDICT_TRACE_TAG) == warm_tr, \
            "steady-state coalesced batch recompiled the traversal"

    def test_feature_width_mismatch_rejected(self):
        x, y = _data(n=300)
        ms = _model_str(x, y, rounds=3)
        registry, server = _serve_setup(ms)

        async def run(cols):
            try:
                return await server.predict("m", x[:5, :cols],
                                            raw_score=True)
            finally:
                await server.close()

        # the engine's feature gathers CLAMP out-of-range indices — a
        # narrow request must be an error, never a silent wrong answer
        with pytest.raises(ValueError, match="features"):
            asyncio.run(run(5))

    def test_server_lowlat_threshold_cannot_exceed_entry_limit(self):
        x, y = _data(n=400)
        ms = _model_str(x, y, rounds=3)
        registry = ModelRegistry(lowlat_max_rows=8)
        registry.load("m", model_str=ms)
        direct = registry.get("m").model
        # server threshold ABOVE the entry's AOT limit: mid-size
        # requests must route to the batcher, not crash the lowlat path
        server = ModelServer(registry, max_batch_rows=512,
                             max_wait_ms=0.5, lowlat_max_rows=64)

        async def run():
            a = await server.predict("m", x[:5], raw_score=True)
            b = await server.predict("m", x[:40], raw_score=True)
            await server.close()
            return a, b

        a, b = asyncio.run(run())
        np.testing.assert_array_equal(a, direct.predict(x[:5],
                                                        raw_score=True))
        np.testing.assert_array_equal(b, direct.predict(x[:40],
                                                        raw_score=True))

    @pytest.mark.slow
    def test_multiclass_parity(self):
        x, _ = _data(n=600, nans=False)
        rng = np.random.RandomState(3)
        y = rng.randint(0, 3, 600).astype(np.float64)
        ms = _model_str(x, y, {"objective": "multiclass", "num_class": 3,
                               "num_leaves": 7}, rounds=4)
        registry, server = _serve_setup(ms)
        direct = registry.get("m").model

        async def run():
            a = await server.predict("m", x[:9], raw_score=True)
            b = await server.predict("m", x[:200], raw_score=True)
            c = await server.predict("m", x[:9])  # softmax transform
            await server.close()
            return a, b, c

        a, b, c = asyncio.run(run())
        assert a.shape == (9, 3)
        np.testing.assert_array_equal(a, direct.predict(x[:9],
                                                        raw_score=True))
        np.testing.assert_array_equal(b, direct.predict(x[:200],
                                                        raw_score=True))
        np.testing.assert_array_equal(c, direct.predict(x[:9]))


# ----------------------------------------------------------------------
class TestRegistry:
    def test_hit_miss_counters_and_unknown_name(self):
        x, y = _data(n=200)
        registry = ModelRegistry()
        registry.load("a", model_str=_model_str(x, y, rounds=2))
        hits = global_metrics.counter("serve/registry_hit")
        misses = global_metrics.counter("serve/registry_miss")
        registry.get("a")
        with pytest.raises(KeyError):
            registry.get("nope")
        assert global_metrics.counter("serve/registry_hit") == hits + 1
        assert global_metrics.counter("serve/registry_miss") == misses + 1

    def test_eviction_under_budget_then_bit_identical_reload(self):
        x, y = _data(n=400)
        ms = _model_str(x, y)
        # budget of 1 byte: every request pushes the OTHER model out
        registry, server = _serve_setup(ms, max_pack_bytes=1)
        registry.load("m2", model_str=ms)
        ev0 = global_metrics.counter("serve/pack_evictions")

        async def run():
            p1 = await server.predict("m", x[:100], raw_score=True)
            q1 = await server.predict("m2", x[:100], raw_score=True)
            p2 = await server.predict("m", x[:100], raw_score=True)
            q2 = await server.predict("m2", x[:100], raw_score=True)
            await server.close()
            return p1, q1, p2, q2

        p1, q1, p2, q2 = asyncio.run(run())
        assert global_metrics.counter("serve/pack_evictions") > ev0
        # evicted-then-repacked models reproduce their bytes exactly
        np.testing.assert_array_equal(p1, p2)
        np.testing.assert_array_equal(q1, q2)
        np.testing.assert_array_equal(p1, q1)  # same model text

    def test_mru_model_is_never_evicted(self):
        x, y = _data(n=300)
        ms = _model_str(x, y, rounds=3)
        registry = ModelRegistry(max_pack_bytes=1)
        registry.load("only", model_str=ms)
        entry = registry.get("only")
        entry.predict_raw(x[:50])
        assert entry.pack_bytes() > 0
        registry.evict_to_budget()
        # a single (therefore MRU) model keeps its pack even over budget
        assert entry.pack_bytes() > 0

    def test_pack_version_invalidation_through_registry_cache(self):
        x, y = _data(n=400)
        registry = ModelRegistry()
        entry = registry.load("m", model_str=_model_str(x, y))
        before = entry.model.predict(x[:64], raw_score=True)
        # in-place leaf mutation (the DART-renorm shape) must invalidate
        # the packed slots via the (tree, pack_version) tokens — WITHOUT
        # any registry-level invalidation call
        for t in entry.model.trees:
            t.apply_shrinkage(0.5)
        after = entry.model.predict(x[:64], raw_score=True)
        assert not np.array_equal(before, after)
        np.testing.assert_allclose(after, before * 0.5, rtol=1e-6,
                                   atol=1e-7)
        # ... and the lowlat path must repack too (it was never built
        # yet here, so build it post-mutation and cross-check)
        np.testing.assert_array_equal(
            entry.lowlat_predict(x[:64])[:, 0], after)

    def test_lowlat_pack_invalidation_after_mutation(self):
        x, y = _data(n=300)
        registry = ModelRegistry()
        entry = registry.load("m", model_str=_model_str(x, y, rounds=3))
        a = entry.lowlat_predict(x[:8])
        for t in entry.model.trees:
            t.apply_shrinkage(0.25)
        # the AOT pack is keyed to the OLD tree bytes: the registry's
        # contract is that mutation goes through drop_packs (model
        # surgery is out-of-band for serving); verify drop_packs resets
        entry.drop_packs()
        b = entry.lowlat_predict(x[:8])
        np.testing.assert_allclose(b, a * 0.25, rtol=1e-6, atol=1e-7)

    def test_retire_and_reload_replaces(self):
        x, y = _data(n=200)
        ms = _model_str(x, y, rounds=2)
        registry = ModelRegistry()
        registry.load("m", model_str=ms)
        assert registry.retire("m") and not registry.retire("m")
        with pytest.raises(KeyError):
            registry.get("m")
        registry.load("m", model_str=ms)
        assert "m" in registry and len(registry) == 1

    def test_load_requires_exactly_one_source(self):
        registry = ModelRegistry()
        with pytest.raises(ValueError):
            registry.load("m")
        with pytest.raises(ValueError):
            registry.load("m", model_str="x", model_file="y")


# ----------------------------------------------------------------------
class TestCLIServe:
    def test_request_sizes_cover_all_rows(self):
        assert sum(request_sizes(1000, 0)) == 1000
        assert request_sizes(100, 32) == [32, 32, 32, 4]
        assert request_sizes(0, 0) == []

    def test_task_serve_writes_direct_predict_outputs(self, tmp_path):
        x, y = _data(n=300, nans=False)
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
        bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                        num_boost_round=3)
        model = tmp_path / "model.txt"
        bst.save_model(str(model))
        data = tmp_path / "rows.tsv"
        with open(data, "w") as fh:
            for row in x:
                fh.write("0\t" + "\t".join(f"{v:.9g}" for v in row) + "\n")
        out = tmp_path / "preds.txt"
        # the bare `serve` token is sugar for task=serve
        assert cli_main(["serve", f"input_model={model}", f"data={data}",
                         f"output_result={out}", "verbosity=-1",
                         "serve_max_wait_ms=0.5"]) == 0
        got = np.loadtxt(out)
        want = bst.predict(np.loadtxt(data)[:, 1:])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------------------
class TestRequestTracing:
    """Request-scoped trace spans (server + batcher + obs/trace): every
    request in a replay appears as a linked `serve/request` span with
    queue-wait / device-time attribution; coalesced batches list the
    trace ids they carried; the whole trace passes check_trace."""

    def setup_method(self):
        from lightgbm_tpu.obs.trace import global_tracer
        self._was_enabled = global_tracer.enabled
        global_tracer.reset()

    def teardown_method(self):
        from lightgbm_tpu.obs.trace import global_tracer
        if not self._was_enabled:
            global_tracer.disable()
        global_tracer.reset()

    def _replayed_events(self, sizes, max_wait_ms=1.0):
        from lightgbm_tpu.obs.trace import global_tracer
        x, y = _data(n=max(sum(sizes), 200), nans=False)
        _registry, server = _serve_setup(_model_str(x, y),
                                         max_wait_ms=max_wait_ms)
        server.warm("m", x.shape[1])
        global_tracer.enable()

        async def run():
            try:
                return await replay(server, "m", x, sizes, raw_score=True)
            finally:
                await server.close()

        outs = asyncio.run(run())
        return outs, global_tracer.chrome_events()

    def test_every_request_appears_linked_and_attributed(self, tmp_path):
        import json
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"))
        from check_trace import check_trace
        from lightgbm_tpu.obs.trace import global_tracer

        sizes = [1, 8, 200, 3, 300, 64, 150]
        outs, events = self._replayed_events(sizes)
        assert len(outs) == len(sizes)
        reqs = [e for e in events if e["name"] == "serve/request"]
        assert len(reqs) == len(sizes)
        ids = set()
        for ev in reqs:
            args = ev["args"]
            assert isinstance(args["trace_id"], str) and args["trace_id"]
            ids.add(args["trace_id"])
            assert args["queue_wait_us"] >= 0
            assert args["device_us"] >= 0
            assert args["path"] in ("lowlat", "batched")
            if args["path"] == "batched":
                assert "batch_id" in args
        assert len(ids) == len(sizes)  # process-unique per request
        # each batch span lists only request ids from this replay
        batches = [e for e in events if e["name"] == "serve/batch"]
        assert batches, "no coalesced batch span recorded"
        batched_ids = {t for b in batches for t in b["args"]["trace_ids"]}
        assert batched_ids <= ids
        # requests that went through a batch point back at a real batch
        batch_ids = {b["args"]["batch_id"] for b in batches}
        for ev in reqs:
            if "batch_id" in ev["args"]:
                assert ev["args"]["batch_id"] in batch_ids
        # and the exported file passes the validator's link checks
        path = str(tmp_path / "serve_trace.json")
        global_tracer.export_chrome(path)
        ok, msg = check_trace(path)
        assert ok, msg
        assert "linked request span" in msg
        with open(path) as fh:
            n_req = sum(1 for e in json.load(fh)["traceEvents"]
                        if e.get("name") == "serve/request")
        assert n_req == len(sizes)

    def test_check_trace_rejects_broken_links(self, tmp_path):
        import json
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"))
        from check_trace import check_trace
        base = [{"name": "serve/request", "ph": "X", "ts": 1, "dur": 2,
                 "pid": 1, "tid": 1,
                 "args": {"trace_id": "a-1", "queue_wait_us": 1.0,
                          "device_us": 2.0}}]
        # batch referencing an unknown request id
        doc = {"traceEvents": base + [
            {"name": "serve/batch", "ph": "X", "ts": 3, "dur": 1,
             "pid": 1, "tid": 2,
             "args": {"batch_id": 9, "trace_ids": ["a-1", "GHOST"]}}]}
        p = tmp_path / "t.json"
        p.write_text(json.dumps(doc))
        ok, msg = check_trace(str(p))
        assert not ok and "GHOST" in msg
        # request missing its attribution args
        doc2 = {"traceEvents": [
            {"name": "serve/request", "ph": "X", "ts": 1, "dur": 2,
             "pid": 1, "tid": 1, "args": {"trace_id": "a-1"}}]}
        p.write_text(json.dumps(doc2))
        ok, msg = check_trace(str(p))
        assert not ok and "queue_wait_us" in msg
        # request pointing at a batch that is not in the trace
        doc3 = {"traceEvents": base}
        doc3["traceEvents"][0]["args"]["batch_id"] = 77
        p.write_text(json.dumps(doc3))
        ok, msg = check_trace(str(p))
        assert not ok and "77" in msg

    def test_tracer_disabled_records_no_request_spans(self):
        from lightgbm_tpu.obs.trace import global_tracer
        x, y = _data(n=300, nans=False)
        _registry, server = _serve_setup(_model_str(x, y))
        assert not global_tracer.enabled

        async def run():
            try:
                return await server.predict("m", x[:100], raw_score=True)
            finally:
                await server.close()

        asyncio.run(run())
        assert global_tracer._events == []


# ----------------------------------------------------------------------
class TestServerEndpoints:
    def test_readiness_gates_on_registry_and_warming(self):
        x, y = _data(n=200, nans=False)
        registry = ModelRegistry()
        server = ModelServer(registry)
        assert not server.ready  # nothing registered
        registry.load("m", model_str=_model_str(x, y, rounds=2))
        assert server.ready
        server._warming += 1  # a warm() in flight
        assert not server.ready
        server._warming -= 1
        assert server.ready

    def test_metrics_endpoint_serves_and_flips_readiness(self):
        import urllib.error
        import urllib.request

        def get(port, path):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                    return r.status, r.read().decode()
            except urllib.error.HTTPError as exc:
                return exc.code, exc.read().decode()

        x, y = _data(n=300, nans=False)
        _registry, server = _serve_setup(_model_str(x, y, rounds=2))
        ep = server.start_metrics_endpoint(port=0)
        try:
            assert get(ep.port, "/healthz")[0] == 200
            assert get(ep.port, "/readyz")[0] == 200
            server._warming += 1
            assert get(ep.port, "/readyz")[0] == 503
            assert get(ep.port, "/healthz")[0] == 200  # liveness holds
            server._warming -= 1
            assert get(ep.port, "/readyz")[0] == 200
            code, body = get(ep.port, "/metrics")
            assert code == 200
            assert "lgbmtpu_serve_pack_bytes" in body
            assert "lgbmtpu_host_info" in body
            assert get(ep.port, "/nope")[0] == 404
        finally:
            asyncio.run(server.close())
        assert server._metrics_endpoint is None
