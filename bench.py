"""Benchmark: boosting iterations/sec on Higgs-shaped data — plus
`--predict` (bulk serving rows/sec through the tree-parallel inference
engine vs the pre-engine per-tree-scan path) and `--serve` (the async
model server's SLO on an open-loop mixed-size request trace).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

`--predict` emits metric `predict_rows_per_sec` on the serving bench
shape (T=100 trees, 255 leaves, 28 features); `vs_baseline` is the
speedup over the per-tree `lax.scan` traversal the engine replaced
(measured in the same run, same chunking), so the serving trajectory
gets its own BENCH series with a self-contained anchor.

`--serve` emits metric `serve_rows_per_sec` plus `serve_p50_ms` /
`serve_p95_ms` / `serve_p99_ms` request-latency quantiles: a synthetic
open-loop arrival trace of mixed-size requests (mostly B<=64 with
periodic medium batches) replays through serve/ModelServer on the same
bench ensemble; `vs_baseline` is the speedup over dispatching the SAME
request list sequentially straight into the engine — the no-scheduler
alternative, measured in the same run.

`--fleet` emits metric `fleet_availability` plus a `fleet` summary
dict (perf-gate check 12): the --serve open-loop trace replays through
a 3-replica FleetRouter (serve/fleet.py) with one replica killed
mid-run; availability is the fraction of requests served despite the
kill (failover retries absorb the dead replica), alongside the fleet
p99 vs a single-replica reference measured in the same run.

Baseline: the reference CPU result on Higgs-10.5M — 500 iterations in
130.094 s => 3.843 iters/sec (docs/Experiments.rst:113; see BASELINE.md).
Config mirrors the reference GPU benchmark setup (max_bin=63,
num_leaves=255, lr=0.1, min_sum_hessian=100, objective=binary —
docs/GPU-Performance.rst:108-123).

The dataset is synthetic with Higgs shape (28 features, N rows; the real
Higgs is not redistributable and this environment has no egress). Row
count defaults to 10.5M (override with BENCH_ROWS) so iters/sec is
directly comparable to the published 3.843.

Resilience: the TPU is reached through a fragile local relay that has
died mid-round before ("Unable to initialize backend 'axon'" killed the
round-3 bench before a single tree trained). main() therefore
orchestrates the actual measurement in a child process: it probes the
relay port first, retries a crashed attempt, shrinks the row count if
the full-size run dies, and finally falls back to a CPU run on a small
shard — so ONE JSON line is always emitted, with the actual row count
and platform recorded in `unit`.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

BASELINE_IPS = 500.0 / 130.094  # reference CPU Higgs-10.5M iters/sec
RELAY_PORTS = (8082, 8083, 8087)


_BENCH_MODES = ("train", "predict", "serve", "continual", "stream",
                "coldstart", "fleet", "shap", "rank")


def parse_bench_mode(argv=None, environ=None) -> str:
    """THE bench flag parser. The mode comes from a `--<mode>` flag
    (`--predict`, `--serve`; no flag = train) or, in orchestrated child
    processes, from the BENCH_MODE env var the parent forwards. Adding
    a mode means adding its name to _BENCH_MODES — not cloning another
    `"--x" in sys.argv` / env-sniff pair."""
    argv = sys.argv[1:] if argv is None else argv
    environ = os.environ if environ is None else environ
    mode = environ.get("BENCH_MODE") or "train"
    for tok in argv:
        name = tok[2:] if tok.startswith("--") else None
        if name in _BENCH_MODES:
            mode = name
        elif name is not None:
            raise SystemExit(
                f"bench.py: unknown flag {tok} "
                f"(known: {', '.join('--' + m for m in _BENCH_MODES[1:])})")
    if mode not in _BENCH_MODES:
        raise SystemExit(f"bench.py: unknown BENCH_MODE={mode!r}")
    return mode

# XLA/absl startup spam (machine-feature warnings, duplicate-registration
# errors) that would otherwise pollute the stderr tail captured into
# BENCH_*.json: abseil-prefixed log lines and the pre-init banner
_STDERR_SPAM = re.compile(
    r"^(?:[EWIF]\d{4} |WARNING: All log messages before absl)")


def _telemetry_enabled() -> bool:
    return (os.environ.get("LGBM_TPU_TIMETAG", "") not in ("", "0")
            or os.environ.get("LGBM_TPU_TELEMETRY", "") not in ("", "0")
            or bool(os.environ.get("LGBM_TPU_TRACE", "")))


def _relay_up() -> bool:
    """True if the axon TPU relay is accepting connections."""
    for port in RELAY_PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=3):
                return True
        except OSError:
            continue
    return False


def _run_child(rows: int, platform: str, timeout: float,
               out_path: str, mode: str) -> int:
    """Run one measurement attempt in a child; return its exit code.

    The child writes its JSON result line to `out_path` (not stdout):
    an abandoned timed-out child that later recovers must not be able
    to inject a second contract line onto the driver's stdout.

    Timeouts use SIGTERM + grace, never SIGKILL: force-killing a process
    attached to the TPU relay wedges the relay for the rest of the round.
    """
    if platform == "cpu":
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from lightgbm_tpu.hostenv import cpu_child_env
        env = cpu_child_env()
    else:
        env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env["BENCH_MODE"] = mode
    env["BENCH_ROWS"] = str(rows)
    env["BENCH_OUT"] = out_path
    # child stderr goes through a file so XLA startup spam can be
    # filtered before it reaches the driver's captured tail
    import tempfile
    with tempfile.NamedTemporaryFile("w+", suffix=".stderr",
                                     delete=False) as ef:
        err_path = ef.name
    rc = -1
    try:
        with open(err_path, "w") as err_fh:
            # child stdout rides the same filtered channel as stderr:
            # the ONE contract line travels via BENCH_OUT, so anything a
            # child prints (tracer exit dumps, partial obs summaries)
            # must never reach the driver's stdout directly
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                env=env, stdout=err_fh, stderr=err_fh)
            try:
                rc = proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                print(f"# bench attempt timed out after {timeout:.0f}s "
                      f"(rows={rows}, platform={platform}); SIGTERM",
                      file=sys.stderr)
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    # Leave it; do NOT SIGKILL a TPU-attached process.
                    print("# child ignored SIGTERM; abandoning it",
                          file=sys.stderr)
                rc = -1
    finally:
        _replay_child_stderr(err_path)
        try:
            os.unlink(err_path)
        except OSError:
            pass
    return rc


def _replay_child_stderr(path: str) -> None:
    """Forward the child's stderr minus the XLA machine-feature spam."""
    try:
        with open(path, errors="replace") as fh:
            for line in fh:
                if _STDERR_SPAM.match(line):
                    continue
                sys.stderr.write(line)
        sys.stderr.flush()
    except OSError:
        pass


_MODE_DEFAULT_ROWS = {"train": 10_500_000, "predict": 8_000_000,
                      "serve": 2_000_000, "continual": 2_000_000,
                      "stream": 10_500_000, "coldstart": 20_000,
                      "fleet": 500_000, "shap": 200_000, "rank": 500_000}
# CPU-fallback shard sizes: the 1-core host must finish in budget (see
# the fallback comment below); inference modes keep more rows than
# training, and --serve pays per-request scheduling on top of traversal.
# --coldstart is compile-bound, not row-bound: the shape only needs to
# be big enough that cold compile dominates, so CPU keeps the default.
_MODE_CPU_ROWS = {"train": 50_000, "predict": 300_000, "serve": 150_000,
                  "continual": 40_000, "stream": 50_000,
                  "coldstart": 20_000, "fleet": 60_000,
                  # --shap pays paths x depth per row, --rank pays
                  # pairwise lambdarank gradients per iteration: both
                  # far heavier per row than plain traversal/training
                  "shap": 20_000, "rank": 30_000}
_MODE_METRIC = {"train": "boosting_iters_per_sec_higgs_shape",
                "predict": "predict_rows_per_sec",
                "serve": "serve_rows_per_sec",
                "continual": "continual_rows_per_sec",
                "stream": "stream_rows_per_sec",
                "coldstart": "coldstart_compile_reduction",
                "fleet": "fleet_availability",
                "shap": "contrib_rows_per_sec",
                "rank": "rank_train_rows_per_sec"}


def main():
    mode = parse_bench_mode()
    requested = int(os.environ.get("BENCH_ROWS", _MODE_DEFAULT_ROWS[mode]))
    budget = float(os.environ.get("BENCH_TRY_TIMEOUT", 1200))

    attempts = []
    if _relay_up():
        attempts.append((requested, "axon", budget))
        # same-size retry first: a crashed/timed-out attempt leaves its
        # finished compilations in .jax_cache, so the retry mostly just
        # measures (a smaller row count would compile a DIFFERENT
        # program and gain nothing) — hence the halved budget
        attempts.append((requested, "axon", budget / 2))
        if requested > 1_000_000:
            attempts.append((1_000_000, "axon", budget / 2))
    else:
        print("# axon relay not listening on 127.0.0.1:8082+; "
              "skipping TPU attempts", file=sys.stderr)
    # CPU fallback: tiny shard so the 1-core host finishes (measured:
    # ~90s compile + ~11s/iter at 20k rows, 255 leaves — 100k rows blew
    # the budget in round 4's relay outage). Clearly flagged via
    # platform=cpu in the child's `unit` string. Inference is far
    # cheaper per row than training, so the inference modes keep more.
    attempts.append((min(requested, _MODE_CPU_ROWS[mode]), "cpu",
                     budget * 0.75))

    import tempfile
    queue = list(attempts)
    i = 0
    hangs = 0
    while queue:
        rows, platform, timeout = queue.pop(0)
        with tempfile.NamedTemporaryFile("r", suffix=".json") as tf:
            rc = _run_child(rows, platform, timeout, tf.name, mode)
            line = tf.read().strip()
        if rc == 0 and line:
            print(line, flush=True)
            return
        print(f"# bench attempt {i} failed rc={rc} "
              f"(rows={rows}, platform={platform})", file=sys.stderr)
        i += 1
        if platform == "axon":
            if rc == -1:
                # the TPU attempt timed out. Once could be a too-slow
                # first compile (the retry then rides .jax_cache); twice
                # means the relay is wedged and every further TPU
                # attempt would hang the same way. The abandoned child
                # may still hold the single-tenant relay — give it time
                # to finish dying before the retry reconnects.
                hangs += 1
                if hangs >= 2:
                    queue = [a for a in queue if a[1] != "axon"]
                else:
                    time.sleep(90)
            else:
                time.sleep(20)  # give a flapping relay a moment

    # Everything failed — still emit the contract line so the driver
    # records a structured result instead of a crash.
    print(json.dumps({
        "metric": _MODE_METRIC[mode],
        "value": 0.0,
        "unit": ("iters/sec" if mode == "train" else "rows/sec")
        + " (all attempts failed; see stderr)",
        "vs_baseline": 0.0,
    }))
    sys.exit(1)


def _measure():
    n = int(os.environ.get("BENCH_ROWS", 10_500_000))
    f = 28
    iters = int(os.environ.get("BENCH_ITERS", 10))
    warmup = 2

    telemetry = _telemetry_enabled()
    if telemetry:
        # record spans for the phase-time summary folded into the JSON
        # line below (export/exit-print still follow the env knobs),
        # arm the span-boundary HBM watermark sampler (no-op on CPU),
        # and the XLA introspector (compile time + cost analysis per
        # program boundary)
        from lightgbm_tpu.obs import global_tracer
        from lightgbm_tpu.obs.health import global_health
        from lightgbm_tpu.obs.memory import global_watermarks
        from lightgbm_tpu.obs.xla import global_xla
        global_tracer.enable()
        global_watermarks.enable()
        global_xla.enable()
        global_health.enable()

    import jax
    # persistent compilation cache (compile_cache.py shared policy): a
    # retried/repeated bench attempt — or a later driver run in the same
    # image — skips the multi-minute waved 255-leaf compile entirely
    from lightgbm_tpu.compile_cache import configure as _cache_configure
    _cache_configure("auto")
    import lightgbm_tpu as lgb

    platform = jax.default_backend()
    if platform == "cpu":
        # the 1-core fallback host can't turn 10 measured iterations
        # around inside the attempt budget; 1+3 iterations still give a
        # valid per-iter number once compile is excluded
        iters = min(iters, int(os.environ.get("BENCH_CPU_ITERS", 3)))
        warmup = 1
    rng = np.random.RandomState(0)
    # Higgs-like: mix of informative and noise features, ~53% positive
    x = rng.randn(n, f).astype(np.float32)
    logit = (x[:, 0] + 0.6 * x[:, 1] ** 2 + 0.4 * x[:, 2] * x[:, 3]
             - 0.3 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0.2).astype(np.float32)
    n_test = min(200_000, n)
    xt = rng.randn(n_test, f).astype(np.float32)
    lt = (xt[:, 0] + 0.6 * xt[:, 1] ** 2 + 0.4 * xt[:, 2] * xt[:, 3]
          - 0.3 * np.abs(xt[:, 4]) + 0.5 * rng.randn(n_test))
    yt = (lt > 0.2).astype(np.float32)

    params = {
        "objective": "binary",
        "num_leaves": 255,
        "learning_rate": 0.1,
        "max_bin": 63,
        "min_sum_hessian_in_leaf": 100,
        "min_data_in_leaf": 0,
        "verbosity": -1,
    }
    t0 = time.time()
    ds = lgb.Dataset(x, label=y, params=params)
    ds.construct()
    bin_time = time.time() - t0

    bst = lgb.Booster(params, ds)
    t0 = time.time()
    for _ in range(warmup):
        bst.update()
    jax.block_until_ready(bst._gbdt.scores)
    warm_time = time.time() - t0

    # BENCH_CHECKPOINT_EVERY=k snapshots the booster every k measured
    # iterations (to BENCH_CHECKPOINT_PATH or a temp file) so the
    # emitted `resilience` record — and perf-gate check 7's overhead
    # ceiling — measures the REAL snapshot cost at bench shape, not a
    # synthetic fixture. Off (default): zero code in the loop.
    ckpt_every = int(os.environ.get("BENCH_CHECKPOINT_EVERY", "0") or 0)
    ckpt_path = os.environ.get("BENCH_CHECKPOINT_PATH") or os.path.join(
        tempfile.gettempdir(), f"bench_ckpt_{os.getpid()}.ckpt")
    if ckpt_every > 0:
        from lightgbm_tpu.resilience import checkpoint as _ckpt
        _ckpt.reset_totals()

    ckpt_is_temp = ckpt_every > 0 and \
        not os.environ.get("BENCH_CHECKPOINT_PATH")
    t0 = time.time()
    try:
        for it in range(iters):
            bst.update()
            if ckpt_every > 0 and (it + 1) % ckpt_every == 0:
                _ckpt.save_checkpoint(bst, ckpt_path, iters)
        # block via a host transfer: block_until_ready alone has proven
        # unreliable on the tunneled axon platform
        _ = np.asarray(bst._gbdt.scores[0, :8])
        dt = (time.time() - t0) / iters
    finally:
        if ckpt_is_temp and os.path.exists(ckpt_path):
            os.remove(ckpt_path)  # bench-shape snapshots are large;
            # don't strand them in /tmp across runs

    iters_per_sec = 1.0 / dt

    # device-time attribution (obs/profile.py): profile a couple of
    # EXTRA iterations after the measured loop — the per-call sync the
    # fallback path inserts would depress the headline iters/sec if the
    # window overlapped the measured iterations. global_xla (enabled
    # under telemetry above) feeds cost-analysis bytes/flops into the
    # roofline join; perf-gate check 11 reads the emitted record.
    profile_extra = int(os.environ.get("BENCH_PROFILE_ITERS", "2") or 0)
    prof_summary = None
    if profile_extra > 0:
        try:
            from lightgbm_tpu.obs.profile import global_profile
            global_profile.start_window(source="bench")
            for _ in range(profile_extra):
                bst.update()
            _ = np.asarray(bst._gbdt.scores[0, :8])
            prof_summary = global_profile.stop_window()
        except Exception:
            prof_summary = None

    unit = "iters/sec (N=%d, 255 leaves, 63 bins, bin=%.1fs" % (n, bin_time)
    if platform != "tpu":
        unit += ", platform=%s" % platform
    unit += ")"
    result = {
        "metric": "boosting_iters_per_sec_higgs_shape",
        "value": round(iters_per_sec, 4),
        "unit": unit,
        "vs_baseline": round(iters_per_sec / BASELINE_IPS, 4),
    }
    # histogram HBM traffic counters (always-on obs meta, set by the
    # grower build): the driver-visible side of ROADMAP item 3 — bytes
    # per iteration under the active encodings (bin packing, gh
    # encoding, fused gradient pass, subtraction-aware wave schedule)
    # vs the unpacked/no-subtraction oracle. Checked by
    # tools/check_perf_gate.py.
    from lightgbm_tpu.obs.metrics import global_metrics
    ht = global_metrics.meta.get("hist_traffic")
    if ht:
        result["hist_bytes_per_iter"] = ht["hist_bytes_per_iter"]
        result["hist_rows_scanned_per_iter"] = ht["rows_scanned_per_iter"]
        result["hist_passes_per_iter"] = ht["passes"]
        result["hist_bytes_oracle_per_iter"] = global_metrics.meta[
            "hist_traffic_oracle"]["hist_bytes_per_iter"]
        result["hist_bytes_reduction"] = global_metrics.meta[
            "hist_bytes_reduction"]
    # cross-device collective traffic model (set when a mesh is active):
    # bytes/iter the active tpu_hist_reduce mode puts on ICI/DCN vs the
    # full-histogram psum oracle. Checked by check_perf_gate.py check 14.
    ct = global_metrics.meta.get("collective_traffic")
    if ct:
        result["collective_bytes_per_iter"] = ct[
            "collective_bytes_per_iter"]
        result["collective_reduction_mode"] = ct["reduction"]
        result["collective_reduction"] = global_metrics.meta[
            "collective_reduction"]
    # peak-HBM accounting (obs/memory.py): the analytic model is
    # always-on meta; the measured peak exists only on accelerator
    # backends (memory_stats() is None on CPU). check_perf_gate.py
    # holds model-vs-measured to the recorded band when both appear.
    # device-time + roofline record (obs/profile.py): per-program
    # device-busy seconds from the post-loop profile window, and the
    # measured-vs-peak join (achieved bytes/s, utilization, memory- vs
    # compute-bound verdict per tag). check_perf_gate.py check 11 holds
    # the coverage band and the utilization floor on this record.
    if prof_summary and prof_summary.get("device_seconds_by_tag"):
        result["device_seconds_by_tag"] = {
            tag: round(sec, 6) for tag, sec in
            prof_summary["device_seconds_by_tag"].items()}
        try:
            from lightgbm_tpu.obs.profile import global_profile
            result["roofline"] = global_profile.roofline(platform=platform)
        except Exception:
            pass
    mm = global_metrics.meta.get("mem_model")
    if mm:
        result["mem_peak_model_bytes"] = mm["peak_bytes"]
        result["mem_peak_phase"] = mm["peak_phase"]
    from lightgbm_tpu.obs.memory import measured_peak_bytes
    measured = measured_peak_bytes()
    if measured:
        result["mem_peak_measured_bytes"] = measured
    # checkpoint-overhead accounting (resilience/checkpoint.py): only
    # present when the run actually snapshotted (tpu_checkpoint_* knobs
    # in the train params); check_perf_gate.py check 7 holds the
    # snapshot time share of train wall-time to the recorded ceiling
    from lightgbm_tpu.resilience.checkpoint import checkpoint_totals
    ck = checkpoint_totals()
    if ck.get("checkpoints"):
        result["resilience"] = {
            "checkpoints": int(ck["checkpoints"]),
            "checkpoint_seconds_total": round(ck["seconds_total"], 4),
            "train_seconds": round(dt * iters, 4),
        }
    if telemetry:
        # fold the phase-time summary into the one JSON line instead of
        # leaving it buried in raw stderr
        from lightgbm_tpu.obs import global_tracer
        phases = {"bin_seconds": round(bin_time, 3),
                  "warmup_compile_seconds": round(warm_time, 3),
                  "per_iter_seconds": round(dt, 4)}
        for name, agg in global_tracer.summary().items():
            phases[name] = round(agg["seconds"], 4)
        result["phases"] = phases
        # XLA compile attribution (obs/xla.py): total compile wall-time
        # and which phase's programs recompiled, per executable
        from lightgbm_tpu.obs.xla import global_xla
        xs = global_xla.summary()
        if xs["n_programs"]:
            result["compile_s_total"] = xs["compile_s_total"]
            result["n_recompiles_by_phase"] = xs["n_recompiles_by_phase"]
        # live per-phase HBM watermarks (accelerator backends only —
        # the sampler self-disables where memory_stats() is None)
        from lightgbm_tpu.obs.memory import global_watermarks
        wm = global_watermarks.summary()
        if wm:
            result["mem_phase_watermarks"] = {
                name: ph["delta_bytes"] for name, ph in wm.items()}
        # training-health summary (obs/health.py): runtime-attributed
        # collective calls/bytes per tag, the timed collective probe,
        # straggler skew, drift/nonfinite counters — the comms-health
        # side of the item-4 gate (tools/check_perf_gate.py health
        # check reads these fields from the candidate JSON)
        from lightgbm_tpu.obs.health import global_health
        hs = global_health.summary()
        if hs:
            result["health"] = hs
    out_path = os.environ.get("BENCH_OUT")
    if out_path:  # orchestrated: parent prints the single contract line
        with open(out_path, "w") as fh:
            fh.write(json.dumps(result) + "\n")
    else:
        print(json.dumps(result), flush=True)
    # quality sanity: held-out AUC after the benchmarked iterations — a
    # guard on the bf16-input histogram path (tpu_hist_precision default)
    try:
        pred = bst.predict(xt, raw_score=True)
        order = np.argsort(pred)
        ranks = np.empty(n_test)
        ranks[order] = np.arange(1, n_test + 1)
        pos = yt > 0.5
        auc = (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) / (
            pos.sum() * (~pos).sum())
        auc_line = f"test_auc@{warmup + iters}iters={auc:.4f}"
    except Exception as exc:  # never let the sanity check kill the bench
        auc_line = f"auc_check_failed={exc!r}"
    print(f"# platform={platform} bin={bin_time:.1f}s "
          f"warmup+compile={warm_time:.1f}s per_iter={dt:.3f}s {auc_line}",
          file=sys.stderr)


def _random_trees(rng, num_trees: int, num_leaves: int, num_features: int):
    """Synthetic 255-leaf ensembles for the serving bench: training 100
    such trees on CPU would dwarf the attempt budget, and inference
    throughput only depends on tree SHAPE, not split quality. Topology
    follows the learner's numbering (internal node s splits an existing
    leaf; left child keeps the parent's leaf id, right child becomes
    leaf s+1)."""
    from lightgbm_tpu.tree import Tree
    trees = []
    for _ in range(num_trees):
        tr = Tree(num_leaves)
        slot = {}  # leaf id -> (node, side) where that leaf hangs
        for s in range(num_leaves - 1):
            leaf = int(rng.randint(0, s + 1))
            if leaf in slot:
                node, side = slot.pop(leaf)
                (tr.left_child if side == 0 else tr.right_child)[node] = s
            tr.split_feature[s] = tr.split_feature_inner[s] = \
                rng.randint(0, num_features)
            tr.threshold[s] = rng.randn() * 0.7
            tr.left_child[s] = ~leaf
            tr.right_child[s] = ~(s + 1)
            slot[leaf] = (s, 0)
            slot[s + 1] = (s, 1)
        tr.leaf_value[:] = rng.randn(num_leaves) * 0.1
        # synthetic cover counts so the SHAP bench can form z-fractions
        # (child_count / parent_count); internal counts are the exact
        # subtree sums, built children-first (node s's children are
        # always leaves or internal nodes > s)
        tr.leaf_count[:] = rng.randint(1, 100, num_leaves)
        for s in reversed(range(num_leaves - 1)):
            tr.internal_count[s] = sum(
                tr.leaf_count[~c] if c < 0 else tr.internal_count[c]
                for c in (tr.left_child[s], tr.right_child[s]))
        trees.append(tr)
    return trees


def _measure_predict():
    """Serving bench: rows/sec through the streaming inference engine
    (vmapped tree-parallel traversal) vs the pre-engine per-tree scan,
    same ensemble, same chunking — bit-equality asserted on a probe
    block before timing."""
    n = int(os.environ.get("BENCH_ROWS", 8_000_000))
    t = int(os.environ.get("BENCH_PREDICT_TREES", 100))
    leaves = int(os.environ.get("BENCH_PREDICT_LEAVES", 255))
    f = 28
    chunk = int(os.environ.get("BENCH_PREDICT_CHUNK", 1 << 20))

    import jax
    from lightgbm_tpu.compile_cache import configure as _cache_configure
    _cache_configure("auto")
    import numpy as np
    from lightgbm_tpu.ops import predict as pred_ops

    platform = jax.default_backend()
    rng = np.random.RandomState(0)
    trees = _random_trees(rng, t, leaves, f)
    data = rng.randn(n, f).astype(np.float64)

    class _Owner:  # packed-ensemble cache host
        pass

    owner = _Owner()

    def engine_run():
        return pred_ops.predict_raw_cached(owner, trees, 1, data, "bench",
                                           chunk)

    ens = pred_ops.pack_ensemble(trees, 1)

    def scan_run():
        # the pre-change path: per-tree lax.scan, exact chunk shapes
        import jax.numpy as jnp
        outs = []
        for lo in range(0, n, chunk):
            x = jnp.asarray(data[lo:lo + chunk], jnp.float32)
            outs.append(np.asarray(pred_ops.predict_raw_scan(ens, x),
                                   np.float64))
        return np.concatenate(outs, axis=0)

    # correctness probe: the engine must reproduce the scan path bitwise
    probe = min(n, 10_000)
    import jax.numpy as jnp
    probe_scan = np.asarray(pred_ops.predict_raw_scan(
        ens, jnp.asarray(data[:probe], jnp.float32)), np.float64)
    probe_engine = pred_ops.predict_raw_cached(
        _Owner(), trees, 1, data[:probe], "probe", chunk)
    bit_equal = bool(np.array_equal(probe_scan, probe_engine))

    engine_run()  # compile + warm
    reps = int(os.environ.get("BENCH_PREDICT_REPS", 3))
    t0 = time.time()
    for _ in range(reps):
        engine_run()
    engine_rps = n * reps / (time.time() - t0)

    scan_run()  # compile + warm
    t0 = time.time()
    scan_run()
    scan_rps = n / (time.time() - t0)

    unit = "rows/sec (N=%d, T=%d, %d leaves" % (n, t, leaves)
    if platform != "tpu":
        unit += ", platform=%s" % platform
    if not bit_equal:
        unit += ", PARITY-MISMATCH"
    unit += ")"
    result = {
        "metric": "predict_rows_per_sec",
        "value": round(engine_rps, 1),
        "unit": unit,
        # anchor: speedup over the per-tree-scan path this engine replaced
        "vs_baseline": round(engine_rps / max(scan_rps, 1e-9), 4),
        "scan_rows_per_sec": round(scan_rps, 1),
    }
    out_path = os.environ.get("BENCH_OUT")
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(json.dumps(result) + "\n")
    else:
        print(json.dumps(result), flush=True)
    print("# platform=%s engine=%.0f rows/s scan=%.0f rows/s "
          "speedup=%.2fx bit_equal=%s"
          % (platform, engine_rps, scan_rps, engine_rps / max(scan_rps, 1e-9),
             bit_equal), file=sys.stderr)


def _measure_shap():
    """Explanation bench: SHAP-contribution rows/sec through the batched
    device TreeSHAP kernel (ops/shap.py, path-decomposed pack) vs the
    reference recursive host oracle measured in the SAME run on a row
    subset — the per-row recursion cost is row-count-independent, so the
    subset extrapolates. Parity between the two is asserted on that
    subset before timing; the path-table pack bytes ride along so the
    perf gate can band them against the analytic memory model."""
    n = int(os.environ.get("BENCH_ROWS", 200_000))
    t = int(os.environ.get("BENCH_SHAP_TREES", 50))
    leaves = int(os.environ.get("BENCH_SHAP_LEAVES", 31))
    f = 28
    chunk = int(os.environ.get("BENCH_SHAP_CHUNK", 4096))

    import jax
    from lightgbm_tpu.compile_cache import configure as _cache_configure
    _cache_configure("auto")
    from lightgbm_tpu.ops import predict as pred_ops
    from lightgbm_tpu.ops import shap as shap_ops
    from lightgbm_tpu import shap as shap_host
    from lightgbm_tpu.obs.memory import predict_memory_model

    platform = jax.default_backend()
    rng = np.random.RandomState(0)
    trees = _random_trees(rng, t, leaves, f)
    data = rng.randn(n, f).astype(np.float64)
    data[::11, 3] = np.nan  # exercise the missing-routing tables

    class _Owner:  # packed path-table cache host
        pass

    owner = _Owner()

    def device_run():
        return shap_ops.shap_contrib_cached(owner, trees, 1, data, f,
                                            "bench", chunk)

    # host recursive oracle on a subset: minutes per thousand rows at
    # this tree count, so the subset carries the baseline
    n_oracle = min(int(os.environ.get("BENCH_SHAP_ORACLE_ROWS", 128)), n)
    t0 = time.time()
    oracle = shap_host._contrib_over_trees(
        lambda it, ki: trees[it], t, 1, data[:n_oracle], f, 0, -1)
    oracle_rps = n_oracle / (time.time() - t0)

    dev = device_run()  # compile + warm (and the parity source)
    scale = max(np.abs(oracle).max(), 1.0)
    rel_err = float(np.abs(dev[:n_oracle] - oracle).max() / scale)
    bit_equal = rel_err <= 2e-3  # f32 recurrence noise vs f64 recursion

    reps = int(os.environ.get("BENCH_SHAP_REPS", 3))
    t0 = time.time()
    for _ in range(reps):
        device_run()
    device_rps = n * reps / (time.time() - t0)

    packer = pred_ops._get_packer(owner, "bench")
    pack = packer.shap_update(trees, 1, f, chunk_rows=chunk)  # cached
    model = predict_memory_model(
        num_rows=n, num_features=f, num_trees=t, num_leaves=leaves,
        chunk_rows=chunk, contrib=True)

    unit = "rows/sec (N=%d, T=%d, %d leaves" % (n, t, leaves)
    if platform != "tpu":
        unit += ", platform=%s" % platform
    if not bit_equal:
        unit += ", PARITY-MISMATCH"
    unit += ")"
    result = {
        "metric": "contrib_rows_per_sec",
        "value": round(device_rps, 1),
        "unit": unit,
        # anchor: speedup over the reference recursion this kernel
        # replaced (perf-gate check 13 floors this)
        "vs_baseline": round(device_rps / max(oracle_rps, 1e-9), 4),
        "shap": {
            "device_rows_per_sec": round(device_rps, 1),
            "oracle_rows_per_sec": round(oracle_rps, 2),
            "oracle_rows": n_oracle,
            "oracle_rel_err": round(rel_err, 8),
            "paths": int(pack.num_paths),
            "depth": int(pack.depth),
            "pack_bytes": int(2 * packer.shap_nbytes),
            "model_pack_bytes": int(model["components"]["shap_pack"]),
            "chunk_rows": chunk,
        },
    }
    out_path = os.environ.get("BENCH_OUT")
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(json.dumps(result) + "\n")
    else:
        print(json.dumps(result), flush=True)
    print("# platform=%s device=%.0f rows/s oracle=%.1f rows/s "
          "speedup=%.1fx paths=%d depth=%d rel_err=%.2g"
          % (platform, device_rps, oracle_rps,
             device_rps / max(oracle_rps, 1e-9), pack.num_paths,
             pack.depth, rel_err), file=sys.stderr)


def _measure_rank():
    """Ranking bench: lambdarank training rows/sec on a synthetic
    query/document fixture plus a served smoke trace of the trained
    ranker — the first recorded datapoint for the ranking objective.
    vs_baseline anchors lambdarank against a pointwise binary train of
    the SAME shape in the same run (the pairwise-gradient overhead)."""
    import asyncio

    n = int(os.environ.get("BENCH_ROWS", 500_000))
    f = 20
    qsize = int(os.environ.get("BENCH_RANK_QUERY_SIZE", 20))
    iters = int(os.environ.get("BENCH_RANK_ITERS", 10))
    warmup = 2

    import jax
    from lightgbm_tpu.compile_cache import configure as _cache_configure
    _cache_configure("auto")
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve import ModelRegistry, ModelServer, replay
    from lightgbm_tpu.obs.metrics import global_metrics

    platform = jax.default_backend()
    rng = np.random.RandomState(0)
    n_query = max(n // qsize, 1)
    n = n_query * qsize
    x = rng.randn(n, f)
    group = np.full(n_query, qsize, np.int32)
    # graded relevance 0..3: a noisy monotone function of two features
    score = x[:, 0] + 0.5 * x[:, 3] + rng.randn(n) * 0.7
    y = np.clip(np.digitize(score, (-1.0, 0.3, 1.5)), 0, 3).astype(
        np.float64)

    params = {"objective": "lambdarank", "num_leaves": 63,
              "learning_rate": 0.1, "verbosity": -1}
    ds = lgb.Dataset(x, label=y, group=group, params=params)
    t0 = time.time()
    bst = lgb.train(params, ds, num_boost_round=warmup)
    warm_time = time.time() - t0
    t0 = time.time()
    bst = lgb.train(params, ds, num_boost_round=warmup + iters)
    rank_rps = n * (warmup + iters) / (time.time() - t0)

    # pointwise anchor: binary train, identical data shape and leaves
    p2 = dict(params, objective="binary")
    yb = (y >= 2).astype(np.float64)
    ds2 = lgb.Dataset(x, label=yb, params=p2)
    lgb.train(p2, ds2, num_boost_round=warmup)
    t0 = time.time()
    lgb.train(p2, ds2, num_boost_round=warmup + iters)
    binary_rps = n * (warmup + iters) / (time.time() - t0)

    # quality sanity: mean NDCG@5 of the trained ranker over the queries
    pred = bst.predict(x, raw_score=True)
    gains, ndcg = 2.0 ** y - 1.0, []
    disc = 1.0 / np.log2(np.arange(2, qsize + 2))
    for q in range(min(n_query, 2000)):
        sl = slice(q * qsize, (q + 1) * qsize)
        g, p = gains[sl], pred[sl]
        ideal = (np.sort(g)[::-1][:5] * disc[:5]).sum()
        if ideal <= 0:
            continue
        got = (g[np.argsort(-p)][:5] * disc[:5]).sum()
        ndcg.append(got / ideal)
    ndcg5 = float(np.mean(ndcg)) if ndcg else 0.0

    # serve smoke: the trained ranker behind ModelServer, mixed-size
    # trace (lowlat + coalesced), request latency reservoir
    registry = ModelRegistry()
    registry.load("rank", booster=bst)
    server = ModelServer(registry, max_batch_rows=8192, max_wait_ms=2.0)
    server.warm("rank", f)
    smoke_rows = min(n, int(os.environ.get("BENCH_RANK_SERVE_ROWS",
                                           100_000)))
    sizes = _serve_request_sizes(rng, smoke_rows)
    global_metrics.reset_latency("serve/request")

    async def run():
        try:
            await replay(server, "rank", x[:smoke_rows], sizes,
                         raw_score=True)
        finally:
            await server.close()

    t0 = time.time()
    asyncio.run(run())
    serve_rps = smoke_rows / (time.time() - t0)
    lat = global_metrics.latency_summary("serve/request")

    unit = ("rows/sec (N=%d, %d queries x %d docs, %d iters"
            % (n, n_query, qsize, warmup + iters))
    if platform != "tpu":
        unit += ", platform=%s" % platform
    unit += ")"
    result = {
        "metric": "rank_train_rows_per_sec",
        "value": round(rank_rps, 1),
        "unit": unit,
        # anchor: lambdarank vs pointwise binary training, same shape
        "vs_baseline": round(rank_rps / max(binary_rps, 1e-9), 4),
        "rank": {
            "train_rows_per_sec": round(rank_rps, 1),
            "binary_rows_per_sec": round(binary_rps, 1),
            "train_ndcg5": round(ndcg5, 4),
            "serve_rows_per_sec": round(serve_rps, 1),
            "serve_p50_ms": lat["p50_ms"],
            "serve_p99_ms": lat["p99_ms"],
            "serve_requests": len(sizes),
        },
    }
    out_path = os.environ.get("BENCH_OUT")
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(json.dumps(result) + "\n")
    else:
        print(json.dumps(result), flush=True)
    print("# platform=%s rank=%.0f rows/s binary=%.0f rows/s "
          "ndcg@5=%.3f serve=%.0f rows/s p50=%.2fms p99=%.2fms "
          "(first train warmup %.1fs)"
          % (platform, rank_rps, binary_rps, ndcg5, serve_rps,
             lat["p50_ms"], lat["p99_ms"], warm_time), file=sys.stderr)


def _serve_request_sizes(rng, total_rows: int):
    """Mixed-traffic request sizes for the serving trace: ~3/4 of
    requests are small (1..64 rows, the low-latency path), the rest
    medium batches (256..2048) — small requests dominate the request
    COUNT while medium ones carry most of the rows, the shape the
    micro-batcher exists for."""
    small = (1, 2, 4, 8, 16, 32, 64)
    medium = (256, 512, 1024, 2048)
    sizes = []
    done = 0
    i = 0
    while done < total_rows:
        pick = (medium[int(rng.randint(len(medium)))] if i % 4 == 3
                else small[int(rng.randint(len(small)))])
        sizes.append(min(pick, total_rows - done))
        done += sizes[-1]
        i += 1
    return sizes


def _measure_serve():
    """Serving SLO bench: an open-loop synthetic arrival trace of
    mixed-size requests replays through serve/ModelServer (warm shape
    buckets, AOT low-latency path, deadline-bounded coalescing);
    emits served rows/sec + request p50/p95/p99. vs_baseline anchors
    against the no-scheduler alternative measured in the same run: the
    SAME request list dispatched sequentially straight into the engine."""
    import asyncio

    n = int(os.environ.get("BENCH_ROWS", 2_000_000))
    t = int(os.environ.get("BENCH_PREDICT_TREES", 100))
    leaves = int(os.environ.get("BENCH_PREDICT_LEAVES", 255))
    f = 28
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", 8192))
    max_wait_ms = float(os.environ.get("BENCH_SERVE_MAX_WAIT_MS", 2.0))

    import jax
    from lightgbm_tpu.compile_cache import configure as _cache_configure
    _cache_configure("auto")
    from lightgbm_tpu.model_io import LoadedModel
    from lightgbm_tpu.serve import ModelRegistry, ModelServer, replay
    from lightgbm_tpu.obs.metrics import global_metrics

    platform = jax.default_backend()
    rng = np.random.RandomState(0)
    trees = _random_trees(rng, t, leaves, f)
    model = LoadedModel()
    model.trees = trees
    model.num_tree_per_iteration = 1
    model.objective_str = "binary sigmoid:1"
    model.max_feature_idx = f - 1

    registry = ModelRegistry()
    registry.load("bench", model=model)
    server = ModelServer(registry, max_batch_rows=max_batch,
                         max_wait_ms=max_wait_ms)
    data = rng.randn(n, f)
    sizes = _serve_request_sizes(rng, n)
    bounds = np.concatenate([[0], np.cumsum(sizes)])

    server.warm("bench", f)

    # parity probe: served bytes must equal direct predict bytes on
    # both paths (small -> lowlat, medium -> coalesced)
    async def probe():
        idx = [i for i, s in enumerate(sizes[:64]) if s <= 64][:2] + \
              [i for i, s in enumerate(sizes[:64]) if s > 64][:2]
        outs = await asyncio.gather(*[
            server.predict("bench", data[bounds[i]:bounds[i + 1]],
                           raw_score=True) for i in idx])
        ok = all(np.array_equal(
            out, model.predict(data[bounds[i]:bounds[i + 1]],
                               raw_score=True))
            for i, out in zip(idx, outs))
        return ok

    bit_equal = asyncio.run(probe())

    # no-scheduler baseline: the same requests, sequential engine calls
    n_base = min(len(sizes), int(os.environ.get("BENCH_SERVE_BASE_REQS",
                                                400)))
    t0 = time.time()
    for i in range(n_base):
        model.predict_raw(data[bounds[i]:bounds[i + 1]])
    direct_rps = float(bounds[n_base]) / (time.time() - t0)

    # bulk engine capacity (informative anchor for the JSON line)
    bulk_rows = int(min(n, 1 << 20))
    model.predict_raw(data[:bulk_rows])  # warm the full-chunk bucket
    t0 = time.time()
    model.predict_raw(data[:bulk_rows])
    bulk_rps = bulk_rows / (time.time() - t0)

    # two trace halves: a zero-gap burst measures sustainable CAPACITY
    # (per-request scheduling included — the headline rows/sec), then
    # the second half replays at 70% of that capacity with Poisson
    # arrivals so p50/p99 reflect steady-state service, not the
    # unbounded queue growth of an over-saturated open loop
    half = max(len(sizes) // 2, 1)
    sizes_cap, sizes_slo = sizes[:half], (sizes[half:] or sizes[:half])
    data_slo = data[bounds[half]:] if sizes[half:] else data

    async def burst():
        await replay(server, "bench", data, sizes_cap, raw_score=True)

    t0 = time.time()
    asyncio.run(burst())
    served_rps = float(bounds[half]) / (time.time() - t0)

    offered_rps = float(os.environ.get("BENCH_SERVE_LOAD", 0.7)) \
        * served_rps
    gaps = rng.exponential(
        np.asarray(sizes_slo, np.float64) / offered_rps)
    arrivals = np.concatenate([[0.0], np.cumsum(gaps)[:-1]])

    global_metrics.reset_latency("serve/request")

    async def timed():
        try:
            return await replay(server, "bench", data_slo, sizes_slo,
                                raw_score=True, arrival_s=arrivals)
        finally:
            await server.close()

    asyncio.run(timed())
    lat = global_metrics.latency_summary("serve/request")

    unit = ("rows/sec (N=%d, T=%d, %d leaves, %d requests, "
            "offered=%.0f rows/s" % (n, t, leaves, len(sizes),
                                     offered_rps))
    if platform != "tpu":
        unit += ", platform=%s" % platform
    if not bit_equal:
        unit += ", PARITY-MISMATCH"
    unit += ")"
    result = {
        "metric": "serve_rows_per_sec",
        "value": round(served_rps, 1),
        "unit": unit,
        # anchor: speedup over sequential per-request engine dispatch
        "vs_baseline": round(served_rps / max(direct_rps, 1e-9), 4),
        "serve_p50_ms": lat["p50_ms"],
        "serve_p95_ms": lat["p95_ms"],
        "serve_p99_ms": lat["p99_ms"],
        "serve_rows_per_sec": round(served_rps, 1),
        "direct_rows_per_sec": round(direct_rps, 1),
        "bulk_rows_per_sec": round(bulk_rps, 1),
    }
    out_path = os.environ.get("BENCH_OUT")
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(json.dumps(result) + "\n")
    else:
        print(json.dumps(result), flush=True)
    print("# platform=%s serve=%.0f rows/s direct=%.0f rows/s "
          "bulk=%.0f rows/s p50=%.2fms p99=%.2fms bit_equal=%s"
          % (platform, served_rps, direct_rps, bulk_rps,
             lat["p50_ms"], lat["p99_ms"], bit_equal), file=sys.stderr)


def _measure_fleet():
    """Fleet chaos bench (serve/fleet.py): the --serve open-loop trace
    fronted by an N-replica FleetRouter with one replica KILLED mid-run.
    Emits `fleet_availability` (fraction of requests served despite the
    kill — failover retries absorb the dead replica; perf-gate check 12
    holds it >= 0.999) plus the fleet p50/p99 against a single-replica
    reference replayed in the same run, the failover/quarantine
    counters, and a served-vs-direct bit-parity verdict."""
    import asyncio

    n = int(os.environ.get("BENCH_ROWS", 500_000))
    t = int(os.environ.get("BENCH_PREDICT_TREES", 100))
    leaves = int(os.environ.get("BENCH_PREDICT_LEAVES", 255))
    f = 28
    n_replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", 3))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", 8192))
    max_wait_ms = float(os.environ.get("BENCH_SERVE_MAX_WAIT_MS", 2.0))

    import jax
    from lightgbm_tpu.compile_cache import configure as _cache_configure
    _cache_configure("auto")
    from lightgbm_tpu.model_io import LoadedModel
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.serve import (InProcessReplica, FleetRouter,
                                    ModelRegistry, ModelServer, replay)

    platform = jax.default_backend()
    rng = np.random.RandomState(0)
    trees = _random_trees(rng, t, leaves, f)

    def make_replica(i: int) -> InProcessReplica:
        # each replica packs its own registry from the SAME trees —
        # the bit-identical-pack contract the failover math rests on
        model = LoadedModel()
        model.trees = trees
        model.num_tree_per_iteration = 1
        model.objective_str = "binary sigmoid:1"
        model.max_feature_idx = f - 1
        registry = ModelRegistry()
        registry.load("bench", model=model)
        return InProcessReplica(f"r{i}", ModelServer(
            registry, max_batch_rows=max_batch, max_wait_ms=max_wait_ms))

    replicas = [make_replica(i) for i in range(n_replicas)]
    fleet = FleetRouter(replicas, probe_interval_ms=10.0,
                        breaker_reset_s=0.25).start()
    data = rng.randn(n, f)
    sizes = _serve_request_sizes(rng, n)
    bounds = np.concatenate([[0], np.cumsum(sizes)])
    for rep in replicas:
        # the process-wide compile cache makes replicas 1..N-1 warm
        # from replica 0's compiles
        rep.server.warm("bench", f)
    ref_model = replicas[0].server.registry.get("bench").model

    # single-replica reference: the same trace shape straight through
    # one ModelServer (what --serve measures), for the p99 comparison
    half = max(len(sizes) // 2, 1)
    global_metrics.reset_latency("serve/request")
    t0 = time.time()
    asyncio.run(replay(replicas[0].server, "bench",
                       data[:bounds[half]], sizes[:half], raw_score=True))
    single_rps = float(bounds[half]) / (time.time() - t0)
    single_lat = global_metrics.latency_summary("serve/request")

    # fleet phase: open-loop Poisson arrivals at 70% of the measured
    # single-replica capacity; replica 0 dies at the 40% mark
    offered_rps = float(os.environ.get("BENCH_SERVE_LOAD", 0.7)) \
        * single_rps
    gaps = rng.exponential(np.asarray(sizes, np.float64) / offered_rps)
    arrivals = np.concatenate([[0.0], np.cumsum(gaps)[:-1]])
    kill_idx = max(int(0.4 * len(sizes)), 1)
    lat_all: list = []
    lat_post_kill: list = []
    state = {"failed": 0, "kill_t": None}

    async def one(i: int) -> None:
        if arrivals[i] > 0:
            await asyncio.sleep(float(arrivals[i]))
        if i == kill_idx:
            replicas[0].fail_dispatch = True  # SIGKILL stand-in
            state["kill_t"] = time.perf_counter()
        t_req = time.perf_counter()
        try:
            await fleet.predict("bench", data[bounds[i]:bounds[i + 1]],
                                raw_score=True)
        except Exception:
            state["failed"] += 1
            return
        dt = time.perf_counter() - t_req
        lat_all.append(dt)
        if state["kill_t"] is not None and \
                t_req >= state["kill_t"]:
            lat_post_kill.append(dt)

    async def fleet_phase() -> None:
        await asyncio.gather(*[one(i) for i in range(len(sizes))])

    t0 = time.time()
    asyncio.run(fleet_phase())
    fleet_wall = time.time() - t0

    # bit parity: fleet answers (now riding the survivors) vs direct
    async def probe() -> bool:
        idx = list(range(min(4, len(sizes))))
        outs = await asyncio.gather(*[
            fleet.predict("bench", data[bounds[i]:bounds[i + 1]],
                          raw_score=True) for i in idx])
        return all(np.array_equal(
            out, ref_model.predict(data[bounds[i]:bounds[i + 1]],
                                   raw_score=True))
            for i, out in zip(idx, outs))

    bit_equal = asyncio.run(probe())
    fstats = fleet.stats()
    counters = fstats["counters"]

    async def teardown() -> None:
        fleet.stop()
        for rep in replicas:
            await rep.server.close()

    asyncio.run(teardown())

    served = len(lat_all)
    total = served + state["failed"]
    availability = served / max(total, 1)
    q = (lambda a, p: float(np.percentile(np.asarray(a) * 1e3, p))
         if a else 0.0)
    fleet_summary = {
        "availability": round(availability, 6),
        "requests": total,
        "served": served,
        "failed": state["failed"],
        "replicas": n_replicas,
        "failovers": int(counters.get("fleet/failovers", 0)),
        "quarantines": int(counters.get("fleet/quarantines", 0)),
        "killed_quarantined": bool(
            fstats["replicas"]["r0"]["quarantined"]),
        "p50_ms": round(q(lat_all, 50), 3),
        "p99_ms": round(q(lat_all, 99), 3),
        "failover_p99_ms": round(q(lat_post_kill, 99), 3),
        "single_p50_ms": single_lat["p50_ms"],
        "single_p99_ms": single_lat["p99_ms"],
        "single_rows_per_sec": round(single_rps, 1),
        "rows_per_sec": round(float(bounds[-1]) / max(fleet_wall, 1e-9),
                              1),
        "parity_ok": bool(bit_equal),
    }
    unit = ("fraction served (N=%d, T=%d, %d leaves, %d requests, "
            "%d replicas, kill@40%%" % (n, t, leaves, total, n_replicas))
    if platform != "tpu":
        unit += ", platform=%s" % platform
    if not bit_equal:
        unit += ", PARITY-MISMATCH"
    unit += ")"
    result = {
        "metric": "fleet_availability",
        "value": round(availability, 6),
        "unit": unit,
        # the anchor IS the availability target: 1.0 = no request lost
        "vs_baseline": round(availability, 6),
        "fleet": fleet_summary,
    }
    out_path = os.environ.get("BENCH_OUT")
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(json.dumps(result) + "\n")
    else:
        print(json.dumps(result), flush=True)
    print("# platform=%s availability=%.6f served=%d/%d failovers=%d "
          "quarantines=%d fleet_p99=%.2fms single_p99=%.2fms "
          "bit_equal=%s"
          % (platform, availability, served, total,
             fleet_summary["failovers"], fleet_summary["quarantines"],
             fleet_summary["p99_ms"], fleet_summary["single_p99_ms"],
             bit_equal), file=sys.stderr)


def _measure_continual():
    """Continual-training bench (resilience/continual.py): BENCH_ROWS
    of Higgs-shaped data ingested in BENCH_CONTINUAL_GENERATIONS
    chunks, one generation per chunk (init_model continuation +
    eval-anomaly gate + validated hot-swap into a live ModelRegistry).
    Emits ingested rows/sec plus the `continual` summary dict —
    swap/rollback overhead share included, which perf-gate check 8
    caps. vs_baseline anchors against the no-continual alternative
    measured in the same run: ONE monolithic train on the full data
    for the same total iteration count (what a fleet would rerun from
    scratch on every refresh)."""
    n = int(os.environ.get("BENCH_ROWS", 40_000))
    gens = int(os.environ.get("BENCH_CONTINUAL_GENERATIONS", 5))
    rounds = int(os.environ.get("BENCH_CONTINUAL_ROUNDS", 10))
    f = 28

    import jax
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve import ModelRegistry

    platform = jax.default_backend()
    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    logit = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.3 * X[:, 2] * X[:, 3]
    y = (logit + 0.2 * rng.randn(n) > 0.5).astype(np.float32)

    params = {"objective": "binary", "max_bin": 63, "num_leaves": 255,
              "learning_rate": 0.1, "min_sum_hessian_in_leaf": 100,
              "verbosity": -1, "tpu_continual_rounds": rounds,
              "tpu_continual_eval_fraction": 0.2}
    registry = ModelRegistry()
    trainer = lgb.ContinualTrainer(params, num_features=f,
                                   registry=registry,
                                   serve_name="bench-continual")
    bounds = np.linspace(0, n, gens + 1).astype(int)
    t0 = time.perf_counter()
    for g in range(gens):
        s, e = bounds[g], bounds[g + 1]
        trainer.push_rows(X[s:e], label=y[s:e])
        trainer.step()
    wall = time.perf_counter() - t0

    # the no-continual anchor: one monolithic train over everything,
    # same total iteration budget, measured in the same run
    t0 = time.perf_counter()
    lgb.train(dict(params), lgb.Dataset(X, label=y),
              num_boost_round=gens * rounds)
    mono_wall = time.perf_counter() - t0

    summary = trainer.summary()
    overhead = summary["swap_seconds_total"] + max(
        wall - summary["train_seconds_total"]
        - summary["swap_seconds_total"], 0.0)
    record = {
        "metric": "continual_rows_per_sec",
        "value": round(n / wall, 3),
        "unit": f"rows/sec (n={n} gens={gens} rounds={rounds} "
                f"platform={platform})",
        "vs_baseline": round(mono_wall / wall, 4),
        "continual": dict(summary,
                          wall_seconds=round(wall, 3),
                          overhead_seconds=round(overhead, 3),
                          swap_share=round(
                              summary["swap_seconds_total"] / wall, 6),
                          monolithic_wall_seconds=round(mono_wall, 3)),
    }
    out = os.environ.get("BENCH_OUT")
    line = json.dumps(record)
    if out:
        with open(out, "w") as fh:
            fh.write(line + "\n")
    else:
        print(line, flush=True)
    print(f"# continual: {summary['generations']} generation(s), "
          f"{summary['rollbacks']} rollback(s), swap share "
          f"{record['continual']['swap_share']:.2%}", file=sys.stderr)


def _measure_stream():
    """Out-of-core streaming bench (tpu_stream, io/streaming.py +
    learner.StreamTreeGrower): trains the SAME Higgs-shaped fixture
    twice — resident (the anchor) and forced-streaming with a
    multi-slab plan — and emits streamed rows/sec, slab upload vs
    kernel wall seconds, the measured `stream_overlap_ratio` (fraction
    of upload time issued while device compute was in flight), and
    `vs_resident` (resident wall / streamed wall; perf-gate check 9
    holds the slowdown to the recorded ceiling)."""
    n = int(os.environ.get("BENCH_ROWS", 10_500_000))
    f = 28
    iters = int(os.environ.get("BENCH_ITERS", 10))
    warmup = 2

    import jax
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import lightgbm_tpu as lgb
    from lightgbm_tpu.io.streaming import global_stream_stats
    from lightgbm_tpu.ops.bin_pack import slab_align

    platform = jax.default_backend()
    if platform == "cpu":
        iters = min(iters, int(os.environ.get("BENCH_CPU_ITERS", 3)))
        warmup = 1
    rng = np.random.RandomState(0)
    x = rng.randn(n, f).astype(np.float32)
    logit = (x[:, 0] + 0.6 * x[:, 1] ** 2 + 0.4 * x[:, 2] * x[:, 3]
             - 0.3 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0.2).astype(np.float32)

    base_params = {"objective": "binary", "num_leaves": 255,
                   "learning_rate": 0.1, "max_bin": 63,
                   "min_sum_hessian_in_leaf": 100, "min_data_in_leaf": 0,
                   "verbosity": -1}

    def timed_train(extra):
        params = dict(base_params, **extra)
        ds = lgb.Dataset(x, label=y, params=params)
        ds.construct()
        bst = lgb.Booster(params, ds)
        for _ in range(warmup):
            bst.update()
        jax.block_until_ready(bst._gbdt.scores)
        t0 = time.perf_counter()
        for _ in range(iters):
            bst.update()
        _ = np.asarray(bst._gbdt.scores[0, :8])  # host-transfer block
        return bst, time.perf_counter() - t0

    # resident anchor (same shape, same iteration count, same run) —
    # tpu_stream pinned OFF so a capacity-constrained host can't
    # silently stream the anchor and gate streaming against itself.
    # The anchor booster is dropped before the streamed half runs: its
    # device-resident bins/scores must not occupy the HBM the streamed
    # measurement is supposed to have free.
    anchor, resident_wall = timed_train({"tpu_stream": "off"})
    del anchor

    # forced streaming with a REAL multi-slab plan: ~4 slabs (or the
    # smallest aligned slab when the fixture is tiny)
    align = slab_align(int(base_params["max_bin"]))
    slab_rows = max(align, (n // 4) // align * align)
    global_stream_stats.reset()
    bst, stream_wall = timed_train({"tpu_stream": "on",
                                    "tpu_stream_slab_rows": slab_rows})
    stats = global_stream_stats.summary()
    plan = bst._gbdt._stream

    rows_per_sec = n * iters / stream_wall
    record = {
        "metric": "stream_rows_per_sec",
        "value": round(rows_per_sec, 3),
        "unit": f"boosted rows/sec (n={n}, 255 leaves, 63 bins, "
                f"{plan.n_slabs} slabs, platform={platform})",
        "vs_baseline": round(resident_wall / stream_wall, 4),
        "stream": dict(
            stats,
            slab_rows=int(plan.slab_rows),
            n_slabs=int(plan.n_slabs),
            stream_overlap_ratio=stats["overlap_ratio"],
            upload_seconds=stats["upload_seconds_total"],
            kernel_seconds=stats["kernel_seconds_total"],
            stream_wall_seconds=round(stream_wall, 3),
            resident_wall_seconds=round(resident_wall, 3),
            vs_resident=round(resident_wall / stream_wall, 4),
        ),
    }
    out = os.environ.get("BENCH_OUT")
    line = json.dumps(record)
    if out:
        with open(out, "w") as fh:
            fh.write(line + "\n")
    else:
        print(line, flush=True)
    print(f"# stream: {plan.n_slabs} slab(s) x {plan.slab_rows} rows, "
          f"overlap={stats['overlap_ratio']:.2%}, "
          f"upload={stats['upload_seconds_total']:.2f}s "
          f"kernel={stats['kernel_seconds_total']:.2f}s, "
          f"resident {resident_wall:.2f}s vs streamed "
          f"{stream_wall:.2f}s", file=sys.stderr)


# one small train, run twice in fresh interpreter processes sharing one
# fresh compile-cache dir: the SECOND run's compile_s_total is what a
# warm-started replica/trainer actually pays (obs/xla measures the real
# lower+compile wall time per program boundary)
_COLDSTART_CHILD = r'''
import json, os, sys, time
sys.path.insert(0, os.environ["COLDSTART_REPO"])
from lightgbm_tpu.obs.xla import global_xla
global_xla.enable()
from lightgbm_tpu.compile_cache import configure
configure("on", os.environ["COLDSTART_CACHE_DIR"])
import numpy as np
import lightgbm_tpu as lgb
n = int(os.environ.get("COLDSTART_ROWS", "20000")); f = 28
rng = np.random.RandomState(0)
x = rng.randn(n, f).astype(np.float32)
y = (x[:, 0] + 0.6 * x[:, 1] ** 2 > 0.2).astype(np.float32)
params = {"objective": "binary",
          "num_leaves": int(os.environ.get("COLDSTART_LEAVES", "63")),
          "max_bin": 63,
          "min_sum_hessian_in_leaf": 100, "min_data_in_leaf": 0,
          "verbosity": -1}
t0 = time.perf_counter()
ds = lgb.Dataset(x, label=y, params=params)
ds.construct()
bst = lgb.train(params, ds,
                num_boost_round=int(os.environ.get("COLDSTART_ITERS", "2")))
t1 = time.perf_counter()
bst.predict(x[:8])
first_pred_s = time.perf_counter() - t1
s = global_xla.summary()
print("COLDSTART " + json.dumps({
    "compile_s_total": s["compile_s_total"],
    "trace_s_total": s["trace_s_total"],
    "cache_load_s_total": s["cache_load_s_total"],
    "n_cache_hits": s["n_cache_hits"], "n_programs": s["n_programs"],
    "wall_s": round(time.perf_counter() - t0, 3),
    "first_pred_s": round(first_pred_s, 4)}), flush=True)
'''


def _coldstart_child_run(cache_dir: str, rows: int) -> dict:
    """One interpreter-fresh train against `cache_dir`; returns the
    child's COLDSTART json dict (raises on a dead/invalid child)."""
    env = dict(os.environ)
    # the parent may itself run under a warm cache (cpu_child_env sets
    # JAX_COMPILATION_CACHE_DIR); the cold/warm pair must only ever see
    # the dedicated fresh dir or the "cold" half measures nothing
    for k in ("JAX_COMPILATION_CACHE_DIR", "LGBM_TPU_COMPILE_CACHE_DIR"):
        env.pop(k, None)
    env["COLDSTART_REPO"] = os.path.dirname(os.path.abspath(__file__))
    env["COLDSTART_CACHE_DIR"] = cache_dir
    env["COLDSTART_ROWS"] = str(rows)
    out = subprocess.run([sys.executable, "-c", _COLDSTART_CHILD],
                         env=env, capture_output=True, text=True,
                         timeout=float(os.environ.get(
                             "BENCH_COLDSTART_TIMEOUT", 600)))
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("COLDSTART "):
            return json.loads(line[len("COLDSTART "):])
    raise RuntimeError(f"coldstart child died rc={out.returncode}: "
                       f"{out.stderr[-800:]}")


def _measure_coldstart():
    """Cold-start bench (ISSUE 14): (1) the SAME small train run in two
    fresh interpreter processes sharing one fresh persistent-cache dir —
    the cold run pays real XLA compiles, the warm rerun's
    ``compile_s_total`` (obs/xla, the real per-program lower+compile
    wall time) should be ~zero; (2) serialized-artifact serving — a
    ModelServer stood up against a saved artifact store must serve its
    first low-latency request with ZERO serve/lowlat compiles, counted
    through the obs recompile counters. Emits
    ``coldstart_compile_reduction`` (cold/warm compile seconds) plus the
    ``coldstart`` summary dict perf-gate check 10 caps."""
    import asyncio
    import shutil

    n = int(os.environ.get("BENCH_ROWS", 20_000))
    import jax
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from lightgbm_tpu.model_io import LoadedModel
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.serve import (ModelRegistry, ModelServer,
                                    SERVE_LOWLAT_TAG, serialize_available)

    platform = jax.default_backend()
    cache_dir = tempfile.mkdtemp(prefix="coldstart_cache_")
    art_dir = tempfile.mkdtemp(prefix="coldstart_art_")
    try:
        cold = _coldstart_child_run(cache_dir, n)
        warm = _coldstart_child_run(cache_dir, n)
        # real compile seconds only: a cache-warm process LOADS its
        # programs (cache_load_s_total, reported alongside) — the floor
        # keeps the ratio finite when warm compiles are exactly zero
        reduction = cold["compile_s_total"] / max(warm["compile_s_total"],
                                                 1e-2)

        # -- phase 2: artifact-store serving restore (in-process; the
        # counters, not process identity, prove no compile ran: a fresh
        # LowLatencyPredictor shares nothing with the exporter but the
        # on-disk artifacts)
        f = 28
        rng = np.random.RandomState(0)
        trees = _random_trees(
            rng, int(os.environ.get("BENCH_COLDSTART_TREES", 50)), 63, f)
        model = LoadedModel()
        model.trees = trees
        model.num_tree_per_iteration = 1
        model.objective_str = "binary sigmoid:1"
        model.max_feature_idx = f - 1

        reg_a = ModelRegistry(artifact_dir=art_dir)
        entry_a = reg_a.load("bench", model=model)
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        t0 = time.perf_counter()
        n_progs = entry_a.lowlat.warm(f)
        export_s = time.perf_counter() - t0
        export_compiles = global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0
        req = rng.randn(4, f)
        ref = entry_a.lowlat(req)

        # replica restart: a fresh registry/server against the store
        reg_b = ModelRegistry(artifact_dir=art_dir)
        entry_b = reg_b.load("bench", model=model)
        server = ModelServer(reg_b)
        c1 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        loads0 = global_metrics.counters.get("serve/aot_loads", 0)
        t0 = time.perf_counter()
        entry_b.lowlat.warm(f)
        restore_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = asyncio.run(server.predict("bench", req, raw_score=True))
        first_req_s = time.perf_counter() - t0
        restore_compiles = global_metrics.recompiles(SERVE_LOWLAT_TAG) - c1
        restore_loads = global_metrics.counters.get("serve/aot_loads",
                                                    0) - loads0
        # ref is raw [B, K]; server.predict squeezes K=1 to [B]
        bit_equal = bool(np.array_equal(
            np.squeeze(np.asarray(ref, np.float64)),
            np.squeeze(np.asarray(out, np.float64))))
        asyncio.run(server.close())
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(art_dir, ignore_errors=True)

    unit = ("x warm/cold compile reduction (train n=%d, %d programs"
            % (n, cold["n_programs"]))
    if platform != "tpu":
        unit += ", platform=%s" % platform
    if not bit_equal:
        unit += ", PARITY-MISMATCH"
    unit += ")"
    record = {
        "metric": "coldstart_compile_reduction",
        "value": round(reduction, 2),
        "unit": unit,
        # anchor: how much of the cold compile bill warm start removes
        "vs_baseline": round(reduction, 2),
        "coldstart": {
            "cold_compile_s": cold["compile_s_total"],
            "warm_compile_s": warm["compile_s_total"],
            "compile_reduction": round(reduction, 2),
            "cold_trace_s": cold.get("trace_s_total", 0.0),
            "warm_trace_s": warm.get("trace_s_total", 0.0),
            "cold_cache_load_s": cold.get("cache_load_s_total", 0.0),
            "warm_cache_load_s": warm.get("cache_load_s_total", 0.0),
            "warm_cache_hits": warm.get("n_cache_hits", 0),
            "cold_wall_s": cold["wall_s"],
            "warm_wall_s": warm["wall_s"],
            "cold_first_pred_s": cold["first_pred_s"],
            "warm_first_pred_s": warm["first_pred_s"],
            "artifact_serialize_available": serialize_available(),
            "artifact_programs": int(n_progs),
            "artifact_export_compiles": int(export_compiles),
            "artifact_export_s": round(export_s, 3),
            "artifact_restore_s": round(restore_s, 4),
            "restore_aot_loads": int(restore_loads),
            "restore_lowlat_compiles": int(restore_compiles),
            "first_request_s": round(first_req_s, 4),
            "restore_bit_identical": bit_equal,
        },
    }
    out_path = os.environ.get("BENCH_OUT")
    line = json.dumps(record)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(line + "\n")
    else:
        print(line, flush=True)
    print(f"# coldstart: compile {cold['compile_s_total']:.2f}s cold -> "
          f"{warm['compile_s_total']:.2f}s warm ({reduction:.1f}x); "
          f"artifact restore {restore_s*1e3:.0f}ms / "
          f"{restore_compiles} compiles / {restore_loads} loads, "
          f"first request {first_req_s*1e3:.0f}ms bit_equal={bit_equal}",
          file=sys.stderr)


_MODE_MEASURE = {"train": _measure, "predict": _measure_predict,
                 "serve": _measure_serve, "fleet": _measure_fleet,
                 "continual": _measure_continual,
                 "stream": _measure_stream, "coldstart": _measure_coldstart,
                 "shap": _measure_shap, "rank": _measure_rank}


def _emit_partial_obs(mode: str, exc) -> None:
    """A failed measurement attempt still surfaces its partial obs
    summary (phase self-times + compile/recompile attribution so far)
    as one stderr comment line the parent's spam filter forwards — the
    old path dropped everything a dead child had already measured."""
    try:
        partial = {"metric": _MODE_METRIC.get(mode, mode), "partial": True,
                   "error": repr(exc)[:300]}
        if _telemetry_enabled():
            from lightgbm_tpu.obs import global_tracer
            phases = {name: round(agg["seconds"], 4)
                      for name, agg in global_tracer.summary().items()}
            if phases:
                partial["phases"] = phases
            from lightgbm_tpu.obs.xla import global_xla
            xs = global_xla.summary()
            if xs["n_programs"]:
                partial["compile_s_total"] = xs["compile_s_total"]
                partial["n_recompiles_by_phase"] = \
                    xs["n_recompiles_by_phase"]
        print("# obs-partial: " + json.dumps(partial), file=sys.stderr,
              flush=True)
    except Exception:
        pass  # the partial dump must never mask the real failure


def _child_main() -> None:
    mode = parse_bench_mode()
    # the parent's timeout path sends SIGTERM; turn it into SystemExit
    # so the partial-obs dump below (and atexit handlers) still run
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    try:
        _MODE_MEASURE[mode]()
    except BaseException as exc:
        _emit_partial_obs(mode, exc)
        raise


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD"):
        _child_main()
    else:
        main()
