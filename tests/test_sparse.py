"""Sparse (CSR/CSC) ingestion without densification.

Covers the reference's sparse path (ref: c_api.cpp:1311
LGBM_DatasetCreateFromCSR, :1330 ...FromCSC; src/io/sparse_bin.hpp:74):
binning from CSC columns + implicit zero counts, direct emission of the
bundled [G, N] EFB storage, aligned sparse valid sets, and batched
sparse prediction.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import BinMapper

sp = pytest.importorskip("scipy.sparse")


def _sparse_binary(n=2000, f=40, density=0.08, seed=3):
    r = np.random.RandomState(seed)
    x = sp.random(n, f, density=density, random_state=r,
                  data_rvs=lambda k: r.randn(k) + 1.5, format="csr")
    xd = np.asarray(x.todense())
    logit = xd[:, 0] * 2 + xd[:, 1] - xd[:, 2] + 0.5 * xd[:, :6].sum(1)
    y = (logit + 0.3 * r.randn(n) > 0.4).astype(np.float32)
    return x, xd, y


def _logloss(y, p):
    p = np.clip(p, 1e-12, 1 - 1e-12)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def test_fit_sparse_matches_dense_fit():
    r = np.random.RandomState(0)
    dense = np.where(r.rand(5000) < 0.9, 0.0, r.randn(5000) * 3)
    nz = dense[dense != 0.0]
    m_dense = BinMapper().fit(dense, max_bin=63)
    m_sparse = BinMapper().fit_sparse(nz, len(dense), max_bin=63)
    assert m_dense.num_bins == m_sparse.num_bins
    np.testing.assert_allclose(m_dense.bin_upper_bound,
                               m_sparse.bin_upper_bound)
    assert m_dense.default_bin == m_sparse.default_bin
    assert m_dense.most_freq_bin == m_sparse.most_freq_bin
    assert m_dense.is_trivial == m_sparse.is_trivial


def test_fit_sparse_nan_and_trivial():
    # NaNs in the explicit values get the dedicated NaN bin
    m = BinMapper().fit_sparse(np.array([1.0, np.nan, 2.0, np.nan]), 100,
                               max_bin=15)
    md = BinMapper().fit(
        np.concatenate([[1.0, np.nan, 2.0, np.nan], np.zeros(96)]),
        max_bin=15)
    assert m.num_bins == md.num_bins
    assert m.missing_type == md.missing_type
    # all-implicit-zero column is trivial
    t = BinMapper().fit_sparse(np.array([]), 50)
    assert t.is_trivial


def test_sparse_storage_matches_dense_binning():
    """The bundled sparse storage must decode to the same logical bins
    the dense path produces for the same data."""
    x, xd, y = _sparse_binary(n=800, f=12, density=0.2)
    params = {"max_bin": 63, "verbosity": -1}
    ds_s = lgb.Dataset(x, label=y, params=params)
    ds_s.construct()
    # dense comparison must carry LOGICAL bins: disable EFB there
    ds_d = lgb.Dataset(xd, label=y,
                       params={**params, "enable_bundle": False})
    ds_d.construct()
    bs, bd = ds_s._binned, ds_d._binned
    assert bs.num_data == bd.num_data
    assert [m.num_bins for m in bs.mappers] == \
        [m.num_bins for m in bd.mappers]
    for ms, md_ in zip(bs.mappers, bd.mappers):
        np.testing.assert_allclose(ms.bin_upper_bound, md_.bin_upper_bound)
    # decode sparse storage to logical bins and compare
    from lightgbm_tpu.bundling import decode_stored_host
    if bs.bundle_info is not None:
        info = bs.bundle_info
        nbins = np.array([m.num_bins for m in bs.mappers])
        for j in range(len(bs.mappers)):
            g = info.group_of[j]
            logical = decode_stored_host(
                bs.bins_fm[g].astype(np.int64),
                np.int64(info.offset_of[j]), np.int64(nbins[j] - 1))
            if len(info.bundles[g]) == 1 and bs.mappers[j].default_bin != 0:
                logical = bs.bins_fm[g].astype(np.int64)
            np.testing.assert_array_equal(logical, bd.bins_fm[j],
                                          err_msg=f"feature {j}")
    else:
        np.testing.assert_array_equal(bs.bins_fm, bd.bins_fm)


@pytest.mark.slow
def test_sparse_train_matches_dense():
    """CSR training must reach the same quality as dense training on
    the same data (VERDICT r3 'done' criterion)."""
    x, xd, y = _sparse_binary()
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1, "deterministic": True}
    bs = lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=20)
    bdense = lgb.train(params, lgb.Dataset(xd, label=y),
                       num_boost_round=20)
    ps = bs.predict(xd)
    pd_ = bdense.predict(xd)
    ls, ld = _logloss(y, ps), _logloss(y, pd_)
    assert abs(ls - ld) < 5e-3, (ls, ld)


def test_sparse_predict_matches_dense_predict():
    x, xd, y = _sparse_binary(n=600, f=20)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10}
    bst = lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=5)
    np.testing.assert_allclose(bst.predict(x), bst.predict(xd),
                               rtol=1e-6, atol=1e-9)
    # csc input too
    np.testing.assert_allclose(bst.predict(x.tocsc()), bst.predict(xd),
                               rtol=1e-6, atol=1e-9)


def test_sparse_valid_set_aligned():
    x, xd, y = _sparse_binary(n=1500, f=30)
    xtr, xva = x[:1000], x[1000:]
    ytr, yva = y[:1000], y[1000:]
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, "metric": "binary_logloss"}
    dtr = lgb.Dataset(xtr, label=ytr)
    dva = lgb.Dataset(xva, label=yva, reference=dtr)
    evals = {}
    bst = lgb.train(params, dtr, num_boost_round=10,
                    valid_sets=[dva], valid_names=["va"],
                    callbacks=[lgb.record_evaluation(evals)])
    replay = evals["va"]["binary_logloss"][-1]
    direct = _logloss(yva, bst.predict(xva))
    assert abs(replay - direct) < 1e-5, (replay, direct)


def test_wide_onehot_memory_bounded():
    """1M-cell-scale one-hot: storage must be O(nnz + G*N), never the
    dense N*F matrix (which would be 200 MB here; the bundled storage
    should be ~2 orders smaller)."""
    n, f = 20000, 1000
    r = np.random.RandomState(1)
    cols = r.randint(0, f, n)
    x = sp.csr_matrix(
        (np.ones(n, np.float32), (np.arange(n), cols)), shape=(n, f))
    y = (cols % 7 == 0).astype(np.float32)
    ds = lgb.Dataset(x, label=y, params={"max_bin": 63, "verbosity": -1})
    ds.construct()
    b = ds._binned
    # one-hot columns are mutually exclusive: they bundle into a few
    # storage columns
    assert b.bins_fm.shape[0] <= 32, b.bins_fm.shape
    assert b.bins_fm.nbytes < 4 * n * 32
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 20},
                    ds, num_boost_round=5)
    p = bst.predict(x[:2000])
    assert p.shape == (2000,)
    assert np.isfinite(p).all()


def test_sparse_csc_input_and_weights():
    x, xd, y = _sparse_binary(n=700, f=15)
    w = np.random.RandomState(5).rand(700).astype(np.float32) + 0.5
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 10}
    b1 = lgb.train(params, lgb.Dataset(x.tocsc(), label=y, weight=w),
                   num_boost_round=5)
    b2 = lgb.train(params, lgb.Dataset(xd, label=y, weight=w),
                   num_boost_round=5)
    np.testing.assert_allclose(b1.predict(xd), b2.predict(xd),
                               rtol=1e-5, atol=1e-7)


def test_sparse_categorical_matches_dense():
    """Implicit zeros of a categorical column must land in category 0's
    bin (transform(0)), not the 'other' bin 0."""
    r = np.random.RandomState(7)
    n = 1200
    cat = np.where(r.rand(n) < 0.7, 0, r.randint(1, 5, n)).astype(np.float64)
    num = np.where(r.rand(n) < 0.5, 0.0, r.randn(n))
    xd = np.stack([cat, num], axis=1)
    x = sp.csr_matrix(xd)
    y = ((cat == 2) | (num > 0.5)).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 10, "categorical_feature": [0]}
    ds_s = lgb.Dataset(x, label=y, params=params,
                       categorical_feature=[0])
    ds_s.construct()
    ds_d = lgb.Dataset(xd, label=y,
                       params={**params, "enable_bundle": False},
                       categorical_feature=[0])
    ds_d.construct()
    bs, bd = ds_s._binned, ds_d._binned
    from lightgbm_tpu.bundling import decode_stored_host
    info = bs.bundle_info
    for j in range(len(bs.mappers)):
        if info is None:
            logical = bs.bins_fm[j].astype(np.int64)
        else:
            g = info.group_of[j]
            if len(info.bundles[g]) == 1:
                logical = bs.bins_fm[g].astype(np.int64)
            else:
                logical = decode_stored_host(
                    bs.bins_fm[g].astype(np.int64),
                    np.int64(info.offset_of[j]),
                    np.int64(bs.mappers[j].num_bins - 1))
        np.testing.assert_array_equal(logical, bd.bins_fm[j],
                                      err_msg=f"feature {j}")
    b1 = lgb.train(params, ds_s, num_boost_round=5)
    b2 = lgb.train(params, lgb.Dataset(xd, label=y, params=params,
                                       categorical_feature=[0]),
                   num_boost_round=5)
    np.testing.assert_allclose(b1.predict(xd), b2.predict(xd),
                               rtol=1e-5, atol=1e-7)


@pytest.mark.slow
def test_coo_input_and_cv():
    x, xd, y = _sparse_binary(n=600, f=10)
    coo = x.tocoo()
    res = lgb.cv({"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "min_data_in_leaf": 10},
                 lgb.Dataset(coo, label=y), num_boost_round=3, nfold=3)
    key = [k for k in res if "binary_logloss" in k and "mean" in k][0]
    assert len(res[key]) == 3


def test_sparse_predict_empty():
    x, xd, y = _sparse_binary(n=300, f=10)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 10},
                    lgb.Dataset(x, label=y), num_boost_round=3)
    p = bst.predict(x[:0])
    assert p.shape == (0,)


def test_fit_sparse_zero_as_missing_stats():
    dense = np.concatenate([np.zeros(950), np.full(50, 2.0)])
    md = BinMapper().fit(dense, max_bin=15, zero_as_missing=True)
    ms = BinMapper().fit_sparse(np.full(50, 2.0), 1000, max_bin=15,
                                zero_as_missing=True)
    assert md.is_trivial == ms.is_trivial
    assert md.most_freq_bin == ms.most_freq_bin
    assert md.num_bins == ms.num_bins


def test_sparse_parallel_learner_not_bundled():
    """Parallel growers index logical [F, N] storage; from_sparse must
    honor the same learner guard as the dense bundler."""
    x, xd, y = _sparse_binary(n=400, f=20, density=0.05)
    ds = lgb.Dataset(x, label=y,
                     params={"tree_learner": "data", "verbosity": -1})
    ds.construct()
    assert ds._binned.bundle_info is None
    assert ds._binned.bins_fm.shape[0] == len(ds._binned.mappers)


def test_sparse_host_path_l1_and_dart():
    """Renewing objectives (L1) and DART take the HOST loop, which
    replays trees on raw valid features every iteration — must work
    with sparse train + valid sets."""
    x, xd, y = _sparse_binary(n=900, f=15)
    xtr, xva = x[:600], x[600:]
    ytr, yva = y[:600], y[600:]
    for extra in ({"objective": "regression_l1"},
                  {"objective": "binary", "boosting": "dart",
                   "drop_rate": 0.5}):
        params = {"num_leaves": 7, "verbosity": -1,
                  "min_data_in_leaf": 10, **extra}
        dtr = lgb.Dataset(xtr, label=ytr)
        dva = lgb.Dataset(xva, label=yva, reference=dtr)
        evals = {}
        bst = lgb.train(params, dtr, num_boost_round=5,
                        valid_sets=[dva],
                        callbacks=[lgb.record_evaluation(evals)])
        assert bst.num_trees() >= 1
        p = bst.predict(xva)
        assert np.isfinite(p).all()


def test_sparse_continued_training():
    x, xd, y = _sparse_binary(n=500, f=12)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 10}
    b1 = lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=3)
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".txt") as tf:
        tf.write(b1.model_to_string())
        tf.flush()
        b2 = lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=3,
                       init_model=tf.name)
    assert b2.num_trees() == 6
    assert np.isfinite(b2.predict(x)).all()


def test_sparse_valid_against_dense_bundled_train():
    """A sparse eval set aligned to a DENSE-trained bundled reference
    must decode to identical metrics (exercises the zb != 0 shared-
    member encoding in build_bundled_from_csc)."""
    r = np.random.RandomState(11)
    n = 1000
    # two mutually-exclusive categoricals (bundleable, default_bin 0,
    # category 0 present) + a numeric column
    c1 = np.where(r.rand(n) < 0.5, 0.0, r.randint(0, 4, n).astype(float))
    c2 = np.where(c1 > 0, 0.0,
                  np.where(r.rand(n) < 0.5, 0.0,
                           r.randint(0, 3, n).astype(float)))
    num = r.randn(n)
    xd = np.stack([c1, c2, num], axis=1)
    y = ((c1 == 2) | (num > 0.8)).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 10, "categorical_feature": [0, 1],
              "metric": "binary_logloss"}
    dtr = lgb.Dataset(xd[:700], label=y[:700], params=params,
                      categorical_feature=[0, 1])
    dtr.construct()
    # sparse valid aligned to the dense train set
    dva_s = lgb.Dataset(sp.csr_matrix(xd[700:]), label=y[700:],
                        reference=dtr)
    dva_d = lgb.Dataset(xd[700:], label=y[700:], reference=dtr)
    dva_s.construct()
    dva_d.construct()
    np.testing.assert_array_equal(dva_s._binned.bins_fm,
                                  dva_d._binned.bins_fm)


def test_sparse_linear_tree_rejected():
    x, _, y = _sparse_binary(n=300, f=10)
    with pytest.raises(Exception, match="linear"):
        lgb.train({"objective": "binary", "linear_tree": True,
                   "verbosity": -1},
                  lgb.Dataset(x, label=y), num_boost_round=2)


class TestSparseRowwiseHistogram:
    """COO sparse histogram path (ref: bin.h:482 MultiValBin +
    multi_val_sparse_bin.hpp:21 — the sparse row-wise variant): for
    ultra-sparse non-bundleable data, histograms run O(nnz) segment-sums
    with implicit-zero mass recovered from leaf totals, instead of the
    dense [G, N] passes."""

    def _make(self, n=2000, f=80, density=0.03, seed=7):
        from scipy import sparse
        rng = np.random.RandomState(seed)
        X = rng.randn(n, f)
        X[rng.rand(n, f) < 1.0 - density] = 0.0  # multi-hot, non-exclusive
        y = (X[:, 0] + X[:, 1] - X[:, 2]
             + 0.1 * rng.randn(n) > 0).astype(np.float32)
        return sparse.csr_matrix(X), y

    @pytest.mark.parametrize("wave", [0, -1])
    def test_matches_dense_path(self, wave):
        import lightgbm_tpu as lgb
        csr, y = self._make()
        preds = {}
        for mode in ("off", "force"):
            params = {"objective": "binary", "num_leaves": 15,
                      "min_data_in_leaf": 5, "verbosity": -1,
                      "tpu_sparse_hist": mode, "tpu_wave_max": wave,
                      "enable_bundle": False}
            dtr = lgb.Dataset(csr, label=y, params=dict(params))
            bst = lgb.train(dict(params), dtr, num_boost_round=6)
            if mode == "force":
                assert dtr._binned.sparse_coo is not None
            preds[mode] = bst.predict(csr)
        np.testing.assert_allclose(preds["force"], preds["off"],
                                   rtol=1e-3, atol=1e-3)

    def test_valid_set_mirrors_coo_layout(self):
        import lightgbm_tpu as lgb
        csr, y = self._make()
        csrv, yv = self._make(n=600, seed=11)
        params = {"objective": "binary", "num_leaves": 15,
                  "verbosity": -1, "tpu_sparse_hist": "force",
                  "enable_bundle": False}
        dtr = lgb.Dataset(csr, label=y, params=dict(params))
        dv = lgb.Dataset(csrv, label=yv, reference=dtr,
                         params=dict(params))
        bst = lgb.train(dict(params), dtr, num_boost_round=5,
                        valid_sets=[dv])
        assert dv._binned.sparse_coo is not None
        name, metric, value, _ = bst.eval_valid()[0]
        assert np.isfinite(value)

    def test_l1_renewal_on_sparse(self):
        """regression_l1 renews leaf outputs through the host
        leaf-binned path, which must materialize COO columns."""
        import lightgbm_tpu as lgb
        csr, y = self._make()
        yr = np.asarray(csr[:, 0].todense()).ravel() + \
            0.1 * np.random.RandomState(0).randn(csr.shape[0])
        params = {"objective": "regression_l1", "num_leaves": 7,
                  "verbosity": -1, "tpu_sparse_hist": "force",
                  "enable_bundle": False}
        bst = lgb.train(dict(params),
                        lgb.Dataset(csr, label=yr, params=dict(params)),
                        num_boost_round=4)
        assert np.isfinite(bst.predict(csr)).all()

    def test_auto_mode_picks_coo_only_when_lean(self):
        import lightgbm_tpu as lgb
        # ultra-sparse, bundling disabled -> COO wins the cost model
        csr, y = self._make(density=0.005)
        params = {"objective": "binary", "verbosity": -1,
                  "enable_bundle": False}
        ds = lgb.Dataset(csr, label=y, params=dict(params)).construct()
        assert ds._binned.sparse_coo is not None
        # dense-ish sparse input -> stays on the dense layout
        csr2, y2 = self._make(density=0.4, f=20)
        ds2 = lgb.Dataset(csr2, label=y2,
                          params=dict(params)).construct()
        assert ds2._binned.sparse_coo is None

    def test_binary_roundtrip_preserves_coo(self, tmp_path):
        """save_binary/load must carry the COO payload, not the [1, N]
        placeholder (binary cache parity for sparse datasets)."""
        import lightgbm_tpu as lgb
        from lightgbm_tpu.io.binary_format import load_dataset_binary
        csr, y = self._make(n=800, f=40)
        params = {"objective": "binary", "verbosity": -1,
                  "tpu_sparse_hist": "force", "enable_bundle": False}
        ds = lgb.Dataset(csr, label=y, params=dict(params)).construct()
        assert ds._binned.sparse_coo is not None
        path = str(tmp_path / "sparse.bin")
        ds.save_binary(path)
        loaded = load_dataset_binary(path)
        lb = loaded._binned
        assert lb.sparse_coo is not None
        for a, b in zip(lb.sparse_coo, ds._binned.sparse_coo):
            np.testing.assert_array_equal(a, b)
        bst = lgb.train(dict(params), loaded, num_boost_round=3)
        assert bst.num_trees() == 3
