"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch re-architecture of LightGBM's capabilities
(ref: /root/reference, nagyist/LightGBM v4.6) for TPU: host-side quantile
binning, a leaf-wise tree learner compiled to XLA (histograms as MXU
one-hot contractions, vectorized split search, mask-based partition),
objectives/metrics, data-parallel training via jax.sharding over an ICI
mesh, and a python API mirroring the reference python-package.
"""

from .basic import Booster, Dataset, LightGBMError, Sequence  # noqa: F401
from .callback import (EarlyStopException, early_stopping,  # noqa: F401
                       log_evaluation, log_telemetry, record_evaluation,
                       record_telemetry, reset_parameter)
from . import obs  # noqa: F401
from .obs.memory import preflight  # noqa: F401  (HBM capacity planner)
from . import serve  # noqa: F401
from .engine import CVBooster, continual_train, cv, train  # noqa: F401
from .resilience.continual import ContinualTrainer  # noqa: F401
from .log import register_logger  # noqa: F401
from . import plotting  # noqa: F401
from .plotting import (create_tree_digraph, plot_importance,  # noqa: F401
                       plot_metric, plot_split_value_histogram, plot_tree)
from .io.streaming import DatasetBuilder  # noqa: F401
from .dask import (DaskLGBMClassifier, DaskLGBMRanker,  # noqa: F401
                   DaskLGBMRegressor)
from .sklearn import (LGBMClassifier, LGBMModel,  # noqa: F401
                      LGBMRanker, LGBMRegressor)

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "LightGBMError",
    "train", "cv", "CVBooster",
    "early_stopping", "log_evaluation", "record_evaluation",
    "log_telemetry", "record_telemetry", "obs", "serve", "preflight",
    "reset_parameter", "EarlyStopException", "register_logger",
    "plot_importance", "plot_metric", "plot_split_value_histogram",
    "plot_tree", "create_tree_digraph", "plotting", "DatasetBuilder",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "Sequence",
    "DaskLGBMRegressor", "DaskLGBMClassifier", "DaskLGBMRanker",
]
