"""`python -m lightgbm_tpu key=value ...` — the CLI entry point
(ref: src/main.cpp:16)."""

import sys

from .cli import main

sys.exit(main())
