"""Command-line application: train / predict / refit / convert_model /
save_binary over `key=value` args and config files.

TPU-native counterpart of the reference CLI (ref: src/main.cpp:16,
src/application/application.cpp:35 Application, application.h task enum).
Accepts the same `key=value` argument style, `config=<file>` config files
(`key = value` lines, `#` comments), and runs against the same example
configs (`examples/*/train.conf`). Command-line pairs override config-file
pairs (ref: application.cpp:60-88 LoadParameters precedence).
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .config import Config
from .engine import train as train_fn
from . import callback as callback_mod


def parse_config_file(path: str) -> Dict[str, str]:
    """`key = value` per line; `#` starts a comment
    (ref: Config::KV2Map + application.cpp:53 LoadParameters)."""
    out: Dict[str, str] = {}
    with open(path) as fh:
        for raw in fh:
            line = raw.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, value = line.split("=", 1)
            key, value = key.strip(), value.strip()
            if key:
                out[Config.canonical_key(key)] = value
    return out


def parse_cli_args(argv: List[str]) -> Dict[str, str]:
    """key=value tokens; config= pulls in a file, CLI pairs win."""
    cli_pairs: Dict[str, str] = {}
    config_file: Optional[str] = None
    for tok in argv:
        if "=" not in tok:
            raise LightGBMError(f"unknown argument (expected key=value): {tok}")
        key, value = tok.split("=", 1)
        key = Config.canonical_key(key.strip())
        value = value.strip()
        if key == "config":
            config_file = value
        else:
            cli_pairs[key] = value
    params: Dict[str, str] = {}
    if config_file:
        params.update(parse_config_file(config_file))
    params.update(cli_pairs)
    return params


def conform_prediction_data(data: np.ndarray, need: int,
                            disable_shape_check: bool) -> np.ndarray:
    """Feature-count gate shared by task=predict and task=serve (ref:
    predict_disable_shape_check — the reference aborts on mismatch
    unless the check is disabled, then pads with NaN / truncates)."""
    if data.shape[1] == need:
        return data
    if not disable_shape_check:
        raise LightGBMError(
            f"prediction data has {data.shape[1]} features but the "
            f"model expects {need}; set "
            "predict_disable_shape_check=true to pad/truncate")
    if data.shape[1] < need:
        pad = np.full((data.shape[0], need - data.shape[1]), np.nan)
        return np.hstack([data, pad])
    return data[:, :need]


def write_prediction_file(path: str, preds_iter) -> int:
    """Write prediction arrays (an iterable — one block per request for
    task=serve, a single block for task=predict) as `%g` lines; returns
    the row count."""
    rows = 0
    with open(path, "w") as fh:
        for preds in preds_iter:
            arr = np.asarray(preds)
            if arr.ndim == 1:
                for v in arr:
                    fh.write(f"{v:g}\n")
            else:
                for row in arr:
                    fh.write("\t".join(f"{v:g}" for v in row) + "\n")
            rows += arr.shape[0] if arr.ndim else 1
    return rows


class Application:
    """One CLI run (ref: src/application/application.cpp:35)."""

    def __init__(self, argv: List[str]):
        self.params = parse_cli_args(argv)
        self.config = Config.from_params(self.params)

    def run(self) -> None:
        task = self.config.task
        if task == "train":
            self._train()
        elif task in ("predict", "prediction", "test"):
            self._predict()
        elif task == "convert_model":
            self._convert_model()
        elif task in ("refit", "refit_tree"):
            self._refit()
        elif task == "save_binary":
            self._save_binary()
        elif task == "serve":
            self._serve()
        else:
            raise LightGBMError(f"unknown task: {task}")

    # ------------------------------------------------------------------
    def _load_train_data(self) -> Dataset:
        if not self.config.data:
            raise LightGBMError("no training data (`data=` missing)")
        return Dataset(self.config.data, params=dict(self.params))

    def _train(self) -> None:
        cfg = self.config
        t0 = time.time()
        train_set = self._load_train_data()
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        valid = cfg.valid
        if valid:
            files = valid.split(",") if isinstance(valid, str) else list(valid)
            for vf in files:
                vf = vf.strip()
                if not vf:
                    continue
                valid_sets.append(Dataset(vf, reference=train_set,
                                          params=dict(self.params)))
                valid_names.append(vf.rsplit("/", 1)[-1])

        callbacks = []
        if cfg.verbosity >= 0 and cfg.metric_freq > 0:
            callbacks.append(callback_mod.log_evaluation(cfg.metric_freq))
        if cfg.snapshot_freq > 0:
            out_model = cfg.output_model
            freq = cfg.snapshot_freq

            def _snapshot(env):
                it = env.iteration + 1
                if it % freq == 0:
                    env.model.save_model(f"{out_model}.snapshot_iter_{it}")
            callbacks.append(_snapshot)

        booster = train_fn(dict(self.params), train_set,
                           num_boost_round=cfg.num_iterations,
                           valid_sets=valid_sets, valid_names=valid_names,
                           init_model=cfg.input_model or None,
                           callbacks=callbacks)
        booster.save_model(cfg.output_model)
        if cfg.verbosity >= 0:
            print(f"[LightGBM-TPU] finished training in "
                  f"{time.time() - t0:.3f} s; model saved to "
                  f"{cfg.output_model}")

    # ------------------------------------------------------------------
    def _predict(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("task=predict requires input_model=")
        if not cfg.data:
            raise LightGBMError("task=predict requires data=")
        booster = Booster(model_file=cfg.input_model)
        from .io.text_loader import load_svmlight_or_csv
        data, _label, _w, _g = load_svmlight_or_csv(cfg.data,
                                                    dict(self.params))
        data = conform_prediction_data(data, booster.num_feature(),
                                       cfg.predict_disable_shape_check)
        preds = booster.predict(
            data,
            start_iteration=cfg.start_iteration_predict,
            num_iteration=cfg.num_iteration_predict,
            raw_score=cfg.predict_raw_score,
            pred_leaf=cfg.predict_leaf_index,
            pred_contrib=cfg.predict_contrib)
        rows = write_prediction_file(cfg.output_result, [preds])
        if cfg.verbosity >= 0:
            print(f"[LightGBM-TPU] predictions for {rows} rows "
                  f"written to {cfg.output_result}")

    # ------------------------------------------------------------------
    def _convert_model(self) -> None:
        """Model -> standalone C++ if-else source
        (ref: task=convert_model, GBDT::SaveModelToIfElse tree.h:253)."""
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("task=convert_model requires input_model=")
        from .codegen import model_to_if_else
        with open(cfg.input_model) as fh:
            from .model_io import load_model_from_string
            model = load_model_from_string(fh.read())
        code = model_to_if_else(model)
        with open(cfg.convert_model, "w") as fh:
            fh.write(code)
        if cfg.verbosity >= 0:
            print(f"[LightGBM-TPU] model converted to {cfg.convert_model}")

    # ------------------------------------------------------------------
    def _refit(self) -> None:
        """Refresh leaf values of input_model on new data
        (ref: task=refit, GBDT::RefitTree gbdt.cpp:267)."""
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("task=refit requires input_model=")
        from .io.text_loader import load_svmlight_or_csv
        data, label, weight, _g = load_svmlight_or_csv(cfg.data,
                                                       dict(self.params))
        booster = Booster(model_file=cfg.input_model)
        new_booster = booster.refit(data, label, weight=weight,
                                    decay_rate=cfg.refit_decay_rate)
        new_booster.save_model(cfg.output_model)
        if cfg.verbosity >= 0:
            print(f"[LightGBM-TPU] refitted model saved to "
                  f"{cfg.output_model}")

    # ------------------------------------------------------------------
    def _serve(self) -> None:
        """Replay a data file through the async model server (serve/)
        as concurrent mixed-size requests — the thin CLI front of the
        in-process serving API (`python -m lightgbm_tpu serve
        input_model=m.txt data=rows.csv`). Predictions are written to
        output_result in row order; one summary JSON line (request
        p50/p95/p99, rows/sec, serve counters) goes to stdout."""
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("task=serve requires input_model=")
        if not cfg.data:
            raise LightGBMError("task=serve requires data=")
        from .serve.server import serve_file
        stats = serve_file(cfg.input_model, cfg.data, cfg.output_result,
                           dict(self.params))
        if cfg.verbosity >= 0:
            import json
            print(json.dumps(stats))
            print(f"[LightGBM-TPU] served {stats['requests']} requests "
                  f"({stats['rows']} rows) in {stats['seconds']:.3f} s; "
                  f"predictions written to {cfg.output_result}")
        if stats.get("drained"):
            # SIGTERM drain: completed answers are on disk; exit with
            # the preemption code so a supervisor re-runs the replica
            raise SystemExit(int(stats["exit_code"]))

    # ------------------------------------------------------------------
    def _save_binary(self) -> None:
        """Bin the dataset and store the binned form for fast reload
        (ref: task=save_binary, Dataset::SaveBinaryFile dataset.h:710)."""
        cfg = self.config
        ds = self._load_train_data()
        out = cfg.data + ".bin"
        ds.save_binary(out)
        if cfg.verbosity >= 0:
            print(f"[LightGBM-TPU] binned dataset saved to {out}")


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m lightgbm_tpu config=<file> [key=value ...]\n"
              "       python -m lightgbm_tpu serve input_model=<model> "
              "data=<file> [key=value ...]")
        return 1
    if argv[0] == "serve":  # `python -m lightgbm_tpu serve ...` sugar
        argv = ["task=serve"] + list(argv[1:])
    try:
        Application(argv).run()
    except (LightGBMError, OSError, ValueError) as exc:
        print(f"[LightGBM-TPU] [Fatal] {exc}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
