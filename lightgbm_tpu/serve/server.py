"""Async model server: size-routed request front over the registry.

``ModelServer.predict(name, x)`` is the in-process serving API:

- requests of <= ``lowlat_max_rows`` rows dispatch through the
  AOT-compiled per-model low-latency path (no queueing, no deadline);
- larger requests coalesce in a per-model ``MicroBatcher`` and ride
  one engine dispatch into the warm shape buckets.

Either way the bytes returned are identical to ``model.predict`` called
directly (same engine math, same ``transform_raw``). Device work from
both paths funnels through ONE single-thread executor — the serving
analog of one accelerator queue: the event loop keeps accepting and
coalescing requests while the device runs the previous batch.

Per-request latency lands in the always-on ``obs.metrics`` reservoirs
(``serve/request`` p50/p95/p99 via ``latency_summary``), request/row
counts in the ``serve/*`` counters, and the registry's pack budget is
re-enforced after every request.

With the span tracer running (``LGBM_TPU_TRACE``), every request gets a
process-unique **trace ID** and lands in the Chrome trace as a
``serve/request`` span carrying ``args.trace_id`` plus its queue-wait /
device-time split; a coalesced batch's device work appears as one
``serve/batch`` span whose ``args.trace_ids`` lists exactly the
requests it carried (link fields validated by ``tools/check_trace.py``).
Tracer off ⇒ one attribute check per request.

``/metrics``, ``/healthz`` and ``/readyz`` are served by
``start_metrics_endpoint()`` (obs/export.py): readiness is false while
any ``warm()`` is in flight or no model is registered, so a rollout
can gate traffic on the warmed program set.

``serve_file`` is the thin driver behind ``python -m lightgbm_tpu
serve``: it replays a data file through the server as concurrent
requests and emits one summary JSON line.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs.flightrec import global_flightrec
from ..obs.metrics import global_metrics
from ..obs.trace import global_tracer
from ..resilience.degrade import CircuitBreaker, backoff_delays
from ..resilience.errors import (DeadlineExceeded, ServerOverloaded,
                                 TransientServeError)
from .batcher import MicroBatcher
from .registry import ModelRegistry, ServedModel

_trace_ids = itertools.count(1)


class _RequestTrace:
    """Per-request attribution carried through the batcher/lowlat split
    while the tracer runs: trace id, queue-wait and device-time in ns,
    and the coalesced batch link."""
    __slots__ = ("trace_id", "t0_ns", "queue_ns", "device_ns", "batch_id",
                 "path")

    def __init__(self) -> None:
        self.trace_id = f"{os.getpid():x}-{next(_trace_ids)}"
        self.t0_ns = time.perf_counter_ns()
        self.queue_ns = 0
        self.device_ns = 0
        self.batch_id = None
        self.path = ""

# the default request-size cycle for file replay (serve_request_rows=0):
# mostly low-latency-path sizes with periodic medium batches — the
# mixed-traffic shape the scheduler exists for
_MIXED_SIZES = (1, 8, 64, 512, 16, 2048, 32, 4)


class ModelServer:
    def __init__(self, registry: ModelRegistry,
                 max_batch_rows: int = 8192, max_wait_ms: float = 2.0,
                 lowlat_max_rows: Optional[int] = None,
                 deadline_ms: float = 0.0, max_queue_rows: int = 0,
                 retry_max: int = 2, retry_backoff_ms: float = 10.0,
                 breaker_threshold: int = 5,
                 breaker_reset_s: float = 30.0):
        self.registry = registry
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.lowlat_max_rows = int(registry.lowlat_max_rows
                                   if lowlat_max_rows is None
                                   else lowlat_max_rows)
        # graceful degradation under load (resilience/):
        # per-request deadline, bounded admission, transient-fault
        # retry schedule, per-model circuit breakers
        self.deadline_s = max(float(deadline_ms), 0.0) / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self.retry_max = max(int(retry_max), 0)
        self.retry_backoff_s = max(float(retry_backoff_ms), 0.0) / 1e3
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._queued_rows = 0  # admitted rows not yet answered
        # one device queue: batched AND low-latency dispatches serialize
        # here while the event loop keeps coalescing the next batch
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="lgbm-serve")
        self._batchers: Dict[str, MicroBatcher] = {}
        # SHAP-contribution requests coalesce separately: an explain
        # batch must never ride a raw-score dispatch (different output
        # widths), but both families share the one device executor
        self._explain_batchers: Dict[str, MicroBatcher] = {}
        self._warming = 0  # warm() calls in flight (readiness gate)
        self._draining = False  # SIGTERM drain: no new admissions
        self._metrics_endpoint = None

    # ------------------------------------------------------------------
    def _batcher(self, entry: ServedModel,
                 kind: str = "predict") -> MicroBatcher:
        explain = kind == "explain"
        pool = self._explain_batchers if explain else self._batchers
        b = pool.get(entry.name)
        if b is None or b._predict_fn.__self__ is not entry:
            # new or re-loaded entry: bind a fresh batcher to it
            b = pool[entry.name] = MicroBatcher(
                entry.dispatch_explain if explain else entry.dispatch_raw,
                max_batch_rows=self.max_batch_rows,
                max_wait_s=self.max_wait_s, executor=self._executor,
                counter_prefix="explain" if explain else "serve")
        return b

    def _breaker(self, entry: ServedModel) -> CircuitBreaker:
        br = self._breakers.get(entry.name)
        if br is None or getattr(br, "_entry", None) is not entry:
            # new or re-loaded entry: a fresh model must not inherit
            # the faults (or an open circuit) of the one it replaced
            br = self._breakers[entry.name] = CircuitBreaker(
                entry.name, threshold=self.breaker_threshold,
                reset_s=self.breaker_reset_s)
            br._entry = entry
        return br

    async def predict(self, name: str, data, raw_score: bool = False
                      ) -> np.ndarray:
        """Serve one request against model `name`. Output shape/values
        match ``LoadedModel.predict(data, raw_score=raw_score)``.

        Degradation contract (resilience/): a request older than the
        server deadline fails fast with ``DeadlineExceeded``; arrivals
        beyond the bounded admission queue are shed with
        ``ServerOverloaded`` (retry-after hint); transient pack/compile
        faults retry with exponential backoff; a model whose dispatches
        keep faulting trips its circuit breaker and fails fast until
        the half-open probe succeeds. Every event lands in the
        ``resilience/*`` obs counters (``lgbmtpu_resilience_*``)."""
        return await self._serve(name, data, raw_score, "predict")

    async def explain(self, name: str, data) -> np.ndarray:
        """Serve one SHAP-explanation request: [B, K * (F + 1)]
        contributions, bit-identical to
        ``entry.model.predict_contrib(data)`` on the same rows — the
        device kernel's per-row results don't depend on the row block,
        so coalesced slices match direct calls exactly (asserted by
        tools/check_shap.py). Same degradation contract as ``predict``
        (deadline / admission shedding / retry / breaker), same single
        device executor; small requests ride the AOT explain ladder
        (``ServedModel.explainer``), larger ones coalesce in a separate
        per-model explain batcher. Volume/latency land in the
        ``explain/*`` counters and the ``explain/request`` reservoir
        (``lgbmtpu_explain_*``). Contributions are raw-space by
        definition, so there is no ``raw_score`` transform. Linear-tree
        models reject with ``ValueError`` (the reference's
        pred_contrib restriction)."""
        return await self._serve(name, data, True, "explain")

    async def _serve(self, name: str, data, raw_score: bool,
                     kind: str) -> np.ndarray:
        explain = kind == "explain"
        pre = "explain" if explain else "serve"
        event = "explain_request" if explain else "serve_request"
        t0 = time.perf_counter()
        if self._draining:
            # graceful-drain contract: a draining server sheds new
            # arrivals BEFORE they cost anything — already-admitted
            # requests keep running to completion (drain() waits on
            # them), so nothing dies mid-batch
            global_metrics.inc_counter("resilience/drain_rejected")
            raise ServerOverloaded(
                "server is draining (shutdown requested): not "
                "admitting new requests", retry_after_s=0.0)
        deadline = (t0 + self.deadline_s) if self.deadline_s > 0 else 0.0
        x = np.asarray(data, np.float64)
        if x.ndim == 1:
            x = x.reshape(1, -1)
        entry = self.registry.get(name)
        need = entry.model.max_feature_idx + 1
        if x.shape[1] != need:
            # the engine's flat feature gathers CLAMP out-of-range
            # indices — a silent wrong answer; reject up front (the CLI
            # replay pads/truncates via conform_prediction_data first)
            raise ValueError(
                f"request has {x.shape[1]} features but model "
                f"'{name}' expects {need}")
        if explain and not entry.supports_explain:
            # mirror the reference restriction up front — a linear-tree
            # model would only raise deep inside the host fallback and
            # unfairly count against the circuit breaker
            raise ValueError(
                f"model '{name}' uses linear trees: pred_contrib "
                "explanations are not supported (reference "
                "restriction)")
        rows = int(x.shape[0])
        if self.max_queue_rows > 0 and self._queued_rows > 0 and \
                self._queued_rows + rows > self.max_queue_rows:
            # bounded admission: shed the arrival BEFORE it costs any
            # queue slot, breaker probe, or device work (an idle server
            # still accepts a single oversized request, mirroring the
            # batcher)
            global_metrics.inc_counter("resilience/load_shed")
            if global_flightrec.armed:
                global_flightrec.record("serve_request", model=name,
                                        rows=rows, ok=False,
                                        error="ServerOverloaded")
            raise ServerOverloaded(
                f"admission queue full ({self._queued_rows} rows "
                f"pending, request adds {rows} > "
                f"{self.max_queue_rows} allowed)",
                retry_after_s=max(self.max_wait_s, 1e-3))
        br = (self._breaker(entry) if self.breaker_threshold > 0
              else None)
        # open circuit -> CircuitOpenError, fail fast; probe_held marks
        # whether THIS request is the single half-open probe (only then
        # may a verdict-less death release the slot)
        probe_held = br.admit() if br is not None else False
        # route + count ONCE per request (retries reuse the routing
        # but must not inflate the request-volume counters)
        lowlat = (x.shape[0] <= min(self.lowlat_max_rows,
                                    entry.lowlat_max_rows)
                  and entry.supports_lowlat)
        global_metrics.inc_counter(f"{pre}/lowlat_requests" if lowlat
                                   else f"{pre}/batched_requests")
        loop = asyncio.get_running_loop()
        # request-scoped tracing: one attribute check when the tracer is
        # off; otherwise the request gets a trace id and its queue/device
        # attribution is collected through whichever path serves it
        rt = _RequestTrace() if global_tracer.enabled else None
        self._queued_rows += rows
        try:
            raw = await self._dispatch_with_retry(entry, x, rt, deadline,
                                                  br, loop, lowlat, kind)
        except (DeadlineExceeded, asyncio.CancelledError) as exc:
            # not a verdict on the model: a half-open PROBE that died
            # this way frees its slot so the breaker can probe again
            # (a closed-state admission holds no slot to free)
            if br is not None and probe_held:
                br.release_probe()
            if global_flightrec.armed:
                global_flightrec.record(event, model=name,
                                        rows=rows, ok=False,
                                        error=type(exc).__name__)
            raise
        except Exception as exc:
            # circuit-open / transient-exhausted / dispatch faults: the
            # black box keeps the outcome even though the error routes
            # back to the caller
            if global_flightrec.armed:
                global_flightrec.record(event, model=name,
                                        rows=rows, ok=False,
                                        error=type(exc).__name__)
            raise
        finally:
            self._queued_rows -= rows
        if explain:
            # contributions are raw-space by definition: no squeeze
            # ([B, F+1] at minimum), no objective transform
            out = raw
        else:
            out = raw[:, 0] if raw.shape[1] == 1 else raw
            if not raw_score:
                from ..model_io import transform_raw
                out = transform_raw(entry.model.objective_str, out)
        global_metrics.inc_counter(f"{pre}/requests")
        global_metrics.inc_counter(f"{pre}/rows", x.shape[0])
        global_metrics.note_latency(f"{pre}/request",
                                    time.perf_counter() - t0)
        if global_flightrec.armed:
            global_flightrec.record(
                event, model=name, rows=rows, ok=True,
                lowlat=bool(lowlat),
                latency_ms=round((time.perf_counter() - t0) * 1e3, 3))
        if rt is not None:
            args = {"trace_id": rt.trace_id, "path": rt.path,
                    "rows": int(x.shape[0]),
                    "queue_wait_us": rt.queue_ns / 1e3,
                    "device_us": rt.device_ns / 1e3}
            if rt.batch_id is not None:
                args["batch_id"] = rt.batch_id
            global_tracer.add_complete_span(
                "serve/explain" if explain else "serve/request",
                rt.t0_ns, time.perf_counter_ns() - rt.t0_ns, args=args)
        self.registry.evict_to_budget()
        return out

    # ------------------------------------------------------------------
    async def _dispatch_with_retry(self, entry: ServedModel,
                                   x: np.ndarray, rt, deadline: float,
                                   br, loop, lowlat: bool,
                                   kind: str = "predict") -> np.ndarray:
        """Route one request (lowlat / batched) with exponential-backoff
        retries of transient faults. Deadline and cancellation pass
        straight through (load conditions, not model faults); any other
        failure — transient retries exhausted included — counts against
        the model's circuit breaker."""
        delays = [0.0] + backoff_delays(self.retry_max,
                                        self.retry_backoff_s)
        last_exc: Optional[BaseException] = None
        for i, delay in enumerate(delays):
            if delay > 0:
                await asyncio.sleep(delay)
            if deadline and time.perf_counter() > deadline:
                global_metrics.inc_counter("resilience/deadline_exceeded")
                raise DeadlineExceeded(
                    f"request expired before dispatch "
                    f"(attempt {i + 1})",
                    elapsed_s=time.perf_counter()
                    - (deadline - self.deadline_s))
            try:
                out = await self._dispatch(entry, x, rt, deadline, loop,
                                           lowlat, kind)
            except (DeadlineExceeded, asyncio.CancelledError):
                raise
            except TransientServeError as exc:
                last_exc = exc
                if i + 1 < len(delays):
                    global_metrics.inc_counter("resilience/retries")
                    continue
                break  # retries exhausted -> breaker failure below
            except Exception:
                if br is not None:
                    br.record_failure()
                global_metrics.inc_counter("resilience/dispatch_failures")
                raise
            if br is not None:
                br.record_success()
            return out
        if br is not None:
            br.record_failure()
        global_metrics.inc_counter("resilience/dispatch_failures")
        raise last_exc

    async def _dispatch(self, entry: ServedModel, x: np.ndarray, rt,
                        deadline: float, loop, lowlat: bool,
                        kind: str = "predict") -> np.ndarray:
        # the route was decided (and counted) once in _serve(): the
        # server-level threshold can only lower the routing cut below
        # the per-entry AOT limit, never push requests past it
        explain = kind == "explain"
        if lowlat:
            if rt is not None:
                rt.path = "lowlat"
            fn = (entry.dispatch_lowlat_explain if explain
                  else entry.dispatch_lowlat)

            def run_lowlat(x=x, fn=fn, rt=rt):
                t_dev = time.perf_counter_ns()
                if deadline and time.perf_counter() > deadline:
                    # the executor queue ate the whole budget: fail
                    # fast instead of spending device time on an
                    # answer nobody is waiting for
                    global_metrics.inc_counter(
                        "resilience/deadline_exceeded")
                    raise DeadlineExceeded(
                        "request expired waiting for the serve "
                        "executor")
                if rt is not None:
                    rt.queue_ns = t_dev - rt.t0_ns  # executor queue wait
                out = fn(x)
                if rt is not None:
                    rt.device_ns = time.perf_counter_ns() - t_dev
                return out

            return await loop.run_in_executor(self._executor, run_lowlat)
        if rt is not None:
            rt.path = "batched"
        return await self._batcher(entry, kind).submit(x, trace=rt,
                                                       deadline=deadline)

    # ------------------------------------------------------------------
    def warm(self, name: str, num_features: int,
             explain: bool = False) -> None:
        """Precompile the serving program set for `name`: the low-
        latency bucket ladder plus the engine's power-of-two batch
        buckets up to max_batch_rows. After this, steady-state traffic
        of any request mix runs with ZERO recompiles (asserted by
        tools/check_serve.py through the obs recompile counters).

        With ``explain=True`` the SHAP program set warms too: the AOT
        explain ladder (``ServedModel.explainer``) plus the streaming
        contribution program's batch buckets — opt-in because the
        explain ladder doubles warm-time compiles and most servers
        never take explain traffic (tools/check_shap.py asserts the
        zero-recompile story for the explain route).

        While a warm() is in flight the server reports NOT ready
        (``/readyz`` 503) — a rollout that gates traffic on readiness
        never lands requests on cold programs."""
        self._warming += 1
        try:
            entry = self.registry.get(name)
            if entry.supports_lowlat:
                entry.lowlat.warm(num_features)
            if explain and entry.supports_explain:
                entry.explainer.warm(num_features)
            # engine buckets floor at 16 rows (ops/predict._row_bucket)
            b = 16
            while b < 2 * self.max_batch_rows:
                entry.predict_raw(np.zeros((b, num_features)))
                if explain and entry.supports_explain:
                    entry.explain_raw(np.zeros((b, num_features)))
                b <<= 1
        finally:
            self._warming -= 1

    @property
    def ready(self) -> bool:
        """Readiness: at least one model registered, no warm() in
        flight, and not draining (a draining replica deregisters itself
        by flipping ``/readyz`` to 503 — the router stops routing to it
        before the process exits). Liveness (``/healthz``) is just the
        listener being up."""
        return (not self._draining and self._warming == 0
                and len(self.registry) > 0)

    # ------------------------------------------------------------------
    # graceful drain (SIGTERM contract, single-replica half of the
    # fleet's drain: serve/fleet.py reuses begin_drain/drain per replica)
    def begin_drain(self) -> None:
        """Stop admitting new requests (idempotent). ``ready`` flips
        false immediately so readiness-gated routers deregister."""
        if self._draining:
            return
        self._draining = True
        global_metrics.inc_counter("resilience/drain_begin")
        if global_flightrec.armed:
            global_flightrec.record("serve_drain",
                                    queued_rows=self._queued_rows)

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: stop admitting, wait (bounded) for every
        already-admitted request to complete, flush pending batches.
        Returns True when the server emptied within the timeout."""
        self.begin_drain()
        deadline = time.perf_counter() + max(float(timeout_s), 0.0)
        while self._queued_rows > 0 and time.perf_counter() < deadline:
            for pool in (self._batchers, self._explain_batchers):
                for b in pool.values():
                    b.flush()  # don't make stragglers wait max_wait_ms
            await asyncio.sleep(0.002)
        drained = self._queued_rows == 0
        if global_flightrec.armed:
            global_flightrec.record("serve_drained", ok=drained,
                                    queued_rows=self._queued_rows)
        return drained

    def start_metrics_endpoint(self, port: int = 0,
                               host: Optional[str] = None):
        """Serve ``/metrics`` (Prometheus text format over the obs
        registries + this server's pack/registry gauges), ``/healthz``
        and ``/readyz`` on a daemon thread. port=0 binds an ephemeral
        port (read it back from ``.port``). `host` defaults to the
        ``LGBM_TPU_METRICS_HOST`` env var or loopback — external
        readiness probes / scrapers need ``0.0.0.0`` (opt-in: the
        document exposes host internals). Returns the endpoint."""
        from ..obs.export import MetricsHTTPEndpoint, render_openmetrics

        def render() -> str:
            return render_openmetrics(extra_gauges={
                "lgbmtpu_serve_pack_bytes": self.registry.pack_bytes(),
                "lgbmtpu_serve_models": len(self.registry),
                "lgbmtpu_resilience_queued_rows": self._queued_rows,
                "lgbmtpu_resilience_breakers_open": sum(
                    1 for b in self._breakers.values() if b.is_open),
            })

        if host is None:
            host = os.environ.get("LGBM_TPU_METRICS_HOST", "") \
                or "127.0.0.1"
        self._metrics_endpoint = MetricsHTTPEndpoint(
            render, ready_fn=lambda: self.ready, port=port, host=host)
        return self._metrics_endpoint

    def stats(self) -> Dict:
        """Serving snapshot: request latency quantiles + counters."""
        return {
            "request_latency": global_metrics.latency_summary(
                "serve/request"),
            "batch_wait": global_metrics.latency_summary(
                "serve/batch_wait"),
            "counters": {k: v for k, v in
                         sorted(global_metrics.counters.items())
                         if k.startswith(("serve/", "explain/",
                                          "resilience/"))},
            "pack_bytes": self.registry.pack_bytes(),
        }

    async def close(self) -> None:
        """Flush pending batches and release the device executor."""
        for pool in (self._batchers, self._explain_batchers):
            for b in pool.values():
                b.flush()
        self._executor.shutdown(wait=True)
        if self._metrics_endpoint is not None:
            self._metrics_endpoint.close()
            self._metrics_endpoint = None


# ----------------------------------------------------------------------
async def replay(server: ModelServer, name: str, data: np.ndarray,
                 sizes: Sequence[int], raw_score: bool = False,
                 arrival_s: Optional[Sequence[float]] = None,
                 drop_rejected: bool = False
                 ) -> List[Optional[np.ndarray]]:
    """Fire one request per entry of `sizes`, slicing `data` in order,
    all concurrently; returns the per-request outputs in request order.
    With `arrival_s`, request i is released at that offset from the
    replay start (an OPEN-loop trace: arrivals don't wait for earlier
    completions — queueing delay shows up in the latency quantiles
    instead of silently throttling the offered load). With
    `drop_rejected`, a request shed because the server started draining
    resolves to None instead of failing the whole replay (serve_file's
    SIGTERM path: completed answers still get written)."""
    async def one(lo: int, hi: int, delay: float) -> Optional[np.ndarray]:
        if delay > 0:
            await asyncio.sleep(delay)
        try:
            return await server.predict(name, data[lo:hi],
                                        raw_score=raw_score)
        except ServerOverloaded:
            if drop_rejected and server._draining:
                return None
            raise

    tasks = []
    lo = 0
    for i, size in enumerate(sizes):
        hi = min(lo + int(size), data.shape[0])
        delay = float(arrival_s[i]) if arrival_s is not None else 0.0
        tasks.append(asyncio.ensure_future(one(lo, hi, delay)))
        lo = hi
        if lo >= data.shape[0]:
            break
    return list(await asyncio.gather(*tasks))


def request_sizes(total_rows: int, request_rows: int = 0) -> List[int]:
    """Split `total_rows` into request sizes: fixed `request_rows`, or
    the mixed small/large cycle when 0."""
    sizes: List[int] = []
    done = 0
    i = 0
    while done < total_rows:
        s = request_rows if request_rows > 0 else \
            _MIXED_SIZES[i % len(_MIXED_SIZES)]
        sizes.append(min(s, total_rows - done))
        done += sizes[-1]
        i += 1
    return sizes


def registry_from_config(cfg) -> ModelRegistry:
    """One registry, sized by the serve_* knobs — shared by the
    single-server driver (serve_file) and each fleet replica
    (serve/fleet.py), so every serving process packs models under the
    identical contract (bit-identical outputs, PR-3)."""
    return ModelRegistry(max_pack_bytes=cfg.serve_cache_bytes,
                         lowlat_max_rows=cfg.serve_lowlat_max_rows,
                         predict_chunk_rows=cfg.tpu_predict_chunk,
                         artifact_dir=cfg.serve_artifact_dir,
                         compile_cache=cfg.tpu_compile_cache)


def server_from_config(registry: ModelRegistry, cfg) -> ModelServer:
    """Build a ModelServer from the serve_* config knobs (the one
    construction recipe for serve_file, fleet replicas, and tests)."""
    return ModelServer(registry,
                       max_batch_rows=cfg.serve_max_batch_rows,
                       max_wait_ms=cfg.serve_max_wait_ms,
                       deadline_ms=cfg.serve_deadline_ms,
                       max_queue_rows=cfg.serve_max_queue_rows,
                       retry_max=cfg.serve_retry_max,
                       retry_backoff_ms=cfg.serve_retry_backoff_ms,
                       breaker_threshold=cfg.serve_breaker_threshold,
                       breaker_reset_s=cfg.serve_breaker_reset_s)


def serve_file(input_model: str, data_path: str, output_result: str,
               params: Optional[Dict] = None) -> Dict:
    """The ``task=serve`` driver: load the model into a registry,
    replay the data file through the async server as concurrent
    requests, write predictions (in row order) to `output_result`, and
    return the serving stats dict. `params` carries the serve_* knobs
    plus loader options.

    SIGTERM contract (single-replica half of the fleet drain): the
    first SIGTERM stops admitting new requests, already-admitted ones
    run to completion, predictions for every COMPLETED request are
    still written, and the stats carry ``drained=True`` +
    ``exit_code=EXIT_PREEMPTED`` so the caller (CLI, fleet replica
    main) exits 75 — "terminated on purpose, re-run me" — instead of
    dying mid-batch."""
    import signal as signal_mod

    from ..cli import conform_prediction_data, write_prediction_file
    from ..config import Config
    from ..io.text_loader import load_svmlight_or_csv
    cfg = Config.from_params(params or {})
    data, _label, _w, _g = load_svmlight_or_csv(data_path,
                                                dict(params or {}))
    registry = registry_from_config(cfg)
    # validate=True: prove the model can pack + predict BEFORE the
    # server starts taking traffic on it (serving startup, not a
    # hot-swap — the upfront smoke is free relative to warm())
    entry = registry.load("default", model_file=input_model,
                          validate=True)
    data = conform_prediction_data(np.asarray(data, np.float64),
                                   entry.model.max_feature_idx + 1,
                                   cfg.predict_disable_shape_check)
    server = server_from_config(registry, cfg)
    metrics_port = None
    if int(cfg.serve_metrics_port) >= 0:
        metrics_port = server.start_metrics_endpoint(
            int(cfg.serve_metrics_port)).port
    sizes = request_sizes(data.shape[0], cfg.serve_request_rows)
    drain_state = {"requested": False}

    async def run() -> List[Optional[np.ndarray]]:
        loop = asyncio.get_running_loop()

        def _on_sigterm() -> None:
            drain_state["requested"] = True
            server.begin_drain()

        try:
            loop.add_signal_handler(signal_mod.SIGTERM, _on_sigterm)
        except (NotImplementedError, ValueError, RuntimeError):
            pass  # non-main-thread / platform without signal support
        try:
            return await replay(server, "default", data, sizes,
                                raw_score=cfg.predict_raw_score,
                                drop_rejected=True)
        finally:
            if drain_state["requested"]:
                await server.drain()
            try:
                loop.remove_signal_handler(signal_mod.SIGTERM)
            except (NotImplementedError, ValueError, RuntimeError):
                pass
            await server.close()

    t0 = time.perf_counter()
    outs = asyncio.run(run())
    elapsed = time.perf_counter() - t0

    served = [o for o in outs if o is not None]
    write_prediction_file(output_result, served)

    stats = server.stats()
    stats.update(requests=len(served), rows=int(data.shape[0]),
                 seconds=round(elapsed, 4),
                 rows_per_sec=round(data.shape[0] / max(elapsed, 1e-9), 1))
    if drain_state["requested"]:
        from ..resilience.errors import EXIT_PREEMPTED
        stats.update(drained=True, shed=len(outs) - len(served),
                     exit_code=EXIT_PREEMPTED)
    if metrics_port is not None:
        stats["metrics_port"] = metrics_port
    return stats
