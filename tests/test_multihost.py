"""Multi-host (two-process) training test — the DistributedMockup
pattern (ref: tests/distributed/_test_distributed.py:53: N worker
subprocesses on localhost, pre-partitioned rows, tree_learner=data,
central-vs-distributed agreement asserted)."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax

REPO = Path(__file__).resolve().parent.parent

# Every test here drives jax.distributed multi-PROCESS collectives
# (parallel/distributed.py sync_dataset -> broadcast_one_to_all), which
# jaxlib's CPU backend does not implement ("Multiprocess computations
# aren't implemented on the CPU backend") — the worker subprocesses die
# on the first broadcast. Capability skip, not xfail: on a TPU/GPU
# backend these run; under the suite's forced-CPU config they cannot.
pytestmark = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="jaxlib CPU backend has no multiprocess collectives "
           "(broadcast_one_to_all raises INVALID_ARGUMENT); needs a "
           "TPU/GPU runtime")

WORKER = r"""
import json, os, sys
import numpy as np
rank = int(sys.argv[1])
port = sys.argv[2]
tmp = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb
from lightgbm_tpu.parallel import distributed as dist

dist.init_distributed(coordinator_address=f"127.0.0.1:{{port}}",
                      num_processes=2, process_id=rank)

data = np.load(f"{{tmp}}/shard{{rank}}.npz")
X, y = data["X"], data["y"]
params = {{"objective": "binary", "tree_learner": "data", "num_leaves": 15,
           "min_data_in_leaf": 5, "verbosity": -1, "max_bin": 63,
           "enable_bundle": False}}
ds = lgb.Dataset(X, label=y, params=dict(params))
ds.construct()
dist.sync_dataset(ds)
bst = lgb.Booster(params, ds)
for _ in range(8):
    bst.update()
if rank == 0:
    bst.save_model(f"{{tmp}}/dist_model.txt")
print(f"worker {{rank}} done: {{bst.num_trees()}} trees", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_data_parallel(tmp_path):
    rng = np.random.RandomState(0)
    n, f = 800, 6  # 400 rows per process, divisible by 2 local devices
    X = rng.randn(n, f)
    logit = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.3 * X[:, 2] * X[:, 3]
    y = (logit + 0.2 * rng.randn(n) > 0.5).astype(np.float32)

    np.savez(tmp_path / "shard0.npz", X=X[: n // 2], y=y[: n // 2])
    np.savez(tmp_path / "shard1.npz", X=X[n // 2:], y=y[n // 2:])

    port = _free_port()
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER.format(repo=str(REPO)))
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), str(r), str(port), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=840)
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out[-4000:]}"

    # central model on the full data for comparison
    import lightgbm_tpu as lgb
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "max_bin": 63}
    ds = lgb.Dataset(X, label=y, params=dict(params))
    central = lgb.train(dict(params), ds, num_boost_round=8)

    dist_model = lgb.Booster(model_file=str(tmp_path / "dist_model.txt"))
    assert dist_model.num_trees() == 8
    p_c = central.predict(X)
    p_d = dist_model.predict(X)
    # distributed mappers come from rank 0's half, so bin boundaries (and
    # with them individual splits) differ slightly from the central run;
    # the MODELS must still agree (ref asserts the same,
    # _test_distributed.py:168,184)
    agree = np.mean((p_c > 0.5) == (p_d > 0.5))
    assert agree > 0.9, f"central-vs-distributed agreement {agree}"
    auc_d = _auc(y, p_d)
    assert auc_d > 0.85, f"distributed AUC {auc_d}"


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty(len(s))
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0.5
    return (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) / (
        pos.sum() * (~pos).sum())


def test_cluster_train_distributed():
    """cluster.train_distributed: the dask-orchestration analog — spawn
    a local worker per partition, train across them, get one model
    (ref: dask.py LocalCluster test pattern, test_dask.py)."""
    from lightgbm_tpu.cluster import train_distributed

    rng = np.random.RandomState(7)
    n, f = 600, 5
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.4).astype(np.float32)
    parts = [{"X": X[:300], "y": y[:300]},
             {"X": X[300:], "y": y[300:]}]
    bst = train_distributed(
        {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1, "max_bin": 63},
        parts, num_boost_round=6, devices_per_worker=2)
    assert bst.num_trees() == 6
    auc = _auc(y, bst.predict(X))
    assert auc > 0.85, auc


def test_dask_analog_estimators():
    """DaskLGBM* analogs (ref: dask.py): sklearn-style estimators that
    train one jax.distributed worker process per partition through
    cluster.train_distributed."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    X = rng.randn(600, 5)
    yr = X[:, 0] * 2 + 0.1 * rng.randn(600)
    m = lgb.DaskLGBMRegressor(n_partitions=2, n_estimators=5,
                              num_leaves=7, verbosity=-1)
    m.fit(X, yr)
    assert m.score(X, yr) > 0.5
    yc = (X[:, 0] > 0).astype(int)
    mc = lgb.DaskLGBMClassifier(n_partitions=2, n_estimators=5,
                                num_leaves=7, verbosity=-1)
    mc.fit(X, yc)
    assert mc.score(X, yc) > 0.8
    assert list(mc.classes_) == [0, 1]


def test_dask_analog_ranker_global_lambdas():
    """Distributed lambdarank: the ranking objective is rebuilt from
    GLOBAL metadata on every worker (labels + query sizes allgathered),
    so the program computes exact global lambdas — where the reference's
    distributed lambdarank approximates with machine-local ones."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(5)
    X = rng.randn(480, 4)
    g = np.full(24, 20)
    y = np.clip((X[:, 0] * 2 + rng.randn(480) * 0.3).round() % 4, 0, 3)
    mr = lgb.DaskLGBMRanker(n_partitions=2, n_estimators=4,
                            num_leaves=7, verbosity=-1)
    mr.fit(X, y, group=g)
    pred = mr.predict(X)
    # ordering signal: better-labeled docs score higher on average
    assert pred[y >= 2].mean() > pred[y <= 1].mean()
    # unequal partitions must fail with the clear contract error
    import pytest as _pytest
    bad_g = np.concatenate([np.full(13, 20), [19]])
    Xb = rng.randn(int(bad_g.sum()), 4)
    with _pytest.raises(ValueError, match="equal-size partitions"):
        lgb.DaskLGBMRanker(n_partitions=2, n_estimators=2,
                           verbosity=-1).fit(
            Xb, np.zeros(int(bad_g.sum())), group=bad_g)
