"""Plotting utilities (ref: python-package/lightgbm/plotting.py:37-749).

Same public surface as the reference — ``plot_importance``,
``plot_split_value_histogram``, ``plot_metric``, ``plot_tree``,
``create_tree_digraph`` — with one TPU-era upgrade: ``plot_tree`` renders
natively with matplotlib (recursive tidy layout) instead of requiring the
graphviz system binary; ``create_tree_digraph`` still produces a graphviz
object when the library is installed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster, LightGBMError
from .tree import Tree

__all__ = [
    "plot_importance",
    "plot_split_value_histogram",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
]


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt  # noqa: F401
        return plt
    except ImportError as exc:  # pragma: no cover - mpl is present in CI
        raise ImportError(
            "matplotlib is required for plotting (install matplotlib)"
        ) from exc


def _booster_trees(booster: Booster) -> List[Tree]:
    """Flat tree list of a live or loaded booster."""
    if getattr(booster, "_loaded", None) is not None:
        return list(booster._loaded.trees)
    return [t for iter_trees in booster._gbdt.models for t in iter_trees]


def _feature_names(booster: Booster) -> List[str]:
    try:
        return list(booster.feature_name())
    except Exception:
        n = booster.num_feature()
        return [f"Column_{i}" for i in range(n)]


# ----------------------------------------------------------------------
def plot_importance(booster: Booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple[float, float]] = None,
                    ylim: Optional[Tuple[float, float]] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True,
                    figsize: Optional[Tuple[float, float]] = None,
                    dpi: Optional[int] = None, grid: bool = True,
                    precision: Optional[int] = 3, **kwargs):
    """Horizontal bar chart of feature importances
    (ref: plotting.py plot_importance)."""
    plt = _check_matplotlib()
    importance = np.asarray(
        booster.feature_importance(importance_type=importance_type),
        np.float64)
    names = _feature_names(booster)

    pairs = sorted(zip(importance, names), key=lambda t: t[0])
    if ignore_zero:
        pairs = [p for p in pairs if p[0] != 0]
    if not pairs:
        raise ValueError("cannot plot importance: all importances are zero")
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    values, labels = zip(*pairs)

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ypos = np.arange(len(values))
    ax.barh(ypos, values, height=height, align="center", **kwargs)
    for y, v in zip(ypos, values):
        txt = f"{v:.{precision}f}" if (
            precision is not None and importance_type == "gain") else f"{v:g}"
        ax.text(v + 1e-9, y, txt, va="center")
    ax.set_yticks(ypos)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    else:
        ax.set_ylim(-1, len(values))
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


# ----------------------------------------------------------------------
def plot_split_value_histogram(booster: Booster,
                               feature: Union[int, str], bins=None,
                               ax=None, width_coef: float = 0.8,
                               xlim=None, ylim=None,
                               title: Optional[str] =
                               "Split value histogram for feature @feature@",
                               xlabel: Optional[str] = "Feature split value",
                               ylabel: Optional[str] = "Count",
                               figsize=None, dpi=None, grid: bool = True,
                               **kwargs):
    """Histogram of the numerical thresholds the model split `feature` at
    (ref: plotting.py plot_split_value_histogram)."""
    plt = _check_matplotlib()
    names = _feature_names(booster)
    if isinstance(feature, str):
        try:
            fidx = names.index(feature)
        except ValueError:
            raise ValueError(f"unknown feature name: {feature}")
        fname = feature
    else:
        fidx = int(feature)
        fname = names[fidx] if fidx < len(names) else f"Column_{fidx}"

    values: List[float] = []
    for tree in _booster_trees(booster):
        for i in range(tree.num_internal):
            if tree.split_feature[i] == fidx and \
                    (tree.decision_type[i] & 1) == 0:  # numerical only
                values.append(float(tree.threshold[i]))
    if not values:
        raise ValueError(
            f"cannot plot split value histogram: feature {fname} was not "
            "used in splitting")
    values = np.asarray(values)
    if bins is None:
        bins = min(len(np.unique(values)) + 1, 50)
    hist, edges = np.histogram(values, bins=bins)
    centers = (edges[:-1] + edges[1:]) / 2
    width = width_coef * (edges[1] - edges[0])

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centers, hist, width=width, align="center", **kwargs)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    else:
        ax.set_ylim(0, max(hist) * 1.1)
    if title is not None:
        ax.set_title(title.replace("@feature@", str(fname)))
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


# ----------------------------------------------------------------------
def plot_metric(booster_or_record, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    """Plot metric curves recorded by ``callback.record_evaluation``
    (ref: plotting.py plot_metric; like the reference, a Booster is
    rejected — pass the eval-result dict)."""
    plt = _check_matplotlib()
    if isinstance(booster_or_record, Booster):
        raise TypeError(
            "plot_metric takes the dict from record_evaluation(), not a "
            "Booster (train with callbacks=[record_evaluation(d)])")
    record: Dict[str, Dict[str, List[float]]] = booster_or_record
    if not record:
        raise ValueError("eval results are empty")

    if dataset_names is None:
        dataset_names = list(record.keys())
    first = record[dataset_names[0]]
    if metric is None:
        if len(first) > 1:
            raise ValueError(
                f"more than one metric recorded ({sorted(first)}); pass "
                "metric= explicitly")
        metric = next(iter(first))

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    for name in dataset_names:
        if metric not in record.get(name, {}):
            raise ValueError(f"metric {metric} not recorded for {name}")
        ys = record[name][metric]
        ax.plot(range(len(ys)), ys, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


# ----------------------------------------------------------------------
def _tree_of(booster: Booster, tree_index: int) -> Tree:
    trees = _booster_trees(booster)
    if not 0 <= tree_index < len(trees):
        raise IndexError(
            f"tree_index {tree_index} out of range (model has "
            f"{len(trees)} trees)")
    return trees[tree_index]


def _node_label(tree: Tree, node: int, is_leaf: bool, names: List[str],
                show_info: List[str], precision: int) -> str:
    if is_leaf:
        lines = [f"leaf {node}",
                 f"value: {tree.leaf_value[node]:.{precision}f}"]
        if "leaf_count" in show_info:
            lines.append(f"count: {int(tree.leaf_count[node])}")
        if "leaf_weight" in show_info:
            lines.append(f"weight: {tree.leaf_weight[node]:.{precision}f}")
        return "\n".join(lines)
    f = int(tree.split_feature[node])
    name = names[f] if f < len(names) else f"Column_{f}"
    if (tree.decision_type[node] & 1) != 0:
        cond = f"{name} in bitset"
    else:
        cond = f"{name} <= {tree.threshold[node]:.{precision}f}"
    lines = [cond]
    if "split_gain" in show_info:
        lines.append(f"gain: {tree.split_gain[node]:.{precision}f}")
    if "internal_value" in show_info:
        lines.append(f"value: {tree.internal_value[node]:.{precision}f}")
    if "internal_count" in show_info:
        lines.append(f"count: {int(tree.internal_count[node])}")
    return "\n".join(lines)


def _layout_tree(tree: Tree):
    """Tidy layout: x = leaf order, y = -depth. Children encodings follow
    the reference: child >= 0 -> internal node, < 0 -> leaf ~child."""
    pos: Dict[Tuple[str, int], Tuple[float, float]] = {}
    next_x = [0.0]

    def walk(node: int, is_leaf: bool, depth: int) -> float:
        if is_leaf:
            x = next_x[0]
            next_x[0] += 1.0
            pos[("L", node)] = (x, -depth)
            return x
        lc, rc = int(tree.left_child[node]), int(tree.right_child[node])
        xl = walk(~lc if lc < 0 else lc, lc < 0, depth + 1)
        xr = walk(~rc if rc < 0 else rc, rc < 0, depth + 1)
        x = (xl + xr) / 2
        pos[("N", node)] = (x, -depth)
        return x

    if tree.num_internal > 0:
        walk(0, False, 0)
    else:
        pos[("L", 0)] = (0.0, 0.0)
    return pos


def plot_tree(booster: Booster, tree_index: int = 0, ax=None,
              figsize=None, dpi=None,
              show_info: Optional[List[str]] = None,
              precision: int = 3, orientation: str = "vertical",
              **kwargs):
    """Draw one tree with matplotlib (no graphviz binary needed, unlike
    the reference's plot_tree which shells out to dot)."""
    plt = _check_matplotlib()
    tree = _tree_of(booster, tree_index)
    names = _feature_names(booster)
    show_info = show_info or []

    pos = _layout_tree(tree)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize or (12, 8), dpi=dpi)

    def xy(key):
        x, y = pos[key]
        return (x, y) if orientation == "vertical" else (-y, -x)

    # edges
    for node in range(tree.num_internal):
        for child, tag in ((int(tree.left_child[node]), "yes"),
                           (int(tree.right_child[node]), "no")):
            ckey = ("L", ~child) if child < 0 else ("N", child)
            x0, y0 = xy(("N", node))
            x1, y1 = xy(ckey)
            ax.plot([x0, x1], [y0, y1], "-", color="0.6", zorder=1)
            ax.annotate(tag, ((x0 + x1) / 2, (y0 + y1) / 2), fontsize=7,
                        color="0.4", ha="center")
    # nodes
    for key in pos:
        kind, node = key
        x, y = xy(key)
        label = _node_label(tree, node, kind == "L", names, show_info,
                            precision)
        color = "#d5e8d4" if kind == "L" else "#dae8fc"
        ax.annotate(label, (x, y), ha="center", va="center", fontsize=8,
                    bbox=dict(boxstyle="round", fc=color, ec="0.4"),
                    zorder=2, **kwargs)
    ax.set_axis_off()
    ax.set_title(f"Tree {tree_index}")
    return ax


def create_tree_digraph(booster: Booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: int = 3, orientation: str = "horizontal",
                        name: Optional[str] = None, comment: Optional[str]
                        = None, filename: Optional[str] = None,
                        directory: Optional[str] = None,
                        format: Optional[str] = None, engine: Optional[str]
                        = None, encoding: Optional[str] = None,
                        graph_attr: Optional[dict] = None,
                        node_attr: Optional[dict] = None,
                        edge_attr: Optional[dict] = None):
    """graphviz.Digraph of one tree (ref: plotting.py create_tree_digraph);
    requires the `graphviz` python package."""
    try:
        import graphviz
    except ImportError as exc:
        raise ImportError(
            "graphviz is required for create_tree_digraph (plot_tree "
            "renders without it)") from exc
    tree = _tree_of(booster, tree_index)
    names = _feature_names(booster)
    show_info = show_info or []

    graph = graphviz.Digraph(
        name=name, comment=comment, filename=filename, directory=directory,
        format=format, engine=engine, encoding=encoding,
        graph_attr=dict(graph_attr or {},
                        rankdir="LR" if orientation == "horizontal" else
                        "TB"),
        node_attr=node_attr, edge_attr=edge_attr)

    def nid(kind: str, node: int) -> str:
        return f"{kind}{node}"

    if tree.num_internal == 0:
        graph.node(nid("L", 0),
                   _node_label(tree, 0, True, names, show_info, precision))
        return graph
    for node in range(tree.num_internal):
        graph.node(nid("N", node),
                   _node_label(tree, node, False, names, show_info,
                               precision), shape="box")
    for leaf in range(tree.num_leaves):
        graph.node(nid("L", leaf),
                   _node_label(tree, leaf, True, names, show_info,
                               precision), shape="ellipse")
    for node in range(tree.num_internal):
        for child, tag in ((int(tree.left_child[node]), "yes"),
                           (int(tree.right_child[node]), "no")):
            target = nid("L", ~child) if child < 0 else nid("N", child)
            graph.edge(nid("N", node), target, label=tag)
    return graph
