"""Fault tolerance for training and serving.

- ``checkpoint`` — atomic full-state snapshots at iteration boundaries
  + bit-identical resume (``tpu_checkpoint_every`` /
  ``tpu_checkpoint_path``; SIGTERM-driven preemption snapshots exiting
  with ``EXIT_PREEMPTED``).
- ``faults`` — the deterministic fault-injection plan the tests and
  ``tools/check_resilience.py`` drive the recovery paths with.
- ``degrade`` — serving-side graceful degradation (per-model circuit
  breaker, backoff schedules) used by ``serve/server.py`` together
  with per-request deadlines and bounded admission.
- ``watchdog`` — the distributed-training heartbeat/deadline watchdog
  (``tpu_watchdog_deadline_s``): a hung peer becomes a structured
  ``PeerLostError`` + checkpoint + ``EXIT_PREEMPTED`` instead of an
  infinite collective stall.
- ``errors`` — the structured exception taxonomy
  (``CorruptModelError`` and friends).
"""

from .errors import (EXIT_PREEMPTED, CircuitOpenError,
                     CorruptCheckpointError, CorruptModelError,
                     DeadlineExceeded, DistributedInitError,
                     ElasticResumeError, PeerLostError,
                     ResumeMismatchError, ServerOverloaded,
                     TransientServeError)
from .faults import FaultPlan, global_faults, install as install_faults
from .checkpoint import (load_checkpoint, restore_booster,
                         save_checkpoint)
from .continual import ContinualTrainer, GenerationResult
from .watchdog import Watchdog

__all__ = [
    "EXIT_PREEMPTED", "CircuitOpenError", "ContinualTrainer",
    "CorruptCheckpointError", "CorruptModelError", "DeadlineExceeded",
    "DistributedInitError", "ElasticResumeError", "GenerationResult",
    "PeerLostError", "ResumeMismatchError", "ServerOverloaded",
    "TransientServeError", "FaultPlan", "Watchdog",
    "global_faults", "install_faults", "load_checkpoint",
    "restore_booster", "save_checkpoint",
]
