"""Plotting tests (ref: tests/python_package_test/test_plotting.py)."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from conftest import make_binary  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import plotting  # noqa: E402


@pytest.fixture(scope="module")
def booster():
    X, y = make_binary(500, 6)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    res = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "metric": "binary_logloss,auc", "verbosity": -1},
                    ds, num_boost_round=8, valid_sets=[ds],
                    valid_names=["train"],
                    callbacks=[lgb.record_evaluation(res)])
    bst._eval_record = res
    return bst


def test_plot_importance(booster):
    ax = plotting.plot_importance(booster)
    assert ax.get_title() == "Feature importance"
    assert len(ax.patches) > 0
    plt.close("all")
    ax = plotting.plot_importance(booster, importance_type="gain",
                                  max_num_features=3)
    assert len(ax.patches) <= 3
    plt.close("all")


def test_plot_split_value_histogram(booster):
    imp = booster.feature_importance()
    feat = int(np.argmax(imp))
    ax = plotting.plot_split_value_histogram(booster, feat)
    assert len(ax.patches) > 0
    plt.close("all")
    with pytest.raises(ValueError):
        unused = int(np.argmin(imp))
        if imp[unused] != 0:
            pytest.skip("all features used")
        plotting.plot_split_value_histogram(booster, unused)
    plt.close("all")


def test_plot_metric(booster):
    ax = plotting.plot_metric(booster._eval_record, metric="auc")
    assert len(ax.lines) == 1
    plt.close("all")
    with pytest.raises(ValueError):
        plotting.plot_metric(booster._eval_record)  # >1 metric, ambiguous
    with pytest.raises(TypeError):
        plotting.plot_metric(booster)
    plt.close("all")


def test_plot_tree(booster):
    ax = plotting.plot_tree(booster, tree_index=0,
                            show_info=["internal_count", "leaf_count"])
    assert len(ax.texts) > 0
    plt.close("all")
    with pytest.raises(IndexError):
        plotting.plot_tree(booster, tree_index=999)


def test_create_tree_digraph_gated(booster):
    try:
        import graphviz  # noqa: F401
        has_graphviz = True
    except ImportError:
        has_graphviz = False
    if has_graphviz:
        g = plotting.create_tree_digraph(booster, 0)
        assert "yes" in g.source
    else:
        with pytest.raises(ImportError):
            plotting.create_tree_digraph(booster, 0)


def test_plot_loaded_model(booster, tmp_path):
    path = tmp_path / "m.txt"
    booster.save_model(str(path))
    loaded = lgb.Booster(model_file=str(path))
    ax = plotting.plot_importance(loaded)
    assert len(ax.patches) > 0
    plt.close("all")
    ax = plotting.plot_tree(loaded, tree_index=1)
    assert len(ax.texts) > 0
    plt.close("all")
