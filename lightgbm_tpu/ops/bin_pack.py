"""Bit-packed bin storage (device).

The per-iteration training cost model (docs/PERF_PROJECTION.md) is
dominated by re-reading the ``[F, N]`` bin tensor once per histogram
pass (~13 full-data passes per 255-leaf tree). When every feature fits
in few bins the uint8 storage wastes most of each byte: 4-bit nibbles
(``max_bins <= 15``) halve that dominant read, 2-bit pairs
(``max_bins <= 3``) quarter it — the TPU shape of the reference's
packed 4-bit bins (ref: include/LightGBM/bin.h Dense4bitsBin; the same
trick powers arXiv:1706.08359's GPU histogram kernels).

Layout — *split sections*, not interleaved nibbles: the padded row axis
(``n_pad = vpb * section``) is cut into ``vpb`` equal sections of
``section`` rows, and byte ``j`` of a feature's packed row carries rows
``j, j + section, ..., j + (vpb-1) * section`` in ascending bit
position.  Unpacking is therefore a concatenation of shifted/masked
*slices* — no lane interleave — which both XLA and Mosaic handle as
cheap vector ops, and a Pallas grid step that reads one byte block can
consume all of its nibbles by pairing it with ``vpb`` gh/row-leaf
blocks taken at ``section``-strided offsets (see
``pallas_histogram``'s packed kernels).

``PackedBins`` flows through the growers in the ``bins_fm`` argument
slot (like ``partition.SparseBins``); every consumer dispatches on
``isinstance``. The logical ``.shape`` property keeps
``bins_fm.shape[1]``-style call sites working unchanged.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# the packed row-section length is padded to a multiple of this so the
# Pallas kernels' byte blocks (1024 bytes/step) always tile a section
# exactly; it also keeps gh block offsets section-aligned
PACK_ALIGN = 2048


@jax.tree_util.register_pytree_node_class
class PackedBins:
    """Bit-packed ``[F, N]`` bin matrix.

    data: ``[F, section]`` uint8, ``vpb`` values per byte (2 = 4-bit,
    4 = 2-bit); ``num_data`` is the logical N (static pytree aux, so
    shapes stay trace-time constants).
    """

    def __init__(self, data, num_data: int, vpb: int):
        self.data = data
        self.num_data = int(num_data)
        self.vpb = int(vpb)

    @property
    def bits(self) -> int:
        return 8 // self.vpb

    @property
    def section(self) -> int:
        return self.data.shape[1]

    @property
    def shape(self):
        """Logical (num_features, num_data) — consumers that size row
        buffers by ``bins_fm.shape[1]`` keep working unchanged."""
        return (self.data.shape[0], self.num_data)

    @property
    def nbytes(self) -> int:
        return int(self.data.shape[0]) * int(self.data.shape[1])

    def tree_flatten(self):
        return (self.data,), (self.num_data, self.vpb)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1])


def pack_vpb(max_bins: int) -> int:
    """Values-per-byte the bin-id range admits: 4 (2-bit) when every id
    AND the out-of-range pad sentinel fit in 2 bits, 2 (4-bit) up to 15
    bins, else 1 (no packing). ``max_bins`` counts bins, so ids span
    [0, max_bins - 1] and the sentinel is ``max_bins`` itself."""
    if max_bins <= 3:
        return 4
    if max_bins <= 15:
        return 2
    return 1


def pack_bins_host(bins_fm: np.ndarray, max_bins: int):
    """Host-side pack of a ``[F, N]`` uint8 matrix; returns a host
    ``PackedBins`` (numpy data — callers ship with ``to_device``) or
    None when ``max_bins`` does not admit packing."""
    vpb = pack_vpb(max_bins)
    if vpb == 1:
        return None
    f, n = bins_fm.shape
    section = -(-n // vpb)
    section = -(-section // PACK_ALIGN) * PACK_ALIGN
    bits = 8 // vpb
    padded = np.zeros((f, vpb * section), np.uint8)
    padded[:, :n] = bins_fm
    data = np.zeros((f, section), np.uint8)
    for v in range(vpb):
        data |= padded[:, v * section:(v + 1) * section] << (bits * v)
    return PackedBins(data, n, vpb)


def to_device(pb: PackedBins) -> PackedBins:
    return PackedBins(jnp.asarray(pb.data), pb.num_data, pb.vpb)


# ---------------------------------------------------------------------------
# slab slicing (out-of-core streaming training, io/streaming.HostSlabBins)
def slab_align(max_bins: int) -> int:
    """Row-count alignment of a streaming slab: each slab is packed as
    its OWN section-aligned PackedBins (section a PACK_ALIGN multiple),
    so a slab whose row count is a multiple of ``vpb * PACK_ALIGN``
    packs with zero padding waste and every full slab shares one device
    shape (one compiled slab program, not one per slab)."""
    return pack_vpb(max_bins) * PACK_ALIGN


def slab_bounds(num_data: int, slab_rows: int, max_bins: int):
    """Cut ``num_data`` rows into section-aligned ``[lo, hi)`` slabs.
    ``slab_rows`` is rounded UP to the slab alignment; the tail slab
    keeps its natural (shorter) row count — consumers mask by
    ``num_data`` exactly like the resident packed path does."""
    align = slab_align(max_bins)
    rows = max(int(slab_rows), 1)
    rows = -(-rows // align) * align
    return [(lo, min(lo + rows, int(num_data)))
            for lo in range(0, int(num_data), rows)]


def pack_bins_range(bins_fm: np.ndarray, max_bins: int, lo: int, hi: int,
                    pack: bool = True):
    """Host storage of rows ``[lo, hi)`` as a streaming slab: a
    section-aligned ``PackedBins`` when ``pack`` and the bin width
    admits packing, else the raw uint8/uint16 row slice. The slab is
    self-contained — its section layout is its own, so every device
    consumer (histogram kernels, partition unpack) treats it exactly
    like a full resident matrix of ``hi - lo`` rows."""
    sub = np.ascontiguousarray(bins_fm[:, lo:hi])
    packed = pack_bins_host(sub, max_bins) if pack else None
    return packed if packed is not None else sub


def unpack_bins(pb: PackedBins):
    """``PackedBins -> [F, N]`` logical bins (jnp; XLA fuses the
    shift/mask into consumers, so the HBM read stays the packed
    bytes). The split-section layout makes this a concat of slices."""
    bits = pb.bits
    bmask = (1 << bits) - 1
    parts = [(pb.data >> (bits * v)) & bmask for v in range(pb.vpb)]
    return jnp.concatenate(parts, axis=1)[:, :pb.num_data]


def unpack_feature(pb: PackedBins, feature):
    """One logical [N] bin column (dynamic feature index): slice the
    packed row, then shift/mask per section — a streaming read of
    ``section`` bytes, not a gather."""
    bits = pb.bits
    bmask = (1 << bits) - 1
    row = jnp.take(pb.data, feature, axis=0).astype(jnp.int32)
    parts = [(row >> (bits * v)) & bmask for v in range(pb.vpb)]
    return jnp.concatenate(parts)[:pb.num_data]


def unpack_rows(pb: PackedBins, feat, rows):
    """Per-row gathered unpack: bins of feature ``feat[i]`` at row
    ``rows[i]`` (the packed analog of a ``bins[feat, rows]`` gather).
    Row r lives in byte ``r % section`` at bit position
    ``bits * (r // section)``."""
    bits = pb.bits
    bmask = (1 << bits) - 1
    sec = pb.section
    byte = pb.data[feat, rows % sec].astype(jnp.int32)
    return (byte >> (bits * (rows // sec))) & bmask
