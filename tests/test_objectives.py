"""Objective gradient/hessian correctness (vs finite differences of the
corresponding losses) — the strategy the reference validates through
training behavior in test_engine.py; here we check the math directly."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.objectives import create_objective


def _make(obj_name, label, weight=None, group=None, **params):
    cfg = Config.from_params({"objective": obj_name, **params})
    obj = create_objective(cfg)
    meta = Metadata(len(label))
    meta.set_label(np.asarray(label, np.float32))
    if weight is not None:
        meta.set_weight(weight)
    if group is not None:
        meta.set_group(group)
    obj.init(meta, len(label))
    return obj


def _fd_check(obj, loss_fn, score, rtol=1e-2, atol=1e-3):
    """Finite-difference check grad of sum(loss) wrt score."""
    g, h = obj.get_gradients(jnp.asarray(score, jnp.float32))
    g = np.asarray(g)
    eps = 1e-3
    for i in range(0, len(score), max(len(score) // 7, 1)):
        sp = score.copy()
        sp[i] += eps
        sm = score.copy()
        sm[i] -= eps
        fd = (loss_fn(sp) - loss_fn(sm)) / (2 * eps)
        assert g[i] == pytest.approx(fd, rel=rtol, abs=atol), f"idx {i}"


def test_l2_gradients():
    rng = np.random.RandomState(0)
    y = rng.randn(50)
    s = rng.randn(50)
    obj = _make("regression", y)
    # LightGBM convention: grad = score - label, hess = 1
    g, h = obj.get_gradients(jnp.asarray(s, jnp.float32))
    np.testing.assert_allclose(np.asarray(g), s - y, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), 1.0)


def test_l1_gradients():
    y = np.array([1.0, 2.0, 3.0])
    s = np.array([2.0, 1.0, 3.5])
    obj = _make("regression_l1", y)
    g, _ = obj.get_gradients(jnp.asarray(s, jnp.float32))
    np.testing.assert_allclose(np.asarray(g), [1.0, -1.0, 1.0])


def test_binary_gradients_fd():
    rng = np.random.RandomState(1)
    y01 = (rng.rand(40) > 0.5).astype(np.float64)
    s = rng.randn(40)
    obj = _make("binary", y01)

    def loss(sc):
        p = 1 / (1 + np.exp(-sc))
        return np.sum(-(y01 * np.log(p) + (1 - y01) * np.log(1 - p)))
    _fd_check(obj, loss, s)


def test_binary_boost_from_score():
    y = np.array([1, 1, 1, 0], np.float64)
    obj = _make("binary", y)
    init = obj.boost_from_score()
    assert 1 / (1 + np.exp(-init)) == pytest.approx(0.75, abs=1e-6)


def test_poisson_gradients_fd():
    rng = np.random.RandomState(2)
    y = rng.poisson(3.0, 30).astype(np.float64)
    s = rng.randn(30) * 0.5
    obj = _make("poisson", y)

    def loss(sc):
        return np.sum(np.exp(sc) - y * sc)
    g, _ = obj.get_gradients(jnp.asarray(s, jnp.float32))
    eps = 1e-4
    for i in range(0, 30, 5):
        sp, sm = s.copy(), s.copy()
        sp[i] += eps
        sm[i] -= eps
        fd = (loss(sp) - loss(sm)) / (2 * eps)
        assert np.asarray(g)[i] == pytest.approx(fd, rel=1e-2)


def test_quantile_gradients():
    y = np.array([0.0, 10.0])
    s = np.array([5.0, 5.0])
    obj = _make("quantile", y, alpha=0.9)
    g, _ = obj.get_gradients(jnp.asarray(s, jnp.float32))
    np.testing.assert_allclose(np.asarray(g), [0.1, -0.9], atol=1e-6)


def test_tweedie_gradients_fd():
    rng = np.random.RandomState(3)
    y = np.abs(rng.randn(30)) * 2
    s = rng.randn(30) * 0.3
    rho = 1.5
    obj = _make("tweedie", y)

    def loss(sc):
        return np.sum(-y * np.exp((1 - rho) * sc) / (1 - rho)
                      + np.exp((2 - rho) * sc) / (2 - rho))
    _fd_check(obj, loss, s, rtol=2e-2)


def test_multiclass_softmax_gradients():
    rng = np.random.RandomState(4)
    n, k = 30, 4
    y = rng.randint(0, k, n).astype(np.float64)
    scores = rng.randn(k, n)
    obj = _make("multiclass", y, num_class=k)
    g, h = obj.get_gradients_multi(jnp.asarray(scores, jnp.float32))
    e = np.exp(scores - scores.max(0, keepdims=True))
    p = e / e.sum(0, keepdims=True)
    onehot = (y[None, :] == np.arange(k)[:, None])
    np.testing.assert_allclose(np.asarray(g), p - onehot, rtol=1e-4,
                               atol=1e-5)
    # hessian factor K/(K-1) (ref: multiclass_objective.hpp:32 factor_)
    np.testing.assert_allclose(np.asarray(h), (k / (k - 1)) * p * (1 - p),
                               rtol=1e-4, atol=1e-5)


def test_weighted_gradients():
    y = np.array([1.0, 2.0])
    w = np.array([2.0, 0.5])
    s = np.array([0.0, 0.0])
    obj = _make("regression", y, weight=w)
    g, h = obj.get_gradients(jnp.asarray(s, jnp.float32))
    np.testing.assert_allclose(np.asarray(g), (s - y) * w, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(h), w, rtol=1e-6)


def test_lambdarank_gradients_direction():
    # 2 queries; within each, doc with higher label should get negative
    # gradient (pushed up) when scores are flat
    y = np.array([0, 2, 0, 1], np.float64)
    group = np.array([2, 2])
    obj = _make("lambdarank", y, group=group)
    s = np.zeros(4, np.float32)
    g, h = obj.get_gradients(jnp.asarray(s))
    g = np.asarray(g)
    assert g[1] < 0 < g[0]
    assert g[3] < 0 < g[2]
    assert np.all(np.asarray(h) >= 0)


def test_rank_xendcg_gradients_sum_zero_per_query():
    y = np.array([0, 1, 2, 0, 3, 1], np.float64)
    group = np.array([3, 3])
    obj = _make("rank_xendcg", y, group=group)
    s = np.random.RandomState(5).randn(6).astype(np.float32)
    g, h = obj.get_gradients(jnp.asarray(s))
    g = np.asarray(g)
    assert abs(g[:3].sum()) < 1e-5
    assert abs(g[3:].sum()) < 1e-5
    # higher label, equal score -> more negative gradient
    assert g[4] == np.min(g[3:])


@pytest.mark.parametrize("alpha", [0.1, 0.5, 0.9])
@pytest.mark.parametrize("weighted", [False, True])
def test_percentile_renew_traced_matches_host(alpha, weighted):
    """The traced percentile renewal (now the ONE implementation both
    the fused and host paths run) must agree with the f64 host-loop
    oracle `_renew_by_percentile_host` on identical inputs."""
    from lightgbm_tpu.objectives import (_percentile_renew_traced,
                                         _renew_by_percentile_host)
    from lightgbm_tpu.tree import Tree
    rng = np.random.RandomState(7)
    n, L = 500, 8
    residual = rng.randn(n).astype(np.float32)
    weights = (rng.rand(n).astype(np.float32) + 0.1 if weighted
               else np.ones(n, np.float32))
    row_leaf = rng.randint(0, L - 1, n)  # leaf L-1 left empty on purpose
    mask = (rng.rand(n) < 0.8).astype(np.float32)
    tree = Tree(L)
    tree.leaf_value = rng.randn(L)
    orig_empty = float(tree.leaf_value[L - 1])
    host = _renew_by_percentile_host(
        tree, residual.astype(np.float64), weights, row_leaf, mask, alpha)
    dev = np.asarray(_percentile_renew_traced(
        jnp.zeros(L, jnp.float32).at[L - 1].set(orig_empty),
        jnp.asarray(row_leaf), jnp.asarray(residual), jnp.asarray(weights),
        jnp.asarray(mask), alpha))
    np.testing.assert_allclose(dev[:L - 1], host.leaf_value[:L - 1],
                               rtol=1e-5, atol=1e-6)
    assert dev[L - 1] == pytest.approx(orig_empty)  # empty leaf untouched


def test_renew_tree_output_l1():
    """L1 leaf values become medians of residuals (ref: RenewTreeOutput)."""
    from lightgbm_tpu.tree import Tree
    y = np.array([0.0, 1.0, 2.0, 10.0, 11.0, 12.0])
    obj = _make("regression_l1", y)
    tree = Tree(2)
    tree.leaf_value = np.array([99.0, 98.0])
    row_leaf = np.array([0, 0, 0, 1, 1, 1])
    renewed = obj.renew_tree_output(tree, np.zeros(6, np.float32), row_leaf,
                                    np.ones(6, np.float32))
    assert renewed.leaf_value[0] == pytest.approx(1.0)
    assert renewed.leaf_value[1] == pytest.approx(11.0)
