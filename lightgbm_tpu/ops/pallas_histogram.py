"""Pallas TPU histogram kernel.

The performance-critical op (ref: the CUDA shared-memory histogram kernels,
src/treelearner/cuda/cuda_histogram_constructor.cu:21). The XLA one-hot
formulation materializes the [N, B] one-hot in HBM (~B x 4 bytes per
element); this kernel builds one-hot tiles in VMEM only, so HBM traffic
drops to one read of the bin matrix (1 byte/element) plus the gh vectors —
the bandwidth floor.

Layout: bins [F, N] (feature-major), gh [3, N] (grad, hess, count rows,
pre-masked), output hist [F, 3, B].

Grid: (feature_blocks, row_chunks); row chunks accumulate into the same
output block (TPU grids execute sequentially, minor-dim fastest).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_PRECISIONS = {
    "default": lax.Precision.DEFAULT,   # 1 bf16 MXU pass, f32 accumulation
    "high": lax.Precision.HIGH,         # 3 passes
    "highest": lax.Precision.HIGHEST,   # 6 passes (f32-faithful)
}


def resolve_precision(precise) -> lax.Precision:
    """bool (legacy) or config string -> lax.Precision."""
    if isinstance(precise, bool):
        return lax.Precision.HIGHEST if precise else lax.Precision.DEFAULT
    return _PRECISIONS[precise]


def _resolve_interpret(interpret) -> bool:
    """None = auto: interpret mode on CPU (tests exercise the kernels and
    their shard_map mesh wrappers without a chip), Mosaic on TPU."""
    if interpret is not None:
        return interpret
    from .histogram import cpu_backend
    return cpu_backend()


def _hist_kernel(bins_ref, gh_ref, out_ref, *, f_blk: int, max_bins: int,
                 precise: bool):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh = gh_ref[...]  # [3, C] f32
    chunk = gh.shape[1]
    prec = resolve_precision(precise)

    # static unroll: dynamic sublane indexing into a uint8 tile is not
    # supported by Mosaic; keep f_blk * chunk * B * 4 bytes under VMEM
    for f in range(f_blk):
        b = bins_ref[f, :].astype(jnp.int32)  # [C]
        onehot = (b[:, None] == lax.broadcasted_iota(
            jnp.int32, (chunk, max_bins), 1)).astype(jnp.float32)
        out_ref[f, :, :] += jax.lax.dot(gh, onehot, precision=prec)


def _multi_kernel(bins_ref, ghT_ref, rlT_ref, leafsel_ref, out_ref, *,
                  f_blk: int, group: int, max_bins: int, precise: bool):
    """One grid step: f_blk features' transposed one-hots ([group*B, R]
    per dot, built in VMEM) x a shared [R, 128] leaf-selected gh operand
    -> accumulate [f_blk*B, 128]."""
    ch = pl.program_id(1)

    @pl.when(ch == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rl = rlT_ref[...]      # [R, 1] int32 row -> leaf
    gh = ghT_ref[...]      # [R, 3] f32 (grad, hess, weight)
    r = rl.shape[0]
    lanes = lax.broadcasted_iota(jnp.int32, (r, 128), 1)
    csel = lanes % 3
    gsel = jnp.where(csel == 0, gh[:, 0:1],
                     jnp.where(csel == 1, gh[:, 1:2], gh[:, 2:3]))
    # leaf-block-diagonal gh operand: lane k = (leaf k//3, channel k%3)
    bop = jnp.where(rl == leafsel_ref[...], gsel, 0.0)  # [R, 128]
    prec = resolve_precision(precise)

    rows = group * max_bins
    riota = lax.broadcasted_iota(jnp.int32, (rows, r), 0)
    for q in range(f_blk // group):
        b_eff = jnp.zeros((rows, r), jnp.int32)
        for p in range(group):
            b_eff = jnp.where(
                riota // max_bins == p,
                bins_ref[q * group + p, :][None, :].astype(jnp.int32), b_eff)
        onehot_t = (b_eff == riota % max_bins).astype(jnp.float32)
        out_ref[0, q * rows:(q + 1) * rows, :] += jax.lax.dot(
            onehot_t, bop, precision=prec)


@functools.partial(jax.jit,
                   static_argnames=("max_bins", "num_slots", "row_chunk",
                                    "precise", "interpret"))
def hist_pallas_multi(bins_fm: jax.Array, ghT: jax.Array, row_leaf: jax.Array,
                      leaf_ids: jax.Array, *, max_bins: int, num_slots: int,
                      row_chunk: int = 2048, precise="highest",
                      interpret=None) -> jax.Array:
    """Histograms of up to `num_slots` leaves in ONE pass over the rows.

    The one-hot (bins) operand is leaf-independent, so packing the MXU's
    128 output columns with (leaf, channel) pairs builds J = 42 leaves'
    histograms for the cost of one (the reference instead loops leaves,
    touching each leaf's rows separately — cuda_histogram_constructor.cu:21
    one kernel per leaf). Rows route to their leaf's columns via a
    compare against row_leaf — the device analog of DataPartition.

    bins_fm: [F, N] uint8/16; ghT: [N, 3] f32 pre-masked (grad, hess, w);
    row_leaf: [N] int32; leaf_ids: [num_slots] int32 (pad with -2).
    Returns hist [num_slots, F, B, 3] f32.
    """
    num_features, n = bins_fm.shape
    assert num_slots * 3 <= 128, "num_slots capped at 42 by MXU columns"
    group = max(1, 128 // max_bins) if max_bins <= 128 else 1
    # bins tile first dim must be a multiple of 8 (Mosaic) AND of group
    # (the kernel consumes features in groups of `group` per dot)
    f_blk = group * 8 // math.gcd(group, 8)
    pad_f = (-num_features) % f_blk
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)),
                          constant_values=0)
    fp = bins_fm.shape[0]
    pad_n = (-n) % row_chunk
    if pad_n:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_n)),
                          constant_values=0)
        ghT = jnp.pad(ghT, ((0, pad_n), (0, 0)))  # zero gh: no contribution
        row_leaf = jnp.pad(row_leaf, (0, pad_n), constant_values=-1)
    npad = bins_fm.shape[1]

    # lane k holds leaf_ids[k//3]; lanes beyond 3*num_slots get sentinel -2
    # (never equals a row_leaf entry, which is >= 0 or -1 padding)
    k = jnp.arange(128)
    leafsel = jnp.where(k < 3 * num_slots,
                        leaf_ids[jnp.minimum(k // 3, num_slots - 1)],
                        -2).astype(jnp.int32)[None, :]

    fblocks = fp // f_blk
    rows = f_blk * max_bins
    grid = (fblocks, npad // row_chunk)
    out = pl.pallas_call(
        functools.partial(_multi_kernel, f_blk=f_blk, group=group,
                          max_bins=max_bins, precise=precise),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 3), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 128), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, 128), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fblocks, rows, 128), jnp.float32),
        interpret=_resolve_interpret(interpret),
    )(bins_fm, ghT, row_leaf[:, None].astype(jnp.int32), leafsel)
    # [fblocks, f_blk*B, 128] -> [F, B, J, 3] -> [J, F, B, 3]
    out = out[:, :, :3 * num_slots]
    out = out.reshape(fp, max_bins, num_slots, 3)
    out = jnp.moveaxis(out, 2, 0)
    return out[:, :num_features]


def _multi_kernel_int8(bins_ref, ghT_ref, rlT_ref, leafsel_ref, out_ref, *,
                       f_blk: int, group: int, max_bins: int):
    """Integer twin of _multi_kernel: int8 one-hot x int8 leaf-selected
    quantized (grad, hess, weight) -> int32 accumulation. This is the MXU
    shape of the reference's quantized histograms (ref:
    gradient_discretizer.hpp:23 int8 packed gradients, bin.h:351-421
    ConstructHistogramInt* variants) — exact integer arithmetic at twice
    the bf16 MXU rate."""
    ch = pl.program_id(1)

    @pl.when(ch == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rl = rlT_ref[...]      # [R, 1] int32 row -> leaf
    gh = ghT_ref[...]      # [R, 3] int8 (g_int, h_int, weight)
    r = rl.shape[0]
    lanes = lax.broadcasted_iota(jnp.int32, (r, 128), 1)
    csel = lanes % 3
    gsel = jnp.where(csel == 0, gh[:, 0:1],
                     jnp.where(csel == 1, gh[:, 1:2], gh[:, 2:3]))
    bop = jnp.where(rl == leafsel_ref[...], gsel,
                    jnp.int8(0)).astype(jnp.int8)  # [R, 128]

    rows = group * max_bins
    riota = lax.broadcasted_iota(jnp.int32, (rows, r), 0)
    for q in range(f_blk // group):
        b_eff = jnp.zeros((rows, r), jnp.int32)
        for p in range(group):
            b_eff = jnp.where(
                riota // max_bins == p,
                bins_ref[q * group + p, :][None, :].astype(jnp.int32), b_eff)
        onehot_t = (b_eff == riota % max_bins).astype(jnp.int8)
        out_ref[0, q * rows:(q + 1) * rows, :] += jax.lax.dot_general(
            onehot_t, bop, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("max_bins", "num_slots", "row_chunk",
                                    "interpret"))
def hist_pallas_multi_int8(bins_fm: jax.Array, ghT_i8: jax.Array,
                           row_leaf: jax.Array, leaf_ids: jax.Array, *,
                           max_bins: int, num_slots: int,
                           row_chunk: int = 2048,
                           interpret=None) -> jax.Array:
    """Quantized multi-leaf histograms: one pass, int32 accumulation.

    ghT_i8: [N, 3] int8 (quantized grad, quantized hess, {0,1} weight),
    pre-masked. Returns [num_slots, F, B, 3] int32 — callers scale by
    (g_scale, h_scale, 1) to recover the f32 statistics. Safe for
    N < 2^31 / (num_grad_quant_bins): |g_int| <= bins/2, so per-bin int32
    sums cannot overflow at any realistic scale.
    """
    num_features, n = bins_fm.shape
    assert num_slots * 3 <= 128, "num_slots capped at 42 by MXU columns"
    group = max(1, 128 // max_bins) if max_bins <= 128 else 1
    f_blk = group * 8 // math.gcd(group, 8)
    pad_f = (-num_features) % f_blk
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)), constant_values=0)
    fp = bins_fm.shape[0]
    pad_n = (-n) % row_chunk
    if pad_n:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_n)), constant_values=0)
        ghT_i8 = jnp.pad(ghT_i8, ((0, pad_n), (0, 0)))
        row_leaf = jnp.pad(row_leaf, (0, pad_n), constant_values=-1)
    npad = bins_fm.shape[1]

    k = jnp.arange(128)
    leafsel = jnp.where(k < 3 * num_slots,
                        leaf_ids[jnp.minimum(k // 3, num_slots - 1)],
                        -2).astype(jnp.int32)[None, :]

    fblocks = fp // f_blk
    rows = f_blk * max_bins
    grid = (fblocks, npad // row_chunk)
    out = pl.pallas_call(
        functools.partial(_multi_kernel_int8, f_blk=f_blk, group=group,
                          max_bins=max_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 3), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 128), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, 128), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fblocks, rows, 128), jnp.int32),
        interpret=_resolve_interpret(interpret),
    )(bins_fm, ghT_i8, row_leaf[:, None].astype(jnp.int32), leafsel)
    out = out[:, :, :3 * num_slots]
    out = out.reshape(fp, max_bins, num_slots, 3)
    out = jnp.moveaxis(out, 2, 0)
    return out[:, :num_features]


def hist_multi_xla(bins_fm, ghT, row_leaf, leaf_ids, *, max_bins: int,
                   num_slots: int) -> jax.Array:
    """XLA fallback (CPU tests + CPU bench): ALL leaf slots in one
    contraction per feature. The bin one-hot is built once and dotted
    against the per-slot masked channels packed side-by-side — the
    former per-slot loop rebuilt the one-hot `num_slots` times, roughly
    doubling the work and unrolling W separate passes into the HLO."""
    from jax import lax

    from .histogram import _hist_all_features

    s = num_slots
    n = ghT.shape[0]
    f = bins_fm.shape[0]

    def hist_of(bins_part, gh_part, leaf_part):
        # [S, c] row->slot selection; ghT channels are pre-masked
        # (g*w, h*w, w) with w in {0,1}, so multiplying by the selector
        # alone reproduces the old per-slot mask exactly
        sel = (leaf_part[None, :] == leaf_ids[:, None]).astype(jnp.float32)
        ghs = (sel[:, :, None] * gh_part[None, :, :])          # [S, c, 3]
        ghs = jnp.moveaxis(ghs, 0, 1).reshape(-1, s * 3)       # [c, S*3]
        # _hist_all_features is generic over the trailing dim
        return _hist_all_features(bins_part, ghs, max_bins, jnp.float32)

    chunk = 131072  # bounds the [c, S*3] packed operand to ~64MB at S=42
    if n > chunk:
        pad = (-n) % chunk
        # padded rows contribute nothing: their gh channels are zero and
        # their leaf sentinel -7 matches no slot (invalid slots are -2)
        ghp = jnp.pad(ghT, ((0, pad), (0, 0)))
        binsp = jnp.pad(bins_fm, ((0, 0), (0, pad)))
        leafp = jnp.pad(row_leaf, (0, pad), constant_values=-7)
        nchunk = (n + pad) // chunk
        ghc = ghp.reshape(nchunk, chunk, 3)
        binsc = jnp.swapaxes(binsp.reshape(f, nchunk, chunk), 0, 1)
        leafc = leafp.reshape(nchunk, chunk)

        def one_chunk(acc, inputs):
            b, g, lf = inputs
            return acc + hist_of(b, g, lf), None

        init = jnp.zeros((f, max_bins, s * 3), jnp.float32)
        hist, _ = lax.scan(one_chunk, init, (binsc, ghc, leafc))
    else:
        hist = hist_of(bins_fm, ghT, row_leaf)
    hist = hist.reshape(f, max_bins, s, 3)
    return jnp.moveaxis(hist, 2, 0)  # [S, F, B, 3]


def hist_multi(bins_fm, ghT, row_leaf, leaf_ids, *, max_bins: int,
               num_slots: int, impl: str = "xla",
               precision: str = "highest") -> jax.Array:
    if impl == "pallas":
        return hist_pallas_multi(bins_fm, ghT, row_leaf, leaf_ids,
                                 max_bins=max_bins, num_slots=num_slots,
                                 precise=precision)
    # XLA path (CPU tests): f32 dots are exact regardless of precision
    return hist_multi_xla(bins_fm, ghT, row_leaf, leaf_ids,
                          max_bins=max_bins, num_slots=num_slots)


@functools.partial(jax.jit,
                   static_argnames=("max_bins", "f_blk", "row_chunk",
                                    "precise", "interpret"))
def hist_pallas(bins_fm: jax.Array, gh3: jax.Array, *, max_bins: int,
                f_blk: int = 8, row_chunk: int = 0,
                precise="highest", interpret=None) -> jax.Array:
    """bins_fm [F, N] uint8/uint16, gh3 [3, N] f32 (pre-masked) ->
    hist [F, B, 3] f32."""
    num_features, n = bins_fm.shape
    if row_chunk == 0:
        # keep the f_blk unrolled one-hot buffers under ~8 MB of VMEM
        budget = 8 * 1024 * 1024 // (f_blk * max_bins * 4)
        row_chunk = max(512, min(2048, (budget // 512) * 512))
    # pad N to a multiple of row_chunk (pad bins with max_bins -> one-hot
    # of the padded rows is all-zero, and gh pads with zeros anyway)
    pad_n = (-n) % row_chunk
    if pad_n:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_n)),
                          constant_values=max_bins)
        gh3 = jnp.pad(gh3, ((0, 0), (0, pad_n)))
    pad_f = (-num_features) % f_blk
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)),
                          constant_values=max_bins)
    fp = bins_fm.shape[0]
    npad = bins_fm.shape[1]

    grid = (fp // f_blk, npad // row_chunk)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, f_blk=f_blk, max_bins=max_bins,
                          precise=precise),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, row_chunk), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f_blk, 3, max_bins), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fp, 3, max_bins), jnp.float32),
        interpret=_resolve_interpret(interpret),
    )(bins_fm, gh3)
    # [F, 3, B] -> [F, B, 3] to match the XLA path's layout
    return jnp.swapaxes(out[:num_features], 1, 2)
