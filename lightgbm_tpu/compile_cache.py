"""Persistent XLA compile-cache policy — warm start as the default.

The one real-silicon datapoint (BENCH_r02) paid 108.9 s of
warmup+compile before the first useful iteration and CPU runs pay
~29 s, yet until this module the persistent compilation cache existed
only in ``hostenv.cpu_child_env`` (driver helper children) and the test
conftest: a real training or serving process recompiled every program
from scratch. This module is the ONE place that policy lives now, and
every program-entry boundary routes through it:

- ``Booster.__init__`` / ``engine.train`` / ``engine.cv`` (training),
- ``serve.ModelRegistry`` / ``serve_file`` (serving),
- ``bench.py`` measurement children and ``hostenv.cpu_child_env``.

``configure(mode, cache_dir)`` arms ``jax.config.jax_compilation_cache_dir``:

- ``auto`` (the ``tpu_compile_cache`` default): enable the cache at the
  default directory unless something already configured one — an
  existing ``jax.config`` setting or ``JAX_COMPILATION_CACHE_DIR`` env
  is respected, so tests/conftest and operator overrides win.
- ``on``: force the cache to ``cache_dir`` (or the default directory),
  replacing any prior setting.
- ``off``: never touch jax config (an already-armed cache is left
  alone — "off" opts this entry point out, it does not disarm others).

Directory resolution: explicit ``cache_dir`` argument >
``LGBM_TPU_COMPILE_CACHE_DIR`` env > ``JAX_COMPILATION_CACHE_DIR`` env >
the repo-local ``.jax_cache`` (shared with the driver's helper children
via ``hostenv``).

Donation policy: buffer donation SEGFAULTS on executables deserialized
from the persistent compilation cache on jaxlib<=0.4.36. That guard
used to live inline in ``obs/xla.instrumented_jit``; it is now the
version-gated ``donation_allowed()`` here, shared by every program
boundary that donates — newer jaxlibs keep donation even with the
cache armed, affected ones drop it (donation is a memory optimisation
only), and ``LGBM_TPU_NO_DONATE`` force-drops regardless.

Hygiene: the cache directory grows without bound on a long-lived host
(every shape bucket of every model adds entries). ``prune_cache()`` is
a best-effort LRU prune to the ``LGBM_TPU_COMPILE_CACHE_MAX_BYTES``
budget (default 4 GiB; <=0 disables), run at most once per directory
per process, and ONLY for directories this framework owns (our knob /
``LGBM_TPU_COMPILE_CACHE_DIR`` / the repo-local default) — an
inherited ``JAX_COMPILATION_CACHE_DIR`` may be shared with other
projects and is never deleted from. A pruned entry is only a future
cache miss — XLA regenerates it — so pruning can never break a
running process.
"""

from __future__ import annotations

import os
from typing import Optional

# first jaxlib where donating into an executable deserialized from the
# persistent compilation cache no longer segfaults (the 0.4.36 crash —
# see obs/xla.py history and the tier-1 conftest notes)
DONATION_SAFE_JAXLIB = (0, 4, 37)

_DEFAULT_MAX_BYTES = 4 << 30

# modes this module accepts for tpu_compile_cache
_MODES = ("auto", "on", "off")


def repo_cache_dir() -> str:
    """The repo-local ``.jax_cache`` shared with hostenv's children."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache")


def default_cache_dir() -> str:
    """Cache directory resolution (env overrides > repo-local)."""
    return (os.environ.get("LGBM_TPU_COMPILE_CACHE_DIR")
            or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or repo_cache_dir())


def cache_active() -> bool:
    """True when a persistent compilation cache is configured — via
    ``jax.config`` (which also absorbs ``JAX_COMPILATION_CACHE_DIR``)
    or, before jax is importable, the env var alone."""
    try:
        import jax
        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:
        return bool(os.environ.get("JAX_COMPILATION_CACHE_DIR"))


def _jaxlib_version() -> tuple:
    try:
        import jaxlib
        return tuple(int(p) for p in
                     str(jaxlib.__version__).split(".")[:3])
    except Exception:
        return (0, 0, 0)


def donation_allowed() -> bool:
    """THE donation policy for every program boundary (obs/xla's
    ``instrumented_jit`` consults this before passing donate_argnums):
    donation is dropped when ``LGBM_TPU_NO_DONATE`` is set, or when the
    persistent cache is armed on a jaxlib where donating into a
    cache-deserialized executable segfaults (<= 0.4.36)."""
    if os.environ.get("LGBM_TPU_NO_DONATE"):
        return False
    if not cache_active():
        return True
    return _jaxlib_version() >= DONATION_SAFE_JAXLIB


def configure(mode: str = "auto", cache_dir: Optional[str] = None) -> bool:
    """Arm the persistent compilation cache per the module docstring.

    Returns True when a cache is active after the call (whether this
    call armed it or an earlier configuration did). Best-effort: any
    jax config failure (too-old jax, read-only filesystem) returns
    False rather than raising — cold compiles are slow, not wrong.
    """
    mode = str(mode or "auto").lower()
    if mode not in _MODES:
        from . import log
        log.warning(f"tpu_compile_cache={mode!r} is not one of {_MODES}; "
                    "treating as 'auto'")
        mode = "auto"
    if mode == "off":
        return False
    if mode == "auto" and cache_active():
        return True
    path = cache_dir or default_cache_dir()
    # only ever prune a directory THIS framework owns: one named by our
    # knob/env or the repo-local default. A user-managed
    # JAX_COMPILATION_CACHE_DIR (possibly shared across projects) is
    # used as-is but never deleted from.
    owned = (cache_dir is not None
             or bool(os.environ.get("LGBM_TPU_COMPILE_CACHE_DIR"))
             or path == repo_cache_dir())
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything, however small/fast: warm start must make
        # compile_s_total ~0, and a skipped tiny program would still
        # recompile every process (hostenv learned this the hard way
        # with driver-timeout rounds 3+4)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        return False
    if owned:
        prune_cache_once(path)
    return True


_pruned_once: set = set()  # dirs already pruned in this process


def prune_cache_once(cache_dir: str) -> int:
    """``prune_cache``, at most once per directory per process — the
    hygiene pass costs a full os.walk/stat sweep, which must not repeat
    for every Booster a sweep or cv() constructs."""
    if cache_dir in _pruned_once:
        return 0
    _pruned_once.add(cache_dir)
    return prune_cache(cache_dir)


def cache_size_bytes(cache_dir: Optional[str] = None) -> int:
    """Total bytes under the cache directory (0 when absent)."""
    root = cache_dir or default_cache_dir()
    total = 0
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            try:
                total += os.stat(os.path.join(dirpath, name)).st_size
            except OSError:
                continue
    return total


def prune_cache(cache_dir: Optional[str] = None,
                max_bytes: Optional[int] = None) -> int:
    """Best-effort LRU prune of the cache directory to `max_bytes`
    (default ``LGBM_TPU_COMPILE_CACHE_MAX_BYTES``, 4 GiB; <=0 =
    unbounded). Oldest entries — by last access where the filesystem
    tracks it, else last modification — go first. Returns the bytes
    removed. Never raises: a prune failure only means a bigger cache."""
    if max_bytes is None:
        try:
            max_bytes = int(os.environ.get(
                "LGBM_TPU_COMPILE_CACHE_MAX_BYTES", _DEFAULT_MAX_BYTES))
        except ValueError:
            max_bytes = _DEFAULT_MAX_BYTES
    if max_bytes <= 0:
        return 0
    root = cache_dir or default_cache_dir()
    entries = []  # (lru_stamp, size, path)
    total = 0
    try:
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in filenames:
                path = os.path.join(dirpath, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                entries.append((max(st.st_atime, st.st_mtime),
                                st.st_size, path))
                total += st.st_size
    except OSError:
        return 0
    if total <= max_bytes:
        return 0
    removed = 0
    entries.sort()  # oldest first
    for _stamp, size, path in entries:
        if total - removed <= max_bytes:
            break
        try:
            os.unlink(path)
        except OSError:
            continue
        removed += size
    return removed
