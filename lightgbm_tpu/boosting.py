"""Boosting loop: GBDT / DART / RF with bagging & GOSS sampling.

TPU-native re-architecture of the reference boosting layer
(ref: src/boosting/gbdt.cpp:60 Init, :353 TrainOneIter, :328
BoostFromAverage; dart.hpp:24; rf.hpp:26; bagging.hpp:15; goss.hpp:19).

The per-iteration pipeline (gradients -> sampling -> tree growth -> score
update) runs as XLA programs on device; tree records stay on device until
the host needs them (model save / prediction / leaf renewal), keeping the
training loop free of per-iteration synchronization — the TPU analog of
keeping boosting_on_gpu_ fully device-resident (gbdt.cpp:111).

Reference order of operations preserved (gbdt.cpp:353-461):
  BoostFromAverage -> gradients -> bagging -> Train -> RenewTreeOutput ->
  Shrinkage -> UpdateScore -> AddBias(first iteration only).
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .config import Config
from .dataset import BinnedDataset
from .learner import grow_tree, grow_tree_waved, replay_tree
from .obs import health as obs_health
from .obs import xla as obs_xla
from .obs.export import global_flusher
from .obs.flightrec import global_flightrec
from .obs.profile import global_profile
from .resilience import faults as faults_mod
from .obs.metrics import global_metrics
from .obs.trace import global_tracer
from .timer import global_timer  # noqa: F401  (compat facade re-export)
from .objectives import ObjectiveFunction, create_objective
from .ops import histogram as hist_ops
from .ops.split import FeatureMeta, SplitHyperParams, leaf_output
from .tree import Tree

K_EPSILON = 1e-35


def _multi_value(value):
    """Multi-value param -> list of floats, accepting both the Python list
    form and the reference's comma-separated string form
    (ref: config.h multi-value params like monotone_constraints)."""
    if value is None:
        return None
    if isinstance(value, str):
        value = [v for v in value.split(",") if v.strip()]
    vals = [float(v) for v in value]
    return vals if vals else None


def _tree_record_to_host(record) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in record._asdict().items()}


def _nonfinite_counts(grad, hess, scores):
    """Traced [3] int32 nonfinite-entry counts of (grad, hess, scores) —
    the per-iteration NaN/Inf sentinel payload (obs/health.py). Pure
    reductions: folding this into a fused program changes none of the
    training math, so models are bit-identical with the sentinel on."""
    def cnt(x):
        if x is None:
            return jnp.int32(0)
        return jnp.sum(~jnp.isfinite(x)).astype(jnp.int32)
    return jnp.stack([cnt(grad), cnt(hess), cnt(scores)])


def _stack_class_records(recs):
    """[K] per-class TreeArrays -> one TreeArrays with a leading class
    axis (traced; used inside the fused programs)."""
    if len(recs) == 1:
        return jax.tree_util.tree_map(lambda x: x[None], recs[0])
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *recs)


def _records_to_host(recs):
    """List of per-iteration records -> host arrays with a leading
    iteration axis, in ONE device->host transfer set (a single-element
    list skips the device-side stack entirely)."""
    if len(recs) == 1:
        host = jax.device_get(recs[0])
        return jax.tree_util.tree_map(lambda x: x[None], host)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *recs)
    return jax.device_get(stacked)


class GBDT:
    """Gradient Boosted Decision Trees (ref: src/boosting/gbdt.h:38)."""

    boosting_type = "gbdt"

    def __init__(self, config: Config, train_set: BinnedDataset,
                 objective: Optional[ObjectiveFunction] = None):
        self.config = config
        self.train_set = train_set
        self.objective = objective
        self.num_data = train_set.num_data
        self.num_class = max(config.num_class, 1)
        self.num_tree_per_iteration = (
            objective.num_model_per_iteration if objective is not None
            else self.num_class)
        self.shrinkage_rate = config.learning_rate
        self.iter = 0
        # masked pad rows appended to the row tensors so they divide a
        # device mesh (parallel/data_parallel._pad_and_shard_rows);
        # num_data stays the REAL row count throughout
        self._row_pad = 0
        # host trees (materialized lazily from device records on the fast
        # path; populated directly on the slow path)
        self._host_models: List[List[Tree]] = []
        self._device_records: List = []  # per fast-path iter: TreeArrays [K,...]
        self.init_scores = [0.0] * self.num_tree_per_iteration
        self._init_done = False

        if objective is not None:
            objective.init(train_set.metadata, self.num_data)

        # device-side constants. Bit-packed bin storage (tpu_bin_pack,
        # ops/bin_pack.py): when the bin-id range fits 4-bit nibbles the
        # device tensor ships packed and every histogram/partition
        # consumer unpacks on the fly — the packed bytes are what each
        # of the ~13 per-iteration full-data passes actually reads.
        # Out-of-core streaming (tpu_stream, io/streaming.py): the bin
        # tensor instead stays HOST-resident as section-aligned slabs
        # and `bins_fm` is the HostSlabBins plan — the streamed growers
        # feed it to the device wave-by-wave, double-buffered.
        self._bin_pack_vpb = 1
        self._stream = self._resolve_stream(train_set)
        self._stream_progs: Dict = {}
        self._stream_next_bins = None  # cross-iteration upload prefetch
        if self._stream is not None:
            self.bins_fm = self._stream
            self._bin_pack_vpb = self._stream.vpb
        else:
            packed = self._maybe_pack_bins(train_set)
            if packed is not None:
                self.bins_fm = packed
                self._bin_pack_vpb = packed.vpb
            else:
                self.bins_fm = train_set.device_bins()
        # EFB (ref: dataset.cpp:251): bins_fm is bundled [G, N] storage;
        # the growers decode through this triple (None when unbundled)
        self._bundle = train_set.device_bundle()
        self._num_bundle_bins = (train_set.bundle_info.num_bundle_bins
                                 if train_set.bundle_info is not None else 0)
        # sparse row-wise COO storage (multi_val_sparse_bin.hpp analog):
        # bins_fm is then a SparseBins pytree, histogram passes run
        # O(nnz) segment-sums
        self._sparse_shape = None
        self._quant_enabled = bool(config.use_quantized_grad)
        if train_set.sparse_coo is not None:
            self._sparse_shape = (train_set.num_features,
                                  train_set.num_data)
            if self._quant_enabled:
                import warnings
                warnings.warn("use_quantized_grad is not supported with "
                              "sparse COO histograms; using f32")
                self._quant_enabled = False
        num_bins, missing, default_bin, is_cat = \
            train_set.feature_meta_arrays()
        mono = np.zeros(train_set.num_features, np.int8)
        mc_vals = _multi_value(config.monotone_constraints)
        if mc_vals is not None:
            mc = np.asarray(mc_vals, np.int8)
            for j, col in enumerate(train_set.used_features):
                if col < len(mc):
                    mono[j] = mc[col]
        penalty = np.ones(train_set.num_features, np.float32)
        fc_vals = _multi_value(config.feature_contri)
        if fc_vals is not None:
            fc = np.asarray(fc_vals, np.float32)
            for j, col in enumerate(train_set.used_features):
                if col < len(fc):
                    penalty[j] = fc[col]

        # CEGB per-feature penalties (ref: cost_effective_gradient_boosting
        # .hpp DeltaGain). Coupled penalties are charged on a feature's
        # first use in the model; the used-set is refreshed between
        # iterations (the reference updates mid-tree). Lazy penalties are
        # charged per row in the leaf (upper bound of the reference's
        # per-(row, feature) first-query tracking).
        def _per_feature(cfg_list):
            out = np.zeros(train_set.num_features, np.float32)
            vals = _multi_value(cfg_list)
            if vals is not None:
                arr = np.asarray(vals, np.float32)
                for j, col in enumerate(train_set.used_features):
                    if col < len(arr):
                        out[j] = arr[col]
            return out
        self._cegb_coupled = _per_feature(config.cegb_penalty_feature_coupled)
        self._cegb_lazy = _per_feature(config.cegb_penalty_feature_lazy)
        self._cegb_used = np.zeros(train_set.num_features, bool)
        self._has_cegb_coupled = bool(np.any(self._cegb_coupled != 0))

        self.feature_meta = FeatureMeta(
            num_bins=jnp.asarray(num_bins),
            missing_type=jnp.asarray(missing),
            default_bin=jnp.asarray(default_bin),
            is_categorical=jnp.asarray(is_cat),
            monotone=jnp.asarray(mono),
            penalty=jnp.asarray(penalty),
            cegb_feat=jnp.asarray(
                config.cegb_tradeoff * self._cegb_coupled),
            cegb_lazy=jnp.asarray(config.cegb_tradeoff * self._cegb_lazy),
        )
        self.hp = SplitHyperParams.from_config(config)
        self.max_depth = jnp.asarray(config.max_depth, jnp.int32)
        self._static = dict(
            num_leaves=int(config.num_leaves),
            max_bins=int(train_set.max_bins),
            # intermediate/advanced monotone methods: exact pairwise
            # leaf-box bounds (split.compute_box_bounds) replace the
            # basic midpoint propagation
            mono_pairwise=bool(
                np.any(mono != 0)
                and str(config.monotone_constraints_method)
                in ("intermediate", "advanced")),
        )
        self._forced = self._parse_forced_splits()
        self._interaction_groups = self._parse_interaction_constraints()

        # scores [K, N] on device (ScoreUpdater analog, score_updater.hpp:22)
        scores = np.zeros((self.num_tree_per_iteration, self.num_data),
                          np.float32)
        meta_init = train_set.metadata.init_score
        self._has_init_score = meta_init is not None
        if self._has_init_score:
            init = np.asarray(meta_init, np.float64)
            if init.size == self.num_data * self.num_tree_per_iteration:
                scores += init.reshape(self.num_tree_per_iteration,
                                       self.num_data, order="C").astype(
                    np.float32)
            else:
                scores += init.reshape(1, -1).astype(np.float32)
        self.scores = jnp.asarray(scores)

        # per-iteration device records not yet materialized into host Trees
        self._pending: List[List] = []  # [(record, row_leaf), ...] per iter
        self._rng = np.random.RandomState(config.seed)
        self._feature_rng = np.random.RandomState(config.feature_fraction_seed)
        self._bagging_key = jax.random.PRNGKey(config.bagging_seed)
        self._sample_mask = jnp.ones(self.num_data, jnp.float32)
        self._grad_scale = None  # GOSS amplification, set per iter

        # training-health sentinels (obs/health.py; tpu_health knob).
        # Resolved BEFORE the grower build: the fused programs emit the
        # sentinel outputs only when armed, so the knob is a build-time
        # program-shape decision (off = byte-identical programs).
        mode = str(config.tpu_health).lower()
        if mode in ("off", "0", "false", "none", ""):
            mode = "off"
        elif mode in ("warn", "warning"):
            mode = "warn"
        elif mode in ("error", "raise", "strict"):
            mode = "error"
        else:
            raise ValueError(
                f"tpu_health={config.tpu_health!r} is not one of "
                "off/warn/error")
        self._health_mode = mode
        self._health_armed = mode != "off"
        self._health_every = max(int(config.tpu_health_every), 1)
        self._health_tick = 0

        # device-time profiling window (obs/profile.py; tpu_profile
        # knob; LGBM_TPU_PROFILE env overrides for driver-side arming)
        pmode = str(os.environ.get("LGBM_TPU_PROFILE", "")
                    or config.tpu_profile).lower()
        if pmode in ("off", "0", "false", "none", ""):
            pmode = "off"
        elif pmode not in ("window", "bench"):
            raise ValueError(
                f"tpu_profile={config.tpu_profile!r} is not one of "
                "off/window/bench")
        self._profile_mode = pmode
        self._profile_left = max(int(config.tpu_profile_window), 1)
        self._profile_started = False
        self._health_vec = None           # device [3] nonfinite counts
        self._health_pending_record = None  # slow-path replicated record

        # valid-set state precedes _build_grow: the memory model it
        # publishes accounts registered valid sets
        self._valid_sets: List = []
        self._valid_scores: List[np.ndarray] = []
        # grown-tree jit (shared across iterations; one XLA program per tree)
        self._build_grow(hist_ops.resolve_impl(config.tpu_hist_impl))
        # slow-path twin of the fused program's score update: the
        # multiply and the add must live in ONE program so XLA makes the
        # same FMA-contraction choice as inside the fused iteration —
        # split across two jits the add rounds separately and the paths
        # drift by one ulp, which flips sign-function gradients (L1
        # family) on rows sitting at score == label
        self._update_score_shrunk = jax.jit(
            lambda score, lv, lr, row_leaf: score + (lv * lr)[row_leaf])

    def _maybe_pack_bins(self, binned):
        """Bit-packed device bins for `binned`, or None when ineligible
        (knob off, bins too wide, EFB/COO storage, or a sharded layout —
        the mesh paths shard raw rows)."""
        cfg = self.config
        if str(cfg.tpu_bin_pack) in ("off", "0", "false", "False"):
            return None
        if cfg.tree_learner != "serial" or int(cfg.tpu_num_shards or 0) > 1:
            return None
        if binned.sparse_coo is not None or binned.bundle_info is not None:
            return None
        from .ops import bin_pack as bp
        host = bp.pack_bins_host(np.asarray(binned.bins_fm),
                                 int(binned.max_bins))
        return bp.to_device(host) if host is not None else None

    def _stream_ineligible(self, train_set) -> Optional[str]:
        """Why out-of-core streaming cannot serve this configuration,
        or None when it can: the shared config-level gate list
        (obs/memory.stream_config_ineligible — the same predicate
        preflight's recommendation screens with) plus the storage-level
        gates only a constructed dataset knows. The streamed grower is
        the waved grower's twin over dense (optionally packed)
        serial/data-parallel storage; everything else keeps the
        resident paths."""
        if train_set.bundle_info is not None:
            return "EFB-bundled storage is not slab-sliceable"
        if train_set.sparse_coo is not None:
            return "COO sparse storage streams by nnz, not row slabs"
        from .obs import memory as obs_memory
        return obs_memory.stream_config_ineligible(
            self.config, num_class=self.num_tree_per_iteration)

    def _resolve_stream(self, train_set):
        """Resolve ``tpu_stream`` into a ``HostSlabBins`` plan or None.

        auto: stream only when the analytic memory model says resident
        training does NOT fit device capacity (ROADMAP item 1's
        "recommend streaming instead of failing"); capacity unknown
        (CPU without LGBM_TPU_HBM_BYTES) keeps resident. on: force
        streaming, raising on ineligible configurations. The slab size
        comes from ``tpu_stream_slab_rows`` or the memory model's auto
        sizing (obs/memory.stream_auto_slab_rows)."""
        cfg = self.config
        mode = str(cfg.tpu_stream).lower()
        if mode in ("off", "0", "false", "none", ""):
            return None
        if mode in ("on", "true", "1"):
            forced = True
        elif mode == "auto":
            forced = False
        else:
            raise ValueError(f"tpu_stream={cfg.tpu_stream!r} is not one "
                             "of auto/on/off")
        why = self._stream_ineligible(train_set)
        if why is not None:
            if forced:
                raise ValueError(f"tpu_stream=on: {why}")
            return None
        from .obs import memory as obs_memory
        n = int(train_set.num_data)
        f_storage = int(train_set.bins_fm.shape[0])
        kw = obs_memory._resolve_train_knobs(
            cfg, n, f_storage, self.num_tree_per_iteration)
        kw["valid_rows"] = []
        cap = obs_memory.device_capacity_bytes()
        if not forced:
            if str(cfg.tpu_preflight).lower() in ("off", "0", "false",
                                                  "none"):
                return None  # auto-streaming IS a preflight action
            if cap is None:
                return None
            resident = obs_memory.train_memory_model(**kw)
            if resident["peak_bytes"] <= cap:
                return None
            from . import log
            log.warning(
                f"memory preflight: resident training needs "
                f"{resident['peak_bytes'] / 1e9:.2f} GB against "
                f"{cap / 1e9:.2f} GB capacity; streaming host-resident "
                "bins instead (tpu_stream=auto)")
        slab_rows = int(cfg.tpu_stream_slab_rows or 0)
        if slab_rows <= 0:
            # size the slab against the STREAMED working set (gradients
            # materialized, fused components off) — stream_model applies
            # the same overrides preflight's recommendation uses, so the
            # slab the booster builds is the slab preflight projected
            slab_rows = obs_memory.stream_model(kw, cap)["slab_rows"]
        # slab packing mirrors _maybe_pack_bins' gates exactly: the mesh
        # paths (shard_map pallas histogram wrappers) assume raw
        # row-aligned [F, N] storage, so sharded streaming keeps raw
        # slabs just like sharded resident training does
        pack = (str(cfg.tpu_bin_pack) not in ("off", "0", "false",
                                              "False")
                and cfg.tree_learner == "serial"
                and int(cfg.tpu_num_shards or 0) <= 1)
        from .io.streaming import HostSlabBins
        return HostSlabBins(np.asarray(train_set.bins_fm),
                            int(train_set.max_bins), slab_rows,
                            pack=pack)

    def _parse_forced_splits(self):
        """forcedsplits_filename JSON -> (leaf, feature, threshold_bin)
        int32 arrays aligned with scan steps, or None
        (ref: serial_tree_learner.cpp:628 ForceSplits; the JSON tree is
        walked breadth-first, left child keeps the parent's leaf id,
        right child becomes leaf step+1 — the learner's numbering)."""
        fname = self.config.forcedsplits_filename
        if not fname:
            return None
        import json as _json
        with open(fname) as fh:
            spec = _json.load(fh)
        if not spec:
            return None
        L = self._static["num_leaves"]
        ts = self.train_set
        used_map = {c: j for j, c in enumerate(ts.used_features)}
        leaf_arr = np.full(L - 1, -1, np.int32)
        feat_arr = np.full(L - 1, -1, np.int32)
        thr_arr = np.full(L - 1, -1, np.int32)
        cat_arr = np.zeros(L - 1, np.bool_)
        queue = [(0, spec)]
        s = 0
        while queue and s < L - 1:
            leaf, node = queue.pop(0)
            raw_f = int(node["feature"])
            if raw_f not in used_map:
                continue  # feature dropped as trivial — skip this subtree
            j = used_map[raw_f]
            # numerical: value -> upper-bound bin; categorical: the
            # category's bin, split one-vs-rest (ref: ForceSplits
            # serial_tree_learner.cpp:628 -> Dataset::BinThreshold; the
            # forced categorical split is the single-category bitset)
            tbin = int(self.train_set.mappers[j].transform(
                np.asarray([float(node["threshold"])]))[0])
            leaf_arr[s], feat_arr[s], thr_arr[s] = leaf, j, tbin
            cat_arr[s] = ts.mappers[j].is_categorical
            if "left" in node and node["left"]:
                queue.append((leaf, node["left"]))
            if "right" in node and node["right"]:
                queue.append((s + 1, node["right"]))
            s += 1
        if s == 0:
            return None
        return (jnp.asarray(leaf_arr), jnp.asarray(feat_arr),
                jnp.asarray(thr_arr), jnp.asarray(cat_arr))

    def _parse_interaction_constraints(self):
        """interaction_constraints -> [G, F_used] bool array or None
        (ref: config.h interaction_constraints; col_sampler.hpp)."""
        ic = self.config.interaction_constraints
        if not ic:
            return None
        if isinstance(ic, str):
            import json as _json
            ic = _json.loads(f"[{ic}]" if not ic.startswith("[[") else ic)
        groups = [list(map(int, g)) for g in ic]
        if not groups:
            return None
        ts = self.train_set
        used_map = {c: j for j, c in enumerate(ts.used_features)}
        out = np.zeros((len(groups), ts.num_features), bool)
        for gi, g in enumerate(groups):
            for raw_f in g:
                if raw_f in used_map:
                    out[gi, used_map[raw_f]] = True
        return out

    def _build_grow(self, hist_impl: str, shard_mesh=None,
                    hist_reduce: str = "psum") -> None:
        if self.config.deterministic_hist:
            # Kahan-compensated accumulation lives on the XLA path; the
            # pallas kernels keep their own (non-compensated) order
            hist_impl = "xla"
        self._hist_impl = hist_impl
        self._shard_mesh = shard_mesh
        self._hist_reduce = hist_reduce if shard_mesh is not None else "psum"
        self._has_categorical = any(
            m.is_categorical for m in self.train_set.mappers)
        # per-node randomness (extra-trees thresholds, by-node feature
        # sampling; ref: config.h extra_trees, feature_fraction_bynode)
        self._use_node_rand = (self.config.extra_trees or
                               self.config.feature_fraction_bynode < 1.0)
        self._extra_key = jax.random.PRNGKey(self.config.extra_seed)
        self._fused_grad_fn = self._resolve_fused_grad()
        if self._stream is not None:
            # out-of-core streaming: the grower is host-orchestrated
            # over HostSlabBins; the slow path's `self._grow` becomes
            # the streamed adapter (same call signature, bins argument
            # carries the plan), and the fast paths route through
            # _train_one_iter_stream
            self._stream.mesh = shard_mesh or getattr(self, "mesh", None)
            self._stream_grower = self._make_stream_grower(hist_impl)
            self._grow = self._stream_grow_slow
        else:
            self._grow = obs_xla.instrumented_jit(
                "boosting/grow", self._grow_partial(), phase="grow")
        self._stream_progs = {}
        self._fused = None
        self._record_lrs: List[float] = []
        self._valid_bins: List = []  # device bins per valid set (fast path)
        self._note_hist_traffic()
        self._note_collective_traffic()
        self._note_memory_model()
        self._note_bin_occupancy()

    def _resolve_fused_grad(self):
        """The objective's pointwise gradient fn when the fused
        gradient/histogram wave applies (tpu_fused_grad), else None.
        Requires the waved single-output path with plain pre-computed
        sampling: GOSS reweights by |g| and quantization re-encodes gh,
        so both keep the materialized-gradient path."""
        cfg = self.config
        if str(cfg.tpu_fused_grad) in ("off", "0", "false", "False"):
            return None
        if self._stream is not None:
            # the streamed prep program materializes gradients (the
            # slab passes consume a resident ghT operand)
            return None
        if not self._use_waved() or self.num_tree_per_iteration != 1:
            return None
        if self._quant_enabled or cfg.data_sample_strategy == "goss":
            return None
        if self._sparse_shape is not None or self.objective is None:
            return None
        return self.objective.pointwise_grad_fn()

    def _resolved_hist_shape(self) -> Dict:
        """The booster's ACTUAL resolved histogram-pass shape/knobs —
        the single source both driver-visible cost models (the traffic
        model and the peak-memory model) consume, so they can never
        desynchronize on e.g. the quantization gate."""
        waved = self._use_waved()
        return dict(
            num_data=int(self.num_data),
            storage_features=int(self.train_set.bins_fm.shape[0]),
            max_bins=int(self._num_bundle_bins
                         or self._static["max_bins"]),
            num_leaves=self._static["num_leaves"],
            wave_max=max(self._resolved_wave_max(), 1),
            waved=waved,
            quant_int8=(self._quant_enabled and waved and
                        int(self.config.num_grad_quant_bins) <= 126),
        )

    def _note_hist_traffic(self) -> None:
        """Publish the static per-iteration histogram traffic model (and
        its unpacked / no-subtraction / unfused oracle) through
        obs.metrics — always-on meta, folded into bench.py's JSON line
        and checked by tools/check_perf_gate.py."""
        if self._sparse_shape is not None:
            return
        from .learner import hist_traffic_model
        kw = self._resolved_hist_shape()
        quant_int8 = kw.pop("quant_int8")
        actual = hist_traffic_model(
            **kw, pack_vpb=self._bin_pack_vpb,
            gh_read_bytes=3 if quant_int8 else 12,
            subtract=bool(self.config.tpu_wave_subtract),
            fused_grad=self._fused_grad_fn is not None)
        # oracle: unpacked f32 ghT, standalone gradient pass, and the
        # non-subtraction-aware schedule (both children built per split)
        oracle = hist_traffic_model(**kw, pack_vpb=1, gh_read_bytes=12,
                                    subtract=False, fused_grad=False)
        global_metrics.set_meta("hist_traffic", actual)
        global_metrics.set_meta("hist_traffic_oracle", oracle)
        global_metrics.set_meta("hist_bytes_per_iter",
                                actual["hist_bytes_per_iter"])
        global_metrics.set_meta(
            "hist_bytes_reduction",
            round(oracle["hist_bytes_per_iter"]
                  / max(actual["hist_bytes_per_iter"], 1), 4))

    def _note_collective_traffic(self) -> None:
        """Publish the static per-iteration COLLECTIVE traffic model —
        the interconnect counterpart of ``_note_hist_traffic`` for mesh
        training (ROADMAP item 3's driver-visible counter for the
        reduce-scatter learner). Always computes the psum oracle next
        to the resolved mode so ``collective_reduction`` prices what
        ``tpu_hist_reduce=scatter`` saves: ~W-fold fewer bytes on the
        wire per iteration at equal models."""
        mesh = getattr(self, "_shard_mesh", None)
        if mesh is None or self._sparse_shape is not None:
            return
        from .learner import collective_traffic_model
        shape = self._resolved_hist_shape()
        axes = tuple(mesh.axis_names)
        width = int(mesh.shape[axes[-1]])
        dcn = int(mesh.size) // max(width, 1)
        reduction = getattr(self, "_hist_reduce", "psum")
        if self._bundle is not None:
            reduction = "psum"  # the learner demotes bundled storage
        kw = dict(num_features=int(self.train_set.num_features),
                  max_bins=int(self._static["max_bins"]),
                  num_leaves=shape["num_leaves"],
                  wave_max=shape["wave_max"], width=width, dcn=dcn,
                  subtract=bool(self.config.tpu_wave_subtract),
                  waved=shape["waved"])
        actual = collective_traffic_model(reduction=reduction, **kw)
        oracle = collective_traffic_model(reduction="psum", **kw)
        global_metrics.set_meta("collective_traffic", actual)
        global_metrics.set_meta("collective_traffic_psum", oracle)
        global_metrics.set_meta("collective_bytes_per_iter",
                                actual["collective_bytes_per_iter"])
        global_metrics.set_meta("collective_reduction", round(
            oracle["collective_bytes_per_iter"]
            / max(actual["collective_bytes_per_iter"], 1), 4))

    def _memory_model_kwargs(self) -> Dict:
        """The analytic peak-HBM model's kwargs with every knob RESOLVED
        the way this booster actually resolved it (pack factor, fused /
        quantized state, wave mode, mesh size) — obs/memory.py's
        ``preflight`` derives the same from a raw config for the
        before-any-allocation path; this is the ground truth after."""
        cfg = self.config
        shape = self._resolved_hist_shape()
        fused = self._fused_grad_fn is not None
        mesh = getattr(self, "_shard_mesh", None)
        return dict(
            num_data=shape["num_data"],
            num_features=shape["storage_features"],
            max_bins=shape["max_bins"],
            num_leaves=shape["num_leaves"],
            num_class=self.num_tree_per_iteration,
            num_iterations=int(cfg.num_iterations),
            pack_vpb=int(self._bin_pack_vpb),
            quantized=shape["quant_int8"],
            fused_grad=fused,
            kernel_fused=fused and self._hist_impl == "pallas",
            waved=shape["waved"],
            wave_max=shape["wave_max"],
            num_shards=int(mesh.size) if mesh is not None else 1,
            has_weight=self.train_set.metadata.weight is not None,
            valid_rows=[vs.num_data for vs, _ in self._valid_sets],
            stream_slab_rows=(self._stream.slab_rows
                              if self._stream is not None else 0),
        )

    def _note_memory_model(self) -> None:
        """Publish the analytic peak-HBM model through obs.metrics
        (always-on meta -> bench.py JSON -> tools/check_perf_gate.py
        ceiling) and run the capacity preflight: predicted peak vs
        device capacity, warning (tpu_preflight=warn, the default) or
        raising (=error) with concrete knob recommendations instead of
        OOMing mid-run. Capacity is unknown on CPU (no memory_stats),
        so the check is silent there unless LGBM_TPU_HBM_BYTES is set."""
        if self._sparse_shape is not None:
            return  # COO working sets are nnz-shaped, not modeled yet
        from .obs import memory as obs_memory
        kw = self._memory_model_kwargs()
        report = obs_memory.train_report(
            kw, stream_ok=self._stream_ineligible(self.train_set) is None)
        global_metrics.set_meta("mem_model", report.model)
        global_metrics.set_meta("mem_peak_model_bytes", report.peak_bytes)
        mode = str(self.config.tpu_preflight).lower()
        if mode in ("off", "0", "false", "none") or report.fits is not False:
            return
        if mode == "error":
            raise obs_memory.PreflightError(
                "memory preflight: " + report.render())
        from . import log
        log.warning("memory preflight: " + report.render())

    def _note_bin_occupancy(self) -> None:
        """Publish static bin-occupancy stats through obs meta (part of
        the obs/health model-quality diagnostics): how much of the
        [F, B] histogram capacity the binning actually uses, and how
        many features binned down to a trivial single bin — a dataset
        whose features collapse to 1-2 bins trains structurally blind
        no matter what the loss curve says. Init-time only, always-on
        like the traffic/memory models."""
        try:
            num_bins, _, _, _ = self.train_set.feature_meta_arrays()
        except Exception:
            return
        nb = np.asarray(num_bins)
        if nb.size == 0:
            return
        cap = max(int(self._static["max_bins"]), 1)
        global_metrics.set_meta("health_bins", {
            "features": int(nb.size),
            "max_bins": cap,
            "mean_bins": round(float(nb.mean()), 2),
            "min_bins": int(nb.min()),
            "bin_occupancy": round(float(nb.mean()) / cap, 4),
            "trivial_features": int(np.sum(nb <= 1)),
        })

    # ------------------------------------------------------------------
    # training-health hooks (obs/health.py; tpu_health knob)
    def _health_end_iteration(self) -> None:
        """Per-iteration health checks, run AFTER the iteration's
        programs were dispatched: read the NaN/Inf sentinel counts
        (one tiny [3] device->host transfer per check period), digest
        replicated state across the mesh (drift sentinel), and refresh
        the telemetry straggler probe. warn mode records + logs; error
        mode raises NonFiniteError / DriftError — the structured alarms
        this layer exists for."""
        self._health_tick += 1
        if self._health_tick % self._health_every:
            self._health_vec = None
            self._health_pending_record = None
            return
        gh = obs_health.global_health
        vec, self._health_vec = self._health_vec, None
        if vec is not None:
            g, h, s = (int(x) for x in np.asarray(vec))
            gh.note_sentinel(self.iter - 1, {"grad": g, "hess": h,
                                             "scores": s},
                             mode=self._health_mode)
        mesh = getattr(self, "_shard_mesh", None)
        if mesh is None:
            mesh = getattr(self, "mesh", None)
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            arrays = self._health_drift_arrays(mesh)
            if arrays:
                gh.check_drift(mesh, arrays, mode=self._health_mode,
                               where=f"iteration {self.iter - 1}")
        if gh.enabled:
            gh.straggler_probe()

    def _health_drift_arrays(self, mesh) -> Dict[str, object]:
        """Replicated device state worth digest-comparing across the
        mesh: the latest tree record (fast-path device records, or the
        slow-path record stashed by _train_one_iter_impl before its
        host transfer collapsed it to one device's copy) plus any
        row-independent state the learner keeps fully replicated
        (feature-parallel scores)."""
        from .parallel.mesh import is_replicated_on
        out: Dict[str, object] = {}
        rec = None
        if self._device_records:
            rec = self._device_records[-1]
        elif self._health_pending_record is not None:
            rec = self._health_pending_record
        self._health_pending_record = None
        if rec is not None and is_replicated_on(mesh, rec.leaf_value):
            out["tree_record"] = {"leaf_value": rec.leaf_value,
                                  "leaf_count": rec.leaf_count,
                                  "num_leaves": rec.num_leaves}
        scores = self.scores
        if isinstance(scores, jax.Array) and is_replicated_on(mesh,
                                                              scores):
            out["scores"] = scores
        return out

    def _resolved_wave_max(self) -> int:
        """tpu_wave_max with -1 (auto) resolved: exact order for softmax
        multiclass (cross-class coupling makes split order
        calibration-critical — see the knob's docstring in config.py),
        waved elsewhere. multiclassova's per-class trees are independent
        binary fits, so OVA keeps the waved default."""
        wm = int(self.config.tpu_wave_max)
        if wm >= 0:
            return wm
        obj_name = getattr(self.objective, "name", "")
        coupled = (self.num_tree_per_iteration > 1
                   and obj_name != "multiclassova")
        return 0 if coupled else 42

    def _use_waved(self) -> bool:
        """Waved growth batches histogram builds of many splits into one
        multi-leaf pass (learner.grow_tree_waved); forced splits need the
        exact per-split grower."""
        return self._resolved_wave_max() > 0 and self._forced is None

    def _grow_fn(self):
        return grow_tree_waved if self._use_waved() else grow_tree

    def _grow_kwargs(self):
        kw = dict(self._static)
        if self._use_waved():
            kw["wave_max"] = self._resolved_wave_max()
            kw["subtract_siblings"] = bool(self.config.tpu_wave_subtract)
        if self._bundle is not None:
            kw["bundle"] = self._bundle
            kw["num_bundle_bins"] = self._num_bundle_bins
        if self._sparse_shape is not None:
            kw["sparse_shape"] = self._sparse_shape
        kw["hist_deterministic"] = bool(self.config.deterministic_hist)
        return kw

    # ------------------------------------------------------------------
    # fast path: one fused XLA program per iteration, zero host round-trips
    # (the TPU analog of boosting_on_gpu_, gbdt.cpp:111 — and beyond: the
    # CUDA learner still syncs once per split, this path not at all)
    @property
    def models(self) -> List[List[Tree]]:
        self._materialize_records()
        return self._host_models

    @models.setter
    def models(self, value) -> None:
        self._host_models = value

    def _fast_path_ok(self, custom_grad) -> bool:
        return self.boosting_type == "gbdt" and \
            self._fast_path_core_ok(custom_grad)

    def _fast_path_core_ok(self, custom_grad) -> bool:
        """Conditions shared by the GBDT and DART fused paths."""
        if custom_grad is not None or self.objective is None:
            return False
        if self._has_cegb_coupled:
            # coupled penalties change per iteration with the used-feature
            # set; needs the host loop
            return False
        if self.config.linear_tree:
            # per-leaf least-squares fits run on host
            return False
        # objectives that renew leaf outputs stay fused when they provide
        # the traced renewal (L1/Huber/Quantile/MAPE percentile renew);
        # only custom objectives with host-only renewal fall back. The
        # traced renewal accumulates weights in f32 (no x64 on TPU), so
        # above 2^24 rows — where unit-weight cumsums stop being exactly
        # representable — the f64 host renewal is used instead.
        renews = type(self.objective).renew_tree_output is not \
            ObjectiveFunction.renew_tree_output
        renews_traced = (type(self.objective).renew_leaves_traced is not
                         ObjectiveFunction.renew_leaves_traced
                         and self.num_data < (1 << 24))
        return not renews or renews_traced

    def _grad_fn(self, scores):
        """Traced gradient computation [K, N] (ref: GBDT::Boosting)."""
        obj = self.objective
        if hasattr(obj, "get_gradients_multi"):
            return obj.get_gradients_multi(scores)
        g, h = obj.get_gradients(scores[0])
        return g[None, :], h[None, :]

    def _pad_tail(self, x, value):
        """Pad a per-row vector back to the padded storage length.

        Sharded row storage may carry ``_row_pad`` masked tail rows (see
        DataParallelGBDT._pad_and_shard_rows). Per-row quantities drawn at
        the real length keep their bits (same key, same shape) and the
        tail gets a neutral ``value`` so the padded rows stay inert.
        """
        if self._row_pad == 0:
            return x
        return jnp.pad(x, (0, self._row_pad), constant_values=value)

    def _valid_rows(self, n):
        """Bool [n] marking real rows (False on the padded tail)."""
        return jnp.arange(n) < self.num_data

    def _sampling_in_jit(self, key, it, prev_mask):
        """Bagging mask (traced; ref: bagging.hpp Bagging)."""
        cfg = self.config
        use_bagging = cfg.bagging_freq > 0 and (
            cfg.bagging_fraction < 1.0 or cfg.pos_bagging_fraction < 1.0
            or cfg.neg_bagging_fraction < 1.0)
        if not use_bagging:
            return prev_mask
        u = self._pad_tail(jax.random.uniform(key, (self.num_data,)), 2.0)
        pos_neg = (cfg.pos_bagging_fraction < 1.0 or
                   cfg.neg_bagging_fraction < 1.0) and \
            self.objective is not None and self.objective.name == "binary"
        if pos_neg:
            is_pos = self.objective.label > 0
            frac = jnp.where(is_pos, cfg.pos_bagging_fraction,
                             cfg.neg_bagging_fraction)
        else:
            frac = cfg.bagging_fraction
        fresh = (u < frac).astype(jnp.float32)
        resample = (it % cfg.bagging_freq) == 0
        return jnp.where(resample, fresh, prev_mask)

    def _goss_in_jit(self, key, grad, hess):
        """(ref: goss.hpp:60-131)"""
        cfg = self.config
        n = self.num_data
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        score = jnp.abs(grad) * jnp.abs(hess)
        if self._row_pad:
            # padded tail must not claim top-k slots or survive sampling
            score = jnp.where(self._valid_rows(score.shape[0]), score, -1.0)
        thr = -jnp.sort(-score)[top_k - 1]
        is_top = score >= thr
        u = self._pad_tail(jax.random.uniform(key, (n,)), 2.0)
        keep_rest_p = other_k / max(n - top_k, 1)
        is_other = (~is_top) & (u < keep_rest_p)
        amplify = (1.0 - cfg.top_rate) / cfg.other_rate
        mask = (is_top | is_other).astype(jnp.float32)
        scale = jnp.where(is_other, amplify, 1.0)
        return mask, scale

    def _discretize_in_jit(self, key, grad, hess):
        """Gradient quantization with stochastic rounding (traced;
        ref: gradient_discretizer.cpp DiscretizeGradients — g_scale =
        max|g| / (bins/2), h_scale = max|h| / bins (max|h| when the
        hessian is constant), int value = trunc-toward-zero of
        scaled ± uniform). Returns dequantized (grad, hess): the learner's
        f32 histograms then accumulate exact multiples of the scales, the
        same statistics the reference's integer histograms hold."""
        cfg = self.config
        bins = max(int(cfg.num_grad_quant_bins), 2)
        const_h = (self.objective is not None and
                   self.objective.is_constant_hessian)
        abs_g, abs_h = jnp.abs(grad), jnp.abs(hess)
        if self._row_pad:
            valid = self._valid_rows(abs_g.shape[0])
            abs_g = jnp.where(valid, abs_g, 0.0)
            abs_h = jnp.where(valid, abs_h, 0.0)
        max_g = jnp.maximum(jnp.max(abs_g), K_EPSILON)
        max_h = jnp.maximum(jnp.max(abs_h), K_EPSILON)
        g_scale = max_g / (bins // 2)
        h_scale = max_h if const_h else max_h / bins
        if cfg.stochastic_rounding:
            kg, kh = jax.random.split(key)
            if self._row_pad:
                # draw at the REAL length, then pad: threefry draws are
                # shape-dependent, so drawing at the padded length would
                # move every real row's rounding off the serial stream
                u_g = self._pad_tail(
                    jax.random.uniform(kg, (self.num_data,)), 0.5)
                u_h = self._pad_tail(
                    jax.random.uniform(kh, (self.num_data,)), 0.5)
            else:
                u_g = jax.random.uniform(kg, grad.shape)
                u_h = jax.random.uniform(kh, hess.shape)
        else:
            u_g = u_h = 0.5
        g_int = jnp.trunc(grad / g_scale + jnp.sign(grad) * u_g)
        h_int = jnp.trunc(hess / h_scale + u_h)
        quant = (g_int, h_int, g_scale.astype(jnp.float32),
                 h_scale.astype(jnp.float32))
        return g_int * g_scale, h_int * h_scale, quant

    def _renew_leaves_in_jit(self, rec, row_leaf, true_grad, true_hess,
                             mask):
        """Recompute leaf outputs from the un-quantized gradients
        (ref: gradient_discretizer.hpp RenewIntGradTreeOutput,
        quant_train_renew_leaf)."""
        L = self._static["num_leaves"]
        w = mask
        sums_g = jnp.zeros(L, jnp.float32).at[row_leaf].add(true_grad * w)
        sums_h = jnp.zeros(L, jnp.float32).at[row_leaf].add(true_hess * w)
        renewed = leaf_output(sums_g, sums_h, self.hp)
        new_vals = jnp.where(rec.leaf_count > 0, renewed, rec.leaf_value)
        return rec._replace(leaf_value=new_vals)

    def _feature_mask_in_jit(self, key):
        cfg = self.config
        f = self.train_set.num_features
        if cfg.feature_fraction >= 1.0:
            return jnp.ones(f, bool)
        k = max(1, int(f * cfg.feature_fraction))
        u = jax.random.uniform(key, (f,))
        thr = jnp.sort(u)[k - 1]
        return u <= thr

    def _obj_state(self):
        return (self.objective.device_state()
                if self.objective is not None else {"arrays": {}, "sub": {}})

    def _grow_partial(self):
        """The grower with all static parameters bound (shared by the GBDT
        and DART fused-program builders)."""
        return functools.partial(self._grow_fn(), **self._grow_kwargs(),
                                 hist_dtype=jnp.float32,
                                 hist_impl=self._hist_impl,
                                 hist_precision=self.config.tpu_hist_precision,
                                 interaction_groups=self._interaction_groups,
                                 has_categorical=self._has_categorical,
                                 extra_trees=bool(self.config.extra_trees),
                                 ff_bynode=float(
                                     self.config.feature_fraction_bynode),
                                 shard_mesh=self._shard_mesh,
                                 hist_reduce=getattr(
                                     self, "_hist_reduce", "psum"))

    def _grow_class_traced(self, grow, bins_fm, k, key, grad, hess,
                           sample_mask, scores_k, it):
        """Traced growth of class k's tree for one iteration: GOSS,
        gradient quantization, feature sampling, growth, leaf renewal.
        Shared by the GBDT and DART fused programs. Returns
        (rec, row_leaf)."""
        mask = sample_mask
        if self.config.data_sample_strategy == "goss":
            mask, scale = self._goss_in_jit(
                jax.random.fold_in(key, 100 + k), grad, hess)
            grad, hess = grad * scale, hess * scale
        true_grad, true_hess = grad, hess
        quant = None
        if self._quant_enabled:
            grad, hess, quant = self._discretize_in_jit(
                jax.random.fold_in(key, 300 + k), grad, hess)
        fmask = self._feature_mask_in_jit(
            jax.random.fold_in(key, 200 + k))
        node_key = (jax.random.fold_in(
            self._extra_key,
            it * self.num_tree_per_iteration + k)
            if self._use_node_rand else None)
        grow_kw = {}
        if quant is not None and self._use_waved() and \
                int(self.config.num_grad_quant_bins) <= 126:
            # int8 integer-histogram passes (the exact grower
            # consumes the dequantized f32 values instead).
            # |h_int| <= bins and |g_int| <= bins/2+1, so the
            # int8 cast is exact only for bins <= 126 — larger
            # settings stay on the f32 hist path
            grow_kw["quant"] = quant
        if grad is None:
            # fused gradient/histogram wave (tpu_fused_grad): the
            # caller skipped _grad_fn entirely; the grower derives
            # gh from the objective's pointwise formula — in-kernel
            # on the pallas path
            grow_kw["fused_grad"] = (self._fused_grad_fn,
                                     self.objective.label,
                                     self.objective.weight, scores_k)
        rec, row_leaf = grow(bins_fm, grad, hess, mask, fmask,
                             self.feature_meta, self.hp,
                             self.max_depth, self._forced,
                             node_key, **grow_kw)
        if self._quant_enabled and \
                self.config.quant_train_renew_leaf:
            rec = self._renew_leaves_in_jit(
                rec, row_leaf, true_grad, true_hess, mask)
        obj = self.objective
        if obj is not None:
            renewed_lv = obj.renew_leaves_traced(
                rec.leaf_value, row_leaf, scores_k, mask)
            if renewed_lv is not None:
                rec = rec._replace(leaf_value=jnp.where(
                    rec.num_leaves > 1, renewed_lv, rec.leaf_value))
        return rec, row_leaf

    def _make_fused(self):
        """Build the one-XLA-program-per-iteration jit. All N-sized device
        buffers (bin tensor, valid bins, objective label/weight/pad arrays)
        are explicit arguments — closure capture would bake them into the
        HLO as multi-hundred-MB literal constants and overflow compilation
        at Higgs scale."""
        grow = self._grow_partial()
        sentinel = self._health_armed

        def fused(bins_fm, valid_bins, obj_state, scores, sample_mask,
                  valid_scores, it, lr):
            obj = self.objective
            old_state = (obj.swap_device_state(obj_state)
                         if obj is not None else None)
            try:
                key = jax.random.fold_in(self._bagging_key, it)
                sample_mask = self._sampling_in_jit(
                    jax.random.fold_in(key, 1), it, sample_mask)
                sen_g = sen_h = None
                if self._fused_grad_fn is not None:
                    # gradients fold into the histogram waves (see
                    # _grow_class_traced) — no [N] gradient buffers in
                    # this program at all
                    grad_all = hess_all = (None,)
                    if sentinel:
                        # NaN/Inf sentinel operands: the same pointwise
                        # formula the grower evaluates — XLA CSEs the
                        # two, so the fused path stays fused
                        sen_g, sen_h = self._fused_grad_fn(
                            scores[0], obj.label, obj.weight)
                else:
                    grad_all, hess_all = self._grad_fn(scores)
                    if sentinel:
                        sen_g, sen_h = grad_all, hess_all
                recs = []
                new_valid = list(valid_scores)
                for k in range(self.num_tree_per_iteration):
                    rec, row_leaf = self._grow_class_traced(
                        grow, bins_fm, k, key, grad_all[k], hess_all[k],
                        sample_mask, scores[k], it)
                    # 1-leaf trees contribute nothing (the reference stops
                    # training instead, gbdt.cpp should_continue)
                    leaf_vals = jnp.where(rec.num_leaves > 1,
                                          rec.leaf_value * lr, 0.0)
                    scores = scores.at[k].add(leaf_vals[row_leaf])
                    for vi in range(len(valid_bins)):
                        vleaf = replay_tree(
                            rec, valid_bins[vi], self.feature_meta,
                            self._bundle,
                            num_data=self._valid_sets[vi][0].num_data)
                        new_valid[vi] = new_valid[vi].at[k].add(
                            leaf_vals[vleaf])
                    recs.append(rec)
                stacked = _stack_class_records(recs)
                # updated objective state: objectives that evolve device
                # state across iterations (e.g. lambdarank position
                # biases) assign tracers to their attributes during the
                # trace; collecting the state here returns the updates
                # as program outputs instead of losing them at restore.
                # Evolving subset only — returning the full state would
                # copy every constant [N] label/weight buffer per iter
                out_state = (obj.device_state(evolving_only=True)
                             if obj is not None
                             else {"arrays": {}, "sub": {}})
                if sentinel:
                    # pure reductions as an EXTRA output: the training
                    # math is untouched, so models are bit-identical
                    # with the sentinel on vs off (tests assert)
                    return (scores, sample_mask, tuple(new_valid),
                            stacked, out_state,
                            _nonfinite_counts(sen_g, sen_h, scores))
                return (scores, sample_mask, tuple(new_valid), stacked,
                        out_state)
            finally:
                if obj is not None:
                    obj.swap_device_state(old_state)

        return obs_xla.instrumented_jit("boosting/fused_iter", fused,
                                        phase="train",
                                        donate_argnums=(3, 4, 5))

    # ------------------------------------------------------------------
    # streamed path (tpu_stream): the fused program's math, split at
    # materialization boundaries so the grower can be host-orchestrated
    # over HostSlabBins slabs. Same RNG folds, same traced expressions
    # (each kept whole within one program so XLA's FMA-contraction
    # choices can't diverge) => models bit-identical to the resident
    # fused path whenever the slab accumulation itself is exact
    # (single slab, or int8-quantized histograms at any slab count).
    def _make_stream_grower(self, hist_impl: str):
        from .learner import StreamTreeGrower
        mesh = self._stream.mesh
        if mesh is not None and mesh.size > 1 and hist_impl == "pallas":
            # pallas_call does not auto-partition under GSPMD and the
            # shard_map wrappers assume resident bins; sharded streaming
            # rides the XLA contraction (GSPMD inserts the psum)
            hist_impl = "xla"
        return StreamTreeGrower(
            self._stream,
            num_leaves=self._static["num_leaves"],
            max_bins=self._static["max_bins"],
            num_features=self.train_set.num_features,
            hist_impl=hist_impl,
            hist_precision=self.config.tpu_hist_precision,
            has_categorical=any(m.is_categorical
                                for m in self.train_set.mappers),
            extra_trees=bool(self.config.extra_trees),
            ff_bynode=float(self.config.feature_fraction_bynode),
            wave_max=self._resolved_wave_max(),
            subtract_siblings=bool(self.config.tpu_wave_subtract),
            hist_deterministic=bool(self.config.deterministic_hist))

    def _stream_prog(self, name: str, builder):
        prog = self._stream_progs.get(name)
        if prog is None:
            prog = self._stream_progs[name] = obs_xla.instrumented_jit(
                f"boosting/stream_{name}", builder(), phase="train")
        return prog

    def _make_stream_prep(self):
        """Head of the streamed iteration: bagging + gradients — the
        same RNG folds and expressions as the fused program's head."""
        def prep(obj_state, scores, sample_mask, it):
            obj = self.objective
            old = obj.swap_device_state(obj_state) if obj is not None \
                else None
            try:
                key = jax.random.fold_in(self._bagging_key, it)
                sample_mask = self._sampling_in_jit(
                    jax.random.fold_in(key, 1), it, sample_mask)
                grad_all, hess_all = self._grad_fn(scores)
                out_state = (obj.device_state(evolving_only=True)
                             if obj is not None
                             else {"arrays": {}, "sub": {}})
                return sample_mask, grad_all, hess_all, out_state
            finally:
                if obj is not None:
                    obj.swap_device_state(old)
        return prep

    def _make_stream_class_prep(self, k: int):
        """Per-class sampling/quantization + the grower's resident
        operands: the pre-masked ghT histogram operand (int8 when the
        int8 wave path applies, f32 otherwise), its dequantization
        vector, the root sums, and the feature mask. Identical RNG
        salts to _grow_class_traced."""
        use_int8 = (self._quant_enabled and
                    int(self.config.num_grad_quant_bins) <= 126)

        def class_prep(grad, hess, sample_mask, it):
            key = jax.random.fold_in(self._bagging_key, it)
            mask = sample_mask
            if self.config.data_sample_strategy == "goss":
                mask, scale = self._goss_in_jit(
                    jax.random.fold_in(key, 100 + k), grad, hess)
                grad, hess = grad * scale, hess * scale
            true_grad, true_hess = grad, hess
            quant = None
            if self._quant_enabled:
                grad, hess, quant = self._discretize_in_jit(
                    jax.random.fold_in(key, 300 + k), grad, hess)
            fmask = self._feature_mask_in_jit(
                jax.random.fold_in(key, 200 + k))
            f32 = jnp.float32
            root_g = jnp.sum(grad * mask, dtype=f32)
            root_h = jnp.sum(hess * mask, dtype=f32)
            root_c = jnp.sum(mask, dtype=f32)
            if use_int8:
                g_int, h_int, g_scale, h_scale = quant
                m8 = mask.astype(jnp.int8)
                ghT = jnp.stack([g_int.astype(jnp.int8) * m8,
                                 h_int.astype(jnp.int8) * m8, m8], axis=1)
                hscale = jnp.stack([g_scale, h_scale,
                                    jnp.float32(1.0)]).astype(f32)
            else:
                ghT = jnp.stack([grad * mask, hess * mask, mask],
                                axis=1).astype(f32)
                hscale = jnp.ones((3,), f32)
            return (ghT, hscale, root_g, root_h, root_c, fmask,
                    true_grad, true_hess, mask)
        return class_prep

    def _make_stream_class_post(self, k: int):
        """Leaf renewal + score/valid updates for one grown class —
        the tail of the fused loop body, kept in ONE program so the
        multiply-gather-add keeps the fused path's FMA shape."""
        def class_post(obj_state, rec, row_leaf, scores, valid_scores,
                       valid_bins, mask, true_grad, true_hess, lr):
            obj = self.objective
            old = obj.swap_device_state(obj_state) if obj is not None \
                else None
            try:
                if self._quant_enabled and \
                        self.config.quant_train_renew_leaf:
                    rec = self._renew_leaves_in_jit(
                        rec, row_leaf, true_grad, true_hess, mask)
                if obj is not None:
                    renewed_lv = obj.renew_leaves_traced(
                        rec.leaf_value, row_leaf, scores[k], mask)
                    if renewed_lv is not None:
                        rec = rec._replace(leaf_value=jnp.where(
                            rec.num_leaves > 1, renewed_lv,
                            rec.leaf_value))
                leaf_vals = jnp.where(rec.num_leaves > 1,
                                      rec.leaf_value * lr, 0.0)
                scores = scores.at[k].add(leaf_vals[row_leaf])
                new_valid = list(valid_scores)
                for vi in range(len(valid_bins)):
                    vleaf = replay_tree(
                        rec, valid_bins[vi], self.feature_meta,
                        self._bundle,
                        num_data=self._valid_sets[vi][0].num_data)
                    new_valid[vi] = new_valid[vi].at[k].add(
                        leaf_vals[vleaf])
                return rec, scores, tuple(new_valid)
            finally:
                if obj is not None:
                    obj.swap_device_state(old)
        return class_post

    def _stream_grow_class(self, k: int, grad_k, hess_k, sample_mask, it):
        """Shared per-class streamed growth (fast twin + DART twin):
        class prep program -> host-orchestrated slab grower."""
        cp = self._stream_prog(f"class_prep_{k}",
                               lambda: self._make_stream_class_prep(k))
        (ghT, hscale, root_g, root_h, root_c, fmask,
         true_grad, true_hess, mask) = cp(grad_k, hess_k, sample_mask, it)
        node_key = (jax.random.fold_in(
            self._extra_key,
            self.iter * self.num_tree_per_iteration + k)
            if self._use_node_rand else None)
        rec, row_leaf = self._stream_grower.grow(
            ghT, hscale, (root_g, root_h, root_c), fmask,
            self.feature_meta, self.hp, self.max_depth, node_key)
        return rec, row_leaf, mask, true_grad, true_hess

    def _stream_grow_slow(self, bins_fm, grad, hess, mask, feature_mask,
                          meta, hp, max_depth, forced=None, node_key=None):
        """Slow-path adapter with the resident grower's signature
        (`bins_fm` carries the HostSlabBins plan): custom-gradient /
        RF / host-renewing objectives stream through the same driver
        code they use resident."""
        assert forced is None, \
            "forced splits are gated out of streaming at resolve time"

        def basic_prep(grad_, hess_, mask_):
            f32 = jnp.float32
            ghT = jnp.stack([grad_ * mask_, hess_ * mask_, mask_],
                            axis=1).astype(f32)
            return (ghT, jnp.sum(grad_ * mask_, dtype=f32),
                    jnp.sum(hess_ * mask_, dtype=f32),
                    jnp.sum(mask_, dtype=f32))

        prep = self._stream_prog("slow_prep", lambda: basic_prep)
        ghT, root_g, root_h, root_c = prep(grad, hess, mask)
        return self._stream_grower.grow(
            ghT, jnp.ones((3,), jnp.float32), (root_g, root_h, root_c),
            feature_mask, meta, hp, max_depth, node_key)

    def _note_stream_meta(self) -> None:
        """Publish the streaming pipeline accounting (always-on meta ->
        bench JSON `stream` field + lgbmtpu_stream_* OpenMetrics)."""
        from .io.streaming import global_stream_stats
        plan = self._stream
        global_metrics.set_meta("stream", {
            **global_stream_stats.summary(),
            "slab_rows": int(plan.slab_rows),
            "n_slabs": int(plan.n_slabs),
            "num_data": int(plan.num_data),
            "host_bytes": int(plan.nbytes_host),
        })

    def _stream_take_bins(self):
        """Single-slab streaming: the staged device copy of the whole
        (packed) bin matrix. Uploaded once and cached — the bins are
        immutable, so re-staging identical bytes every iteration would
        only waste link bandwidth, and holding the one copy is exactly
        the memory the model budgeted for the slab pair. The plan
        degenerates to resident behavior with an explicit upload, which
        is what makes single-slab streamed models bit-identical."""
        dev = self._stream_next_bins
        if dev is None:
            dev = self._stream_next_bins = self._stream.stage_noted(0)
        return dev

    def _stream_prefetch_bins(self) -> None:
        """Called right after the fused program dispatches (async):
        bookkeeping hook of the cross-iteration pipeline (the cached
        single-slab upload needs no re-stage; multi-slab plans overlap
        via HostSlabBins.feed instead)."""
        self._stream.stats.note_dispatch()

    def _train_one_iter_stream(self) -> bool:
        """Streamed iteration dispatch. A single-slab plan (the whole
        matrix fits the streaming budget — every fits-in-HBM fixture)
        runs the SAME fused XLA program as resident training on a
        staged-once upload of the bins: bit-identical models by
        construction. Multi-slab plans run the host-orchestrated slab
        grower (bit-identical to the resident host/slow path; int8
        histograms stay bit-identical at any slab count)."""
        if self._stream.n_slabs == 1:
            return self._train_one_iter_fused_upload()
        return self._train_one_iter_stream_orchestrated()

    def _train_one_iter_fused_upload(self) -> bool:
        import time as _time
        from .io.streaming import global_stream_stats as _stats
        self._boost_from_average()
        if self._fused is None:
            with global_tracer.span("train/compile_fused"):
                self._fused = self._make_fused()
        bins = self._stream_take_bins()
        with global_tracer.span("train/iteration",
                                block=lambda: self.scores):
            out = self._fused(
                bins, tuple(self._valid_bins), self._obj_state(),
                self.scores, self._sample_mask, tuple(self._valid_scores),
                jnp.int32(self.iter), jnp.float32(self.shrinkage_rate))
            self._stream_prefetch_bins()
            if self._health_armed:
                (self.scores, self._sample_mask, valid, recs,
                 new_obj_state, self._health_vec) = out
            else:
                (self.scores, self._sample_mask, valid, recs,
                 new_obj_state) = out
            t0 = _time.perf_counter()
            jax.block_until_ready(self.scores)
            _stats.note_block(_time.perf_counter() - t0)
        if self.objective is not None:
            self.objective.swap_device_state(new_obj_state)
        self._valid_scores = list(valid)
        self._device_records.append(recs)
        self._record_lrs.append(self.shrinkage_rate)
        _stats.iterations_total += 1
        self._note_stream_meta()
        self.iter += 1
        return False

    def _train_one_iter_stream_orchestrated(self) -> bool:
        import time as _time
        self._boost_from_average()
        from .io.streaming import global_stream_stats as _stats
        prep = self._stream_prog("prep", self._make_stream_prep)
        with global_tracer.span("train/iteration",
                                block=lambda: self.scores):
            it = jnp.int32(self.iter)
            lr = jnp.float32(self.shrinkage_rate)
            sample_mask, grad_all, hess_all, new_obj_state = prep(
                self._obj_state(), self.scores, self._sample_mask, it)
            self._sample_mask = sample_mask
            if self.objective is not None:
                self.objective.swap_device_state(new_obj_state)
            recs = []
            for k in range(self.num_tree_per_iteration):
                rec, row_leaf, mask, true_g, true_h = \
                    self._stream_grow_class(k, grad_all[k], hess_all[k],
                                            sample_mask, it)
                post = self._stream_prog(
                    f"class_post_{k}",
                    lambda k=k: self._make_stream_class_post(k))
                rec, self.scores, valid = post(
                    self._obj_state(), rec, row_leaf, self.scores,
                    tuple(self._valid_scores), tuple(self._valid_bins),
                    mask, true_g, true_h, lr)
                self._valid_scores = list(valid)
                recs.append(rec)
            if self._health_armed:
                sen = self._stream_prog(
                    "sentinel", lambda: _nonfinite_counts)
                self._health_vec = sen(grad_all, hess_all, self.scores)
            t0 = _time.perf_counter()
            jax.block_until_ready(self.scores)
            _stats.note_block(_time.perf_counter() - t0)
        _stats.iterations_total += 1
        self._device_records.append(_stack_class_records(recs))
        self._record_lrs.append(self.shrinkage_rate)
        self._note_stream_meta()
        self.iter += 1
        return False

    def _train_one_iter_fast(self) -> bool:
        if self._stream is not None:
            return self._train_one_iter_stream()
        self._boost_from_average()
        if self._fused is None:
            with global_tracer.span("train/compile_fused"):
                self._fused = self._make_fused()
        with global_tracer.span("train/iteration",
                                block=lambda: self.scores):
            out = self._fused(
                self.bins_fm, tuple(self._valid_bins), self._obj_state(),
                self.scores, self._sample_mask, tuple(self._valid_scores),
                jnp.int32(self.iter), jnp.float32(self.shrinkage_rate))
            if self._health_armed:
                (self.scores, self._sample_mask, valid, recs,
                 new_obj_state, self._health_vec) = out
            else:
                (self.scores, self._sample_mask, valid, recs,
                 new_obj_state) = out
        if self.objective is not None:
            self.objective.swap_device_state(new_obj_state)
        self._valid_scores = list(valid)
        self._device_records.append(recs)
        self._record_lrs.append(self.shrinkage_rate)
        self.iter += 1
        return False

    def _materialize_records(self) -> None:
        if not self._device_records:
            return
        with global_tracer.span("train/materialize_trees"):
            self._materialize_records_inner()

    def _materialize_records_inner(self) -> None:
        recs, lrs = self._device_records, self._record_lrs
        self._device_records, self._record_lrs = [], []
        host = _records_to_host(recs)
        k_per = self.num_tree_per_iteration
        for i in range(len(recs)):
            first_iter = len(self._host_models) == 0
            iter_trees = []
            for k in range(k_per):
                rec = {f: np.asarray(getattr(host, f)[i][k])
                       for f in host._fields}
                tree = Tree.from_arrays(rec, self.train_set.mappers,
                                        self.train_set.used_features)
                if tree.num_leaves > 1:
                    tree.apply_shrinkage(lrs[i])
                    if first_iter and abs(self.init_scores[k]) > K_EPSILON:
                        tree.add_bias(self.init_scores[k])
                else:
                    tree.leaf_value[:] = (self.init_scores[k]
                                          if first_iter else 0.0)
                iter_trees.append(tree)
            self._host_models.append(iter_trees)

    # ------------------------------------------------------------------
    # bagging / GOSS (ref: bagging.hpp:15, goss.hpp:19)
    def _resample_mask(self):
        cfg = self.config
        strategy = cfg.data_sample_strategy
        if strategy == "goss":
            return None  # computed per-iteration with gradients
        use_bagging = cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0
        pos_neg = (cfg.pos_bagging_fraction < 1.0 or
                   cfg.neg_bagging_fraction < 1.0) and cfg.bagging_freq > 0
        if not use_bagging and not pos_neg:
            return
        if self.iter % cfg.bagging_freq != 0:
            return  # keep previous subset (ref: bagging.hpp Bagging)
        key = jax.random.fold_in(self._bagging_key, self.iter)
        u = jax.random.uniform(key, (self.num_data,))
        if pos_neg and self.objective is not None and \
                self.objective.name == "binary":
            is_pos = jnp.asarray(self.objective.label_np > 0)
            frac = jnp.where(is_pos, cfg.pos_bagging_fraction,
                             cfg.neg_bagging_fraction)
            self._sample_mask = self._pad_tail(
                (u < frac).astype(jnp.float32), 0.0)
        else:
            self._sample_mask = self._pad_tail(
                (u < cfg.bagging_fraction).astype(jnp.float32), 0.0)

    def _goss_mask(self, grad, hess):
        """GOSS: keep top_rate by |g*h|, sample other_rate of the rest and
        amplify them (ref: goss.hpp:60-131)."""
        cfg = self.config
        top_rate, other_rate = cfg.top_rate, cfg.other_rate
        n = self.num_data
        top_k = max(1, int(n * top_rate))
        other_k = max(1, int(n * other_rate))
        score = jnp.abs(grad) * jnp.abs(hess)
        if self._row_pad:
            score = jnp.where(self._valid_rows(score.shape[0]), score, -1.0)
        thr = -jnp.sort(-score)[top_k - 1]
        is_top = score >= thr
        key = jax.random.fold_in(self._bagging_key, self.iter + (1 << 20))
        u = self._pad_tail(jax.random.uniform(key, (n,)), 2.0)
        keep_rest_p = other_k / max(n - top_k, 1)
        is_other = (~is_top) & (u < keep_rest_p)
        amplify = (1.0 - top_rate) / other_rate
        mask = (is_top | is_other).astype(jnp.float32)
        scale = jnp.where(is_other, amplify, 1.0)
        return mask, scale

    def _feature_mask(self):
        cfg = self.config
        f = self.train_set.num_features
        if cfg.feature_fraction >= 1.0:
            return jnp.ones(f, bool)
        k = max(1, int(f * cfg.feature_fraction))
        idx = self._feature_rng.choice(f, k, replace=False)
        mask = np.zeros(f, bool)
        mask[idx] = True
        return jnp.asarray(mask)

    # ------------------------------------------------------------------
    def _sync_init_scores(self, scores: np.ndarray) -> np.ndarray:
        """Hook: distributed learners average per-machine init scores
        (ref: gbdt.cpp:322 Network::GlobalSyncUpByMean)."""
        return scores

    def _boost_from_average(self):
        """(ref: gbdt.cpp:328)"""
        if self._init_done:
            return
        self._init_done = True
        if (self.objective is None or self._has_init_score or
                not self.config.boost_from_average):
            return
        raw = self._sync_init_scores(np.asarray(
            [self.objective.boost_from_score(k)
             for k in range(self.num_tree_per_iteration)], np.float64))
        for k in range(self.num_tree_per_iteration):
            if abs(raw[k]) > K_EPSILON:
                self.init_scores[k] = float(raw[k])
        if any(abs(s) > K_EPSILON for s in self.init_scores):
            init = jnp.asarray(np.asarray(self.init_scores, np.float32)
                               [:, None])
            add = jax.jit(lambda s, i: s + i)  # jit: works on globally
            # sharded multi-host arrays too (eager ops would not)
            self.scores = add(self.scores, init)
            for vi in range(len(self._valid_scores)):
                self._valid_scores[vi] = add(self._valid_scores[vi], init)

    def _gradients(self, custom_grad=None, custom_hess=None):
        """-> grad, hess [K, N] (ref: GBDT::Boosting gbdt.cpp:229)."""
        if custom_grad is not None:
            g = jnp.asarray(np.asarray(custom_grad, np.float32).reshape(
                self.num_tree_per_iteration, self.num_data))
            h = jnp.asarray(np.asarray(custom_hess, np.float32).reshape(
                self.num_tree_per_iteration, self.num_data))
            if self._row_pad:
                pad = ((0, 0), (0, self._row_pad))
                g, h = jnp.pad(g, pad), jnp.pad(h, pad)
            return g, h
        obj = self.objective
        if hasattr(obj, "get_gradients_multi"):
            return obj.get_gradients_multi(self.scores)
        g, h = obj.get_gradients(self.scores[0])
        return g[None, :], h[None, :]

    # ------------------------------------------------------------------
    def train_one_iter(self, custom_grad=None, custom_hess=None) -> bool:
        """Returns True when training should stop (no splittable leaves),
        matching the reference return convention (gbdt.cpp:353).

        With telemetry on (obs.metrics), each call opens a per-iteration
        metrics record; disabled mode is a single attribute check."""
        if global_flusher.armed:  # LGBM_TPU_METRICS_FILE textfile egress
            global_flusher.maybe_flush()
        if faults_mod.global_faults.armed:
            # deterministic fault plan (resilience/faults.py): the
            # slow-shard fault injects its straggler delay at the
            # iteration lifecycle so skew probes see it from ANY entry
            # point (engine / capi / sklearn), not just engine.train
            faults_mod.global_faults.maybe_slow_iteration()
        if global_flightrec.armed:
            # black-box iteration marker (obs/flightrec.py): at the
            # lifecycle so every entry point records it, and BEFORE the
            # work so a crashing iteration is in the dump
            global_flightrec.record("iteration", iteration=int(self.iter),
                                    trees=len(self._device_records)
                                    + len(self._host_models))
        if self._profile_mode != "off":
            self._profile_tick()
        if not global_metrics.enabled:
            if not self._health_armed:
                return self._train_one_iter_impl(custom_grad, custom_hess)
            # tpu_health without full telemetry: the sentinels run, the
            # per-iteration metrics machinery stays off
            stop = self._train_one_iter_impl(custom_grad, custom_hess)
            self._health_end_iteration()
            return stop
        global_metrics.begin_iteration(self.iter)
        n_dev0, n_host0 = len(self._device_records), len(self._host_models)
        self._observe_safely(self._observe_gradient_metrics,
                             custom_grad, custom_hess)
        try:
            stop = self._train_one_iter_impl(custom_grad, custom_hess)
            if self._health_armed:
                # inside the try: a DriftError/NonFiniteError must
                # propagate while the finally still closes the record
                self._health_end_iteration()
            return stop
        finally:
            self._observe_safely(self._observe_tree_metrics, n_dev0, n_host0)
            global_metrics.end_iteration()
            if not self._health_armed and \
                    obs_health.global_health.enabled:
                # telemetry-only runs still get the straggler probe
                obs_health.global_health.straggler_probe()

    def _profile_tick(self) -> None:
        """tpu_profile window lifecycle (obs/profile.py), called at the
        top of each iteration. "window": opens the capture at iteration
        1 — the compile-heavy first iteration would drown the steady
        state — and closes it after tpu_profile_window iterations
        (micro-reruns + roofline happen at close). "bench": opens
        immediately and stays open; the harness reads/stops it."""
        if self._profile_mode == "bench":
            if not global_profile.capturing:
                global_profile.start_window(source="bench")
            return
        if not self._profile_started:
            if self.iter >= 1:
                self._profile_started = True
                global_profile.start_window(source="window")
        elif global_profile.capturing:
            self._profile_left -= 1
            if self._profile_left <= 0:
                global_profile.stop_window()

    @staticmethod
    def _observe_safely(fn, *args) -> None:
        """Telemetry must never kill training (e.g. eager norm ops on
        multi-host sharded arrays can be unsupported)."""
        try:
            fn(*args)
        except Exception as exc:
            from . import log
            log.debug(f"telemetry observation failed: {exc!r}")

    def _observe_gradient_metrics(self, custom_grad, custom_hess) -> None:
        """Gradient norms / clip counts for the iteration about to run
        (telemetry-enabled path only — recomputes gradients from the
        current scores, so it adds one gradient pass)."""
        m = global_metrics
        if custom_grad is not None:
            g = np.asarray(custom_grad, np.float32)
            h = np.asarray(custom_hess, np.float32)
            m.observe("grad_norm", float(np.linalg.norm(g)))
            m.observe("hess_norm", float(np.linalg.norm(h)))
            m.observe("grad_nonfinite", int(np.sum(~np.isfinite(g))))
            return
        if self.objective is None:
            return
        # iteration 0 gradients are taken AFTER the init score lands
        # (idempotent; both train paths apply it before their gradients)
        self._boost_from_average()
        with global_tracer.span("train/telemetry_gradients"):
            g, h = self._grad_fn(self.scores)
            g_abs = jnp.abs(g)
            m.observe("grad_norm", float(jnp.linalg.norm(g)))
            m.observe("hess_norm", float(jnp.linalg.norm(h)))
            m.observe("grad_nonfinite", int(jnp.sum(~jnp.isfinite(g))))
            if self._quant_enabled:
                # entries landing in the extreme quantization bin — the
                # discretizer's saturation count (ref:
                # gradient_discretizer.cpp DiscretizeGradients)
                bins = max(int(self.config.num_grad_quant_bins), 2)
                g_scale = jnp.maximum(jnp.max(g_abs), K_EPSILON) / (bins // 2)
                m.observe("grad_clipped", int(jnp.sum(
                    g_abs >= g_scale * (bins // 2 - 0.5))))

    def _observe_tree_metrics(self, n_dev0: int, n_host0: int) -> None:
        """Leaves grown / split-gain stats of the iteration that just
        finished, plus sampled-row count (telemetry-enabled path only)."""
        m = global_metrics
        gains = None
        split_leaves = leaf_counts = None
        if len(self._device_records) > n_dev0:
            rec = self._device_records[-1]  # stacked [K, ...] TreeArrays
            nl, gains, split_leaves, leaf_counts = jax.device_get(
                (rec.num_leaves, rec.split_gain, rec.split_leaf,
                 rec.leaf_count))
            m.observe("leaves_grown", int(np.sum(nl)))
            gains = np.asarray(gains).reshape(-1)
        elif len(self._host_models) > n_host0:
            trees = self._host_models[-1]
            m.observe("leaves_grown",
                      int(sum(t.num_leaves for t in trees)))
            gains = np.concatenate(
                [np.asarray(t.split_gain[:t.num_internal], np.float64)
                 for t in trees]) if trees else np.zeros(0)
            leaf_counts = np.concatenate(
                [np.asarray(t.leaf_count[:t.num_leaves], np.float64)
                 for t in trees]) if trees else None
        if gains is not None:
            pos = gains[gains > 0]
            m.observe("splits_made", int(pos.size))
            if pos.size:
                m.observe("best_gain", float(pos.max()))
                m.observe("mean_split_gain", float(pos.mean()))
                # gain DISTRIBUTION, not just the extremes: a healthy
                # iteration's gain spectrum decays smoothly; a spectrum
                # collapsing toward zero flags exhausted structure long
                # before eval loss plateaus (obs/health diagnostics)
                m.observe("gain_p50", float(np.percentile(pos, 50)))
                m.observe("gain_p90", float(np.percentile(pos, 90)))
        if split_leaves is not None:
            depth_max = 0
            for sl in np.asarray(split_leaves).reshape(
                    -1, np.asarray(split_leaves).shape[-1]):
                depths = obs_health.tree_depths(sl)
                depth_max = max(depth_max, int(depths.max()))
            m.observe("tree_depth_max", depth_max)
        if leaf_counts is not None:
            lc = np.asarray(leaf_counts, np.float64).reshape(-1)
            lc = lc[lc > 0]
            if lc.size:
                m.observe("leaf_count_min", int(lc.min()))
                m.observe("leaf_count_median", float(np.median(lc)))
                m.observe("leaf_count_max", int(lc.max()))
        m.observe("sampled_rows", int(jnp.sum(self._sample_mask)))

    def _train_one_iter_impl(self, custom_grad=None,
                             custom_hess=None) -> bool:
        if self._fast_path_ok(custom_grad):
            return self._train_one_iter_fast()
        if custom_grad is None:
            self._boost_from_average()
        with global_tracer.span("train/gradients",
                                block=lambda: grad_all):
            grad_all, hess_all = self._gradients(custom_grad, custom_hess)
        if self._health_armed:
            # NaN/Inf sentinel payload, from the gradients this
            # iteration is about to train on — no extra passes, the
            # buffers are already live (obs/health.py)
            self._health_vec = _nonfinite_counts(grad_all, hess_all,
                                                 self.scores)
        with global_tracer.span("train/sampling"):
            self._resample_mask()

        iter_trees: List[Tree] = []
        should_continue = False
        for k in range(self.num_tree_per_iteration):
            grad, hess = grad_all[k], hess_all[k]
            mask = self._sample_mask
            if self.config.data_sample_strategy == "goss" and \
                    custom_grad is None:
                with global_tracer.span("train/sampling"):
                    mask, scale = self._goss_mask(grad, hess)
                    grad, hess = grad * scale, hess * scale
            true_grad, true_hess = grad, hess
            if self._quant_enabled:
                qkey = jax.random.fold_in(self._bagging_key,
                                          self.iter + (3 << 20) + k)
                grad, hess, _quant = self._discretize_in_jit(qkey, grad, hess)
            feature_mask = self._feature_mask()

            node_key = (jax.random.fold_in(
                self._extra_key,
                self.iter * self.num_tree_per_iteration + k)
                if self._use_node_rand else None)
            with global_tracer.span("train/grow",
                                    block=lambda: record.leaf_value):
                record, row_leaf = self._grow(
                    self.bins_fm, grad, hess, mask, feature_mask,
                    self.feature_meta, self.hp, self.max_depth, self._forced,
                    node_key)
            if self._health_armed:
                # keep the REPLICATED device record alive until the
                # end-of-iteration drift digest: the host transfer
                # below reads one device's copy, which is exactly how
                # a diverged replica would go unnoticed
                self._health_pending_record = record
            if self._quant_enabled and \
                    self.config.quant_train_renew_leaf:
                record = self._renew_leaves_in_jit(
                    record, row_leaf, true_grad, true_hess, mask)

            rec_host = _tree_record_to_host(record)
            tree = Tree.from_arrays(rec_host, self.train_set.mappers,
                                    self.train_set.used_features)
            if tree.num_leaves > 1:
                should_continue = True
                # RenewTreeOutput for L1-family (ref: gbdt.cpp:420)
                if self.objective is not None:
                    # host renewal pairs these with real-length label
                    # arrays — drop the padded tail rows
                    nd = self.num_data
                    renewed = self.objective.renew_tree_output(
                        tree, np.asarray(self.scores[k])[:nd],
                        np.asarray(row_leaf)[:nd], np.asarray(mask)[:nd])
                    if renewed is not None:
                        tree = renewed
                if self.config.linear_tree:
                    raw = self.train_set.raw_data
                    if raw is None:
                        raise ValueError(
                            "linear_tree requires raw feature values "
                            "(unavailable for binary-loaded datasets)")
                    from .linear import fit_linear_models
                    fit_linear_models(
                        tree, np.asarray(raw, np.float64),
                        np.asarray(row_leaf), np.asarray(true_grad),
                        np.asarray(true_hess), np.asarray(mask),
                        self.config.linear_lambda)
                # pre-shrinkage leaf values are exactly f32 (grower
                # output / traced renewal); captured before the f64
                # host shrinkage so the score update below can multiply
                # in f32 — the SAME rounding the fused program applies
                # (rec.leaf_value * lr). A one-ulp score skew here flips
                # sign-function gradients (L1 family) on rows sitting
                # at score == label, which cascades into different
                # splits a few iterations later.
                lv32 = tree.leaf_value.astype(np.float32)
                tree.apply_shrinkage(self._tree_shrinkage())
                with global_tracer.span("train/update_score",
                                        block=lambda: self.scores):
                    if tree.is_linear:
                        # within-leaf outputs vary by row: linear outputs
                        # over the grower's row->leaf map (no re-traversal)
                        vals = tree.predict_given_leaves(
                            np.asarray(self.train_set.raw_data, np.float64),
                            np.asarray(row_leaf))
                        new_score_k = self.scores[k] + jnp.asarray(
                            vals.astype(np.float32))
                    else:
                        new_score_k = self._slow_score_update(
                            tree, lv32, row_leaf, k)
                    self.scores = self.scores.at[k].set(new_score_k)
                    self._update_valid_scores(tree, k)
                if abs(self.init_scores[k]) > K_EPSILON and \
                        len(self.models) == 0:
                    tree.add_bias(self.init_scores[k])
            else:
                # constant tree (ref: gbdt.cpp AsConstantTree): bias on
                # the first iteration, ZERO afterwards — the grower's
                # unshrunk root output must not leak into the model (it
                # was never added to the training scores, and a DART
                # drop would subtract it; the fused path stores 0 for
                # 1-leaf trees, asserted equal by TestFusedDart)
                tree.leaf_value[:] = (self.init_scores[k]
                                      if len(self.models) == 0 else 0.0)
            iter_trees.append(tree)

        self.models.append(iter_trees)
        if not should_continue:
            self.models.pop()
            return True
        if self._has_cegb_coupled:
            # refresh first-use coupled penalties
            # (ref: UpdateLeafBestSplits marks is_feature_used_in_split_)
            changed = False
            for tree in iter_trees:
                for f_inner in tree.split_feature_inner[:tree.num_internal]:
                    if not self._cegb_used[f_inner]:
                        self._cegb_used[f_inner] = True
                        changed = True
            if changed:
                new_pen = self.config.cegb_tradeoff * np.where(
                    self._cegb_used, 0.0, self._cegb_coupled)
                self.feature_meta = self.feature_meta._replace(
                    cegb_feat=jnp.asarray(new_pen.astype(np.float32)))
        if self._stream is not None:
            # slow-path streamed iterations (custom fobj / RF / CEGB /
            # host-renewing objectives) carry the same always-on stream
            # accounting as the fast twins — and the end-of-iteration
            # sync resets the overlap classifier's in-flight count so a
            # later pipeline can't inherit stale dispatches
            import time as _time
            from .io.streaming import global_stream_stats as _stats
            t0 = _time.perf_counter()
            jax.block_until_ready(self.scores)
            _stats.note_block(_time.perf_counter() - t0)
            _stats.iterations_total += 1
            self._note_stream_meta()
        self.iter += 1
        return False

    def _tree_shrinkage(self) -> float:
        return self.shrinkage_rate

    def _slow_score_update(self, tree, lv32: np.ndarray, row_leaf, k):
        """Slow-path score update, bit-aligned with the fused program:
        f32 pre-shrinkage leaf values x f32 learning rate, multiplied
        and added in one XLA program (see _update_score_shrunk). DART
        overrides: its drop/re-add cycle subtracts f64 host leaf
        values, so its slow path must add exactly those."""
        return self._update_score_shrunk(
            self.scores[k], jnp.asarray(lv32),
            jnp.float32(self._tree_shrinkage()), row_leaf)

    # ------------------------------------------------------------------
    def add_valid(self, valid_set, raw_data: Optional[np.ndarray]) -> None:
        """Register a validation set; scores held on device [K, Nv] and
        updated incrementally (ref: GBDT::AddValidDataset gbdt.cpp)."""
        self._valid_sets.append((valid_set, raw_data))
        n = valid_set.num_data
        score = np.zeros((self.num_tree_per_iteration, n), np.float32)
        # catch up on existing model
        if self.current_iteration() > 0:
            raw = self.predict_raw(raw_data)
            score = raw.reshape(n, self.num_tree_per_iteration).T
        elif any(abs(s) > K_EPSILON for s in self.init_scores):
            score += np.asarray(self.init_scores, np.float32)[:, None]
        if valid_set.metadata.init_score is not None:
            init = np.asarray(valid_set.metadata.init_score, np.float64)
            score += (init.reshape(-1, n) if init.size != n
                      else init.reshape(1, n)).astype(np.float32)
        self._valid_scores.append(jnp.asarray(score))
        vbins = (self._maybe_pack_bins(valid_set)
                 if self._bin_pack_vpb > 1 else None)
        self._valid_bins.append(vbins if vbins is not None
                                else valid_set.device_bins())
        self._fused = None  # fused program must include the new valid set
        self._stream_progs = {}  # streamed post programs carry valid sets
        # the valid bins + scores just moved on device: refresh the
        # published peak-memory model (and re-judge the preflight) so a
        # big eval set can't silently blow past a "fits" verdict
        self._note_memory_model()

    def _valid_raw(self, i: int) -> np.ndarray:
        """Valid set i's raw features as a DENSE array — the host tree
        paths (renewing objectives, DART normalize, rollback) index raw
        values row-wise every iteration, so a sparse valid set is
        densified once and cached rather than per iteration."""
        raw = self._valid_sets[i][1]
        from .dataset import is_sparse
        if is_sparse(raw):
            cache = getattr(self, "_valid_dense", None)
            if cache is None:
                cache = self._valid_dense = {}
            if i not in cache:
                cache[i] = np.asarray(raw.toarray(), np.float64)
            return cache[i]
        return raw

    def _update_valid_scores(self, tree: Tree, class_id: int) -> None:
        for i, (vs, raw) in enumerate(self._valid_sets):
            self._valid_scores[i] = self._valid_scores[i].at[class_id].add(
                jnp.asarray(tree.predict(self._valid_raw(i))
                            .astype(np.float32)))

    def valid_raw_scores(self, idx: int) -> np.ndarray:
        return np.asarray(self._valid_scores[idx]).T

    # ------------------------------------------------------------------
    def init_from_loaded(self, loaded) -> None:
        """Continued training: seed the booster with a previously trained
        model's trees and fast-forward train/valid scores by prediction
        (ref: boosting.cpp:74-90 LoadFileToBoosting; continued-training
        init score via Predictor, application.cpp:92-100)."""
        k = self.num_tree_per_iteration
        if loaded.num_tree_per_iteration != k:
            raise ValueError(
                f"init_model has {loaded.num_tree_per_iteration} trees per "
                f"iteration, training config needs {k}")
        n_feat = self.train_set.num_total_features
        if loaded.max_feature_idx + 1 > n_feat:
            raise ValueError(
                f"init_model uses {loaded.max_feature_idx + 1} features, "
                f"train data has {n_feat}")
        if self.train_set.raw_data is None:
            raise ValueError(
                "continued training requires raw feature values to "
                "fast-forward scores (binary-loaded datasets keep none)")
        trees = list(loaded.trees)
        self._materialize_records()
        self._host_models = [trees[i:i + k]
                             for i in range(0, len(trees), k)]
        self.iter = len(self._host_models)
        # the loaded first tree already carries the boost-from-average
        # bias; never re-apply it
        self._init_done = True
        self.init_scores = [0.0] * k

        def _dataset_init_offset(meta_init, n):
            """Per-row init_score offsets a dataset contributes to its
            scores (same layout handling as __init__)."""
            off = np.zeros((k, n), np.float32)
            if meta_init is not None:
                init = np.asarray(meta_init, np.float64)
                if init.size == n * k:
                    off += init.reshape(k, n, order="C").astype(np.float32)
                else:
                    off += init.reshape(1, -1).astype(np.float32)
            return off

        raw = self.predict_raw(self.train_set.raw_data)  # [N, K]
        scores = raw.T.astype(np.float32) + _dataset_init_offset(
            self.train_set.metadata.init_score, self.num_data)
        if self._row_pad:
            scores = np.pad(scores, ((0, 0), (0, self._row_pad)))
        self.scores = jnp.asarray(scores)
        for i, (vs, raw_v) in enumerate(self._valid_sets):
            vraw = self.predict_raw(raw_v)  # handles sparse + dense
            self._valid_scores[i] = jnp.asarray(
                vraw.T.astype(np.float32) + _dataset_init_offset(
                    vs.metadata.init_score, vs.num_data))

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """(ref: gbdt.cpp:463 RollbackOneIter)"""
        if self.iter <= 0:
            return
        trees = self.models.pop()
        for k, tree in enumerate(trees):
            if tree.num_leaves > 1:
                # recompute leaf assignment for train rows via binned predict
                leaves = self._predict_leaf_binned_train(tree)
                if tree.is_linear:
                    vals = tree.predict_given_leaves(
                        np.asarray(self.train_set.raw_data, np.float64),
                        np.asarray(leaves))
                    self.scores = self.scores.at[k].add(
                        jnp.asarray(-vals.astype(np.float32)))
                else:
                    self.scores = self.scores.at[k].add(
                        jnp.asarray((-tree.leaf_value.astype(np.float32)))
                        [leaves])
        for i, (vs, raw) in enumerate(self._valid_sets):
            for k, tree in enumerate(trees):
                self._valid_scores[i] = self._valid_scores[i].at[k].add(
                    jnp.asarray(-tree.predict(self._valid_raw(i))
                                .astype(np.float32)))
        self.iter -= 1

    def _predict_leaf_binned_train(self, tree: Tree):
        """Leaf index per train row using the binned matrix."""
        bins = self.train_set.bins_fm
        n = bins.shape[1]
        sparse_cols = None
        if self.train_set.sparse_coo is not None:
            # COO storage: materialize only the tree's split features
            uniq = np.unique(np.asarray(
                tree.split_feature_inner[:tree.num_internal], np.int64))
            sparse_cols = {int(ff): self.train_set.host_feature_bins(
                int(ff)) for ff in uniq}
        node = np.zeros(n, np.int32)
        out = np.zeros(n, np.int32)
        if tree.num_internal == 0:
            return jnp.asarray(out)
        done = np.zeros(n, bool)
        num_bins, missing, default_bin, is_cat = \
            self.train_set.feature_meta_arrays()
        # bin-level go-left lookup for categorical nodes: mapper bin ->
        # raw category value -> membership in the node's value bitset
        max_b = int(self.train_set.max_bins)
        cat_lut = np.zeros((tree.num_internal, max_b), bool)
        for nd_i in range(tree.num_internal):
            if not (tree.decision_type[nd_i] & 1):
                continue
            mapper = self.train_set.mappers[tree.split_feature_inner[nd_i]]
            cat_idx = int(tree.threshold[nd_i])
            lo, hi = (tree.cat_boundaries[cat_idx],
                      tree.cat_boundaries[cat_idx + 1])
            for b in range(1, mapper.num_bins):
                v = int(mapper.bin_to_value(b))
                if v >= 0 and v // 32 < hi - lo and \
                        (tree.cat_threshold[lo + v // 32] >> (v % 32)) & 1:
                    cat_lut[nd_i, b] = True
        bi = self.train_set.bundle_info
        for _ in range(tree.num_internal + 1):
            if done.all():
                break
            active = np.flatnonzero(~done)
            nd = node[active]
            feat = tree.split_feature_inner[nd]
            if sparse_cols is not None:
                b = np.empty(len(active), np.int32)
                for ff in np.unique(feat):
                    m = feat == ff
                    b[m] = sparse_cols[int(ff)][active[m]]
            elif bi is None:
                b = bins[feat, active].astype(np.int32)
            else:  # EFB decode
                from .bundling import decode_stored_host
                b = decode_stored_host(
                    bins[bi.group_of[feat], active].astype(np.int32),
                    bi.offset_of[feat], num_bins[feat] - 1)
            tbin = tree.threshold_bin[nd]
            nan_bin = num_bins[feat] - 1
            is_nan = (missing[feat] == 2) & (b == nan_bin)
            dleft = (tree.decision_type[nd] & 2) > 0
            cat = (tree.decision_type[nd] & 1) > 0
            go_left = np.where(cat, cat_lut[nd, b],
                               np.where(is_nan, dleft, b <= tbin))
            child = np.where(go_left, tree.left_child[nd],
                             tree.right_child[nd])
            is_leaf = child < 0
            out[active[is_leaf]] = ~child[is_leaf]
            done[active[is_leaf]] = True
            node[active[~is_leaf]] = child[~is_leaf]
        return jnp.asarray(out)

    # ------------------------------------------------------------------
    # prediction (ref: gbdt_prediction.cpp:16-91, predictor.hpp:31)
    # Default path: the streaming tree-parallel inference engine
    # (ops/predict.py) — vmapped traversal over the packed [T] trees,
    # shape-bucketed chunking, optional mesh sharding; host fallback for
    # linear trees (per-leaf models live on host).
    def predict_raw(self, data: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1,
                    predict_chunk: Optional[int] = None) -> np.ndarray:
        from .dataset import is_sparse, sparse_row_batches
        if is_sparse(data):
            if data.shape[0] == 0:
                data = np.zeros(data.shape)
            else:
                return np.concatenate(
                    [self.predict_raw(b, start_iteration, num_iteration,
                                      predict_chunk=predict_chunk)
                     for b in sparse_row_batches(data)], axis=0)
        data = np.asarray(data, np.float64)
        end = len(self.models) if num_iteration < 0 else \
            min(len(self.models), start_iteration + num_iteration)
        trees = [t for it in self.models[start_iteration:end] for t in it]
        if not trees:
            return np.zeros((data.shape[0], self.num_tree_per_iteration))
        # classification only — regression/ranking need accurate sums
        # (ref: predictor.hpp:47 gates on !NeedAccuratePrediction)
        if self.config.pred_early_stop and self.config.objective in (
                "binary", "multiclass", "multiclassova", "cross_entropy",
                "cross_entropy_lambda"):
            return self._predict_raw_early_stop(data, start_iteration, end)
        if any(t.is_linear for t in trees):
            return self._predict_raw_host(data, start_iteration, end)
        from .ops.predict import predict_raw_cached
        key = (start_iteration, end, self.current_iteration())
        chunk = (int(predict_chunk) if predict_chunk
                 else int(self.config.tpu_predict_chunk or (1 << 20)))
        shards = int(self.config.tpu_num_shards or 0)
        with global_tracer.span("predict/raw"):
            return predict_raw_cached(self, trees,
                                      self.num_tree_per_iteration,
                                      data, key, chunk,
                                      num_shards=shards if shards > 1 else 0)

    def _predict_raw_host(self, data: np.ndarray, start_iteration: int,
                          end: int) -> np.ndarray:
        n = data.shape[0]
        k = self.num_tree_per_iteration
        out = np.zeros((n, k))
        for it in range(start_iteration, end):
            for ki, tree in enumerate(self.models[it]):
                out[:, ki] += tree.predict(data)
        return out

    def _predict_raw_early_stop(self, data: np.ndarray, start_iteration: int,
                                end: int) -> np.ndarray:
        """Row-wise prediction with early termination (ref:
        prediction_early_stop.cpp CreatePredictionEarlyStopInstance:
        binary stops when |margin| > margin_threshold, multiclass when
        top1 - top2 > threshold, checked every `freq` trees). A host
        path by design: data-dependent per-row loop exits fit the CPU;
        the device ensemble path evaluates all trees faster than it
        could branch."""
        n = data.shape[0]
        k = self.num_tree_per_iteration
        freq = max(int(self.config.pred_early_stop_freq), 1)
        margin = float(self.config.pred_early_stop_margin)
        out = np.zeros((n, k))
        active = np.ones(n, bool)
        for idx, it in enumerate(range(start_iteration, end)):
            rows = np.flatnonzero(active)
            if rows.size == 0:
                break
            sub = data[rows]
            for ki, tree in enumerate(self.models[it]):
                out[rows, ki] += tree.predict(sub)
            if (idx + 1) % freq == 0:
                if k == 1:
                    # ref: prediction_early_stop.cpp CreateBinary uses
                    # margin = 2 * |pred|
                    stop = 2.0 * np.abs(out[rows, 0]) > margin
                else:
                    part = np.partition(out[rows], k - 2, axis=1)
                    stop = (part[:, -1] - part[:, -2]) > margin
                active[rows[stop]] = False
        return out

    def predict(self, data: np.ndarray, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False,
                predict_chunk: Optional[int] = None) -> np.ndarray:
        if pred_leaf:
            return self.predict_leaf(data, start_iteration, num_iteration)
        if pred_contrib:
            return self.predict_contrib(data, start_iteration, num_iteration,
                                        predict_chunk=predict_chunk)
        raw = self.predict_raw(data, start_iteration, num_iteration,
                               predict_chunk=predict_chunk)
        if raw.shape[1] == 1:
            raw = raw[:, 0]
        if raw_score or self.objective is None:
            return raw
        return self.objective.convert_output(raw)

    def predict_leaf(self, data: np.ndarray, start_iteration: int = 0,
                     num_iteration: int = -1) -> np.ndarray:
        data = np.asarray(data, np.float64)
        end = len(self.models) if num_iteration < 0 else \
            min(len(self.models), start_iteration + num_iteration)
        cols = []
        for it in range(start_iteration, end):
            for tree in self.models[it]:
                cols.append(tree.predict_leaf(data))
        return np.stack(cols, axis=1) if cols else \
            np.zeros((data.shape[0], 0), np.int32)

    def predict_contrib(self, data: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1,
                        predict_chunk: Optional[int] = None) -> np.ndarray:
        """SHAP values via the tree-path algorithm (ref: tree.h
        PredictContrib). Routed through the batched device kernel
        (ops/shap.py) unless config.tpu_shap says off or the model has
        linear-tree leaves (shap.py owns the dispatch)."""
        from .shap import predict_contrib
        return predict_contrib(self, data, start_iteration, num_iteration,
                               predict_chunk=predict_chunk)

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        """(ref: GBDT::FeatureImportance gbdt.cpp — num_iteration <= 0
        means all trees)"""
        end = len(self.models) if iteration <= 0 else min(
            len(self.models), iteration)
        imp = np.zeros(self.train_set.num_total_features)
        for it in range(end):
            for tree in self.models[it]:
                for nd in range(tree.num_internal):
                    if tree.left_child[nd] == -1 and \
                            tree.right_child[nd] == -1:
                        continue
                    # only positive-gain splits count (ref:
                    # GBDT::FeatureImportance gbdt_model_text.cpp)
                    if tree.split_gain[nd] <= 0.0:
                        continue
                    f = tree.split_feature[nd]
                    if importance_type == "split":
                        imp[f] += 1
                    else:
                        imp[f] += tree.split_gain[nd]
        return imp

    @property
    def num_trees(self) -> int:
        return self.current_iteration() * self.num_tree_per_iteration

    def current_iteration(self) -> int:
        return len(self._host_models) + len(self._device_records)


class DART(GBDT):
    """Dropouts meet MART (ref: src/boosting/dart.hpp:24)."""

    boosting_type = "dart"

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        # per-NEW-iteration weights used by weighted drop selection
        # (ref: dart.hpp:200 tree_weight_, :68 push_back(shrinkage_rate_))
        self._tree_weights: List[float] = []
        self._sum_tree_weight = 0.0
        self._num_init_iteration = 0
        # fused-path state: dropped-tree contributions are recomputed on
        # device from a [T, K, N] leaf-assignment history, so a DART
        # iteration stays one XLA program with zero host round-trips
        self._dart = None            # device buffers
        self._dart_t = 0             # fused iterations stored
        self._dart_base = 0          # _host_models index of first fused iter
        self._dart_unshrunk: List[dict] = []  # host unshrunk records
        self._dart_fused = None      # jitted program
        self._dart_fast_disabled = False
        self._cur_shrinkage = float(config.learning_rate)
        self._dart_update_score = None  # see _slow_score_update

    def init_from_loaded(self, loaded) -> None:
        super().init_from_loaded(loaded)
        # loaded trees are never dropped (ref: dart.hpp num_init_iteration_)
        self._num_init_iteration = len(self._host_models)

    def _tree_shrinkage(self) -> float:
        # the DART shrinkage is the drop-count-dependent factor set
        # BEFORE the new tree trains (ref: dart.hpp:139-147
        # shrinkage_rate_ update in DroppingTrees); the reference's
        # Normalize never rescales the new tree — and the bias of a
        # first tree is added AFTER this shrinkage (gbdt.cpp:426)
        return self._cur_shrinkage

    def _slow_score_update(self, tree, lv32: np.ndarray, row_leaf, k):
        # bit-aligned with the fused DART program's creation add
        # (`scores_adj + old_factor*delta + new_factor*lv[row_leaf]`):
        # PRE-shrinkage f32 leaf values, gathered FIRST, then multiplied
        # by the f32 drop-factor and added in one XLA program — the same
        # FMA-contraction shape, so drop-free iterations are bitwise
        # identical between the paths. (The GBDT twin multiplies before
        # the gather because ITS fused program does; the shapes must
        # each match their own fused path, not each other.) Drop-cycle
        # iterations still subtract/re-add f64 host leaf values and keep
        # ulp-level drift — the multiclass knife-edge this kills is a
        # split flip born in the drop-FREE early iterations.
        if self._dart_update_score is None:
            self._dart_update_score = jax.jit(
                lambda score, lv, nf, rl: score + nf * lv[rl])
        return self._dart_update_score(
            self.scores[k], jnp.asarray(lv32),
            jnp.float32(self._tree_shrinkage()), row_leaf)

    # -- fused path ----------------------------------------------------
    def _fast_path_ok(self, custom_grad) -> bool:
        if self._dart_fast_disabled or \
                not self._fast_path_core_ok(custom_grad):
            return False
        cfg = self.config
        if cfg.max_drop <= 0:
            return False  # unbounded drop count has no static shape
        # new trees grown by the host loop are missing from the device
        # drop history; fused mode only starts on a clean booster
        if self._dart_t == 0 and \
                len(self._host_models) > self._num_init_iteration:
            return False
        k = self.num_tree_per_iteration
        leaves = int(cfg.num_leaves)
        t_cap = max(int(cfg.num_iterations), 64, self._dart_t * 2)
        nv = sum(vs.num_data for vs, _ in self._valid_sets)
        item = 1 if leaves <= 256 else (2 if leaves <= 65536 else 4)
        need = t_cap * k * ((self.num_data + nv) * item + leaves * 4 + 4)
        return need <= int(cfg.tpu_dart_fused_max_bytes)

    def _dart_hist_dtype(self):
        leaves = int(self.config.num_leaves)
        return (jnp.uint8 if leaves <= 256
                else jnp.uint16 if leaves <= 65536 else jnp.int32)

    def _ensure_dart_state(self) -> None:
        k = self.num_tree_per_iteration
        leaves = self._static["num_leaves"]
        dt = self._dart_hist_dtype()
        if self._dart is None:
            t_cap = max(int(self.config.num_iterations), 64)
            self._dart_base = len(self._host_models)
            self._dart = {
                "leaf_hist": jnp.zeros((t_cap, k, self.num_data), dt),
                "vhist": [jnp.zeros((t_cap, k, vs.num_data), dt)
                          for vs, _ in self._valid_sets],
                "leaf_vals": jnp.zeros((t_cap, k, leaves), jnp.float32),
                "factors": jnp.zeros((t_cap,), jnp.float32),
            }
        elif self._dart_t >= self._dart["leaf_hist"].shape[0]:
            # double capacity (continued training past num_iterations);
            # the jit re-specializes on the new shapes automatically
            def grow_buf(b):
                pad = [(0, b.shape[0])] + [(0, 0)] * (b.ndim - 1)
                return jnp.pad(b, pad)
            st = self._dart
            st["leaf_hist"] = grow_buf(st["leaf_hist"])
            st["vhist"] = [grow_buf(v) for v in st["vhist"]]
            st["leaf_vals"] = grow_buf(st["leaf_vals"])
            st["factors"] = grow_buf(st["factors"])

    def _dart_factors(self, k_drop: int):
        """(new_factor, old_factor) as python floats
        (ref: dart.hpp:139-147 shrinkage bookkeeping + :159 Normalize)."""
        lr = float(self.config.learning_rate)
        if self.config.xgboost_dart_mode:
            new_factor = lr if k_drop == 0 else lr / (lr + k_drop)
            old_factor = k_drop / (k_drop + lr)
        else:
            new_factor = lr / (1.0 + k_drop)
            old_factor = k_drop / (k_drop + 1.0)
        return new_factor, old_factor

    def _update_drop_weights(self, drop_slots: List[int]) -> None:
        """Weighted-mode bookkeeping after renormalizing k dropped trees
        — shared by the host and fused paths so their tested exact parity
        can't desynchronize (ref: dart.hpp:159-196 Normalize, including
        the reference's xgboost-mode quirk of subtracting w/(k+lr) rather
        than the true delta w*lr/(k+lr), dart.hpp:175,193).
        `drop_slots` are NEW-tree indices (init offset excluded)."""
        if self.config.uniform_drop or not drop_slots:
            return
        k_drop = len(drop_slots)
        lr = float(self.config.learning_rate)
        _new, old_factor = self._dart_factors(k_drop)
        sub = (1.0 / (k_drop + lr) if self.config.xgboost_dart_mode
               else 1.0 / (k_drop + 1.0))
        for s in drop_slots:
            self._sum_tree_weight -= self._tree_weights[s] * sub
            self._tree_weights[s] *= old_factor

    def _make_fused_dart(self):
        """One-XLA-program DART iteration. Drop selection happens on the
        host from host-held tree weights (no device data involved), the
        dropped trees' score contributions are recomputed on device by
        indexing the leaf-assignment history, and normalization
        (dart.hpp:159) becomes a per-tree factor buffer update — the
        model's trees materialize later as unshrunk records x factors."""
        grow = self._grow_partial()
        xgb_mode = bool(self.config.xgboost_dart_mode)
        k_per = self.num_tree_per_iteration
        sentinel = self._health_armed

        # the reference bakes the boost-from-average bias into the first
        # tree AFTER its score update (gbdt.cpp:426 AddBias), so dropped
        # first trees carry the bias and later normalizations scale it.
        # The history buffer therefore stores lv + bias/creation_factor
        # for iteration 0: factor[t] * buffer then reproduces the
        # reference's current leaf values at every later point in time.
        with_bias = self._dart_base == 0 and any(
            abs(s) > K_EPSILON for s in self.init_scores)
        init_vec = jnp.asarray(np.asarray(self.init_scores, np.float32))

        def fused(bins_fm, valid_bins, obj_state, scores, sample_mask,
                  valid_scores, leaf_hist, vhists, leaf_vals, factors,
                  dropped, n_drop, t_cur, it, lr):
            obj = self.objective
            old_state = obj.swap_device_state(obj_state)
            try:
                t_max = leaf_hist.shape[0]
                key = jax.random.fold_in(self._bagging_key, it)
                sample_mask = self._sampling_in_jit(
                    jax.random.fold_in(key, 1), it, sample_mask)

                live = dropped >= 0                      # [D]
                d_gather = jnp.where(live, dropped, 0)
                d_scatter = jnp.where(live, dropped, t_max)  # OOB = no-op
                fac_d = factors[d_gather] * live.astype(jnp.float32)

                def drop_delta(hist, vals):
                    h = jnp.take(hist, d_gather, axis=0).astype(jnp.int32)
                    v = jnp.take(vals, d_gather, axis=0) * \
                        fac_d[:, None, None]
                    return jnp.take_along_axis(v, h, axis=2).sum(axis=0)

                delta = drop_delta(leaf_hist, leaf_vals)      # [K, N]
                deltas_v = [drop_delta(vhists[vi], leaf_vals)
                            for vi in range(len(valid_bins))]
                scores_adj = scores - delta
                grad_all, hess_all = self._grad_fn(scores_adj)

                kd = n_drop.astype(jnp.float32)
                if xgb_mode:
                    new_factor = jnp.where(n_drop > 0, lr / (lr + kd), lr)
                    old_factor = kd / (kd + lr)
                else:
                    new_factor = lr / (1.0 + kd)
                    old_factor = kd / (kd + 1.0)

                hd = leaf_hist.dtype
                recs = []
                new_valid = list(valid_scores)
                new_vhists = list(vhists)
                for k in range(k_per):
                    rec, row_leaf = self._grow_class_traced(
                        grow, bins_fm, k, key, grad_all[k], hess_all[k],
                        sample_mask, scores_adj[k], it)
                    lv = jnp.where(rec.num_leaves > 1, rec.leaf_value, 0.0)
                    scores = scores.at[k].set(
                        scores_adj[k] + old_factor * delta[k]
                        + new_factor * lv[row_leaf])
                    leaf_hist = leaf_hist.at[t_cur, k].set(
                        row_leaf.astype(hd))
                    lv_store = lv
                    if with_bias:
                        # bias applies to 1-LEAF first-iteration trees
                        # too: the reference's constant tree carries
                        # leaf_value == init (AsConstantTree), and a
                        # drop must subtract it — a class with (near-)
                        # empty data keeps a 1-leaf tree whose bias the
                        # history would otherwise lose (multiclass DART
                        # parity, tests/test_engine.py)
                        lv_store = lv + jnp.where(
                            t_cur == 0, init_vec[k] / new_factor, 0.0)
                    leaf_vals = leaf_vals.at[t_cur, k].set(lv_store)
                    for vi in range(len(valid_bins)):
                        vleaf = replay_tree(
                            rec, valid_bins[vi], self.feature_meta,
                            self._bundle,
                            num_data=self._valid_sets[vi][0].num_data)
                        new_valid[vi] = new_valid[vi].at[k].set(
                            new_valid[vi][k]
                            - (1.0 - old_factor) * deltas_v[vi][k]
                            + new_factor * lv[vleaf])
                        new_vhists[vi] = new_vhists[vi].at[t_cur, k].set(
                            vleaf.astype(hd))
                    recs.append(rec)
                factors = factors.at[d_scatter].multiply(old_factor)
                factors = factors.at[t_cur].set(new_factor)
                stacked = _stack_class_records(recs)
                out_state = obj.device_state(evolving_only=True)
                outs = (scores, sample_mask, tuple(new_valid), stacked,
                        out_state, leaf_hist, tuple(new_vhists), leaf_vals,
                        factors)
                if sentinel:  # see _make_fused: pure extra reductions
                    outs = outs + (_nonfinite_counts(
                        grad_all, hess_all, scores),)
                return outs
            finally:
                obj.swap_device_state(old_state)

        return obs_xla.instrumented_jit("boosting/fused_dart_iter", fused,
                                        phase="train",
                                        donate_argnums=(3, 4, 5, 6, 7, 8, 9))

    # -- streamed DART twin (tpu_stream): _make_fused_dart's math split
    # at the same materialization boundaries as the GBDT streamed path
    def _make_stream_dart_prep(self):
        xgb_mode = bool(self.config.xgboost_dart_mode)
        n_valid = len(self._valid_sets)

        def prep(obj_state, scores, sample_mask, leaf_hist, vhists,
                 leaf_vals, factors, dropped, n_drop, it, lr):
            obj = self.objective
            old = obj.swap_device_state(obj_state)
            try:
                key = jax.random.fold_in(self._bagging_key, it)
                sample_mask = self._sampling_in_jit(
                    jax.random.fold_in(key, 1), it, sample_mask)
                live = dropped >= 0
                d_gather = jnp.where(live, dropped, 0)
                fac_d = factors[d_gather] * live.astype(jnp.float32)

                def drop_delta(hist, vals):
                    h = jnp.take(hist, d_gather, axis=0).astype(jnp.int32)
                    v = jnp.take(vals, d_gather, axis=0) * \
                        fac_d[:, None, None]
                    return jnp.take_along_axis(v, h, axis=2).sum(axis=0)

                delta = drop_delta(leaf_hist, leaf_vals)
                deltas_v = tuple(drop_delta(vhists[vi], leaf_vals)
                                 for vi in range(n_valid))
                scores_adj = scores - delta
                grad_all, hess_all = self._grad_fn(scores_adj)
                kd = n_drop.astype(jnp.float32)
                if xgb_mode:
                    new_factor = jnp.where(n_drop > 0, lr / (lr + kd), lr)
                    old_factor = kd / (kd + lr)
                else:
                    new_factor = lr / (1.0 + kd)
                    old_factor = kd / (kd + 1.0)
                out_state = obj.device_state(evolving_only=True)
                return (sample_mask, scores_adj, delta, deltas_v,
                        grad_all, hess_all, new_factor, old_factor,
                        out_state)
            finally:
                obj.swap_device_state(old)
        return prep

    def _make_stream_dart_post(self, k: int):
        hd = self._dart_hist_dtype()
        with_bias = self._dart_base == 0 and any(
            abs(s) > K_EPSILON for s in self.init_scores)
        init_vec = jnp.asarray(np.asarray(self.init_scores, np.float32))

        def post(obj_state, rec, row_leaf, scores, scores_adj, delta,
                 valid_scores, valid_bins, vhists, leaf_hist, leaf_vals,
                 new_factor, old_factor, deltas_v, t_cur, mask,
                 true_grad, true_hess):
            obj = self.objective
            old = obj.swap_device_state(obj_state) if obj is not None \
                else None
            try:
                if self._quant_enabled and \
                        self.config.quant_train_renew_leaf:
                    rec = self._renew_leaves_in_jit(
                        rec, row_leaf, true_grad, true_hess, mask)
                if obj is not None:
                    renewed_lv = obj.renew_leaves_traced(
                        rec.leaf_value, row_leaf, scores_adj[k], mask)
                    if renewed_lv is not None:
                        rec = rec._replace(leaf_value=jnp.where(
                            rec.num_leaves > 1, renewed_lv,
                            rec.leaf_value))
                lv = jnp.where(rec.num_leaves > 1, rec.leaf_value, 0.0)
                scores = scores.at[k].set(
                    scores_adj[k] + old_factor * delta[k]
                    + new_factor * lv[row_leaf])
                leaf_hist = leaf_hist.at[t_cur, k].set(
                    row_leaf.astype(hd))
                lv_store = lv
                if with_bias:
                    # see _make_fused_dart: first-iteration trees carry
                    # bias/creation_factor in the history buffer
                    lv_store = lv + jnp.where(
                        t_cur == 0, init_vec[k] / new_factor, 0.0)
                leaf_vals = leaf_vals.at[t_cur, k].set(lv_store)
                new_valid = list(valid_scores)
                new_vhists = list(vhists)
                for vi in range(len(valid_bins)):
                    vleaf = replay_tree(
                        rec, valid_bins[vi], self.feature_meta,
                        self._bundle,
                        num_data=self._valid_sets[vi][0].num_data)
                    new_valid[vi] = new_valid[vi].at[k].set(
                        new_valid[vi][k]
                        - (1.0 - old_factor) * deltas_v[vi][k]
                        + new_factor * lv[vleaf])
                    new_vhists[vi] = new_vhists[vi].at[t_cur, k].set(
                        vleaf.astype(hd))
                return (rec, scores, tuple(new_valid),
                        tuple(new_vhists), leaf_hist, leaf_vals)
            finally:
                if obj is not None:
                    obj.swap_device_state(old)
        return post

    def _make_stream_dart_factors(self):
        def upd(factors, dropped, t_cur, new_factor, old_factor):
            t_max = factors.shape[0]
            live = dropped >= 0
            d_scatter = jnp.where(live, dropped, t_max)  # OOB = no-op
            factors = factors.at[d_scatter].multiply(old_factor)
            return factors.at[t_cur].set(new_factor)
        return upd

    def _train_one_iter_fused_upload(self) -> bool:
        """Single-slab streamed DART: the fused DART program on a
        per-iteration upload of the bins (see the GBDT twin)."""
        import time as _time
        from .io.streaming import global_stream_stats as _stats
        self._boost_from_average()
        self._ensure_dart_state()
        drop_slots = self._select_drop(self._dart_t)
        n_drop = len(drop_slots)
        global_metrics.observe("dart_dropped_trees", n_drop)
        d_cap = max(int(self.config.max_drop), 1)
        dropped = np.full(d_cap, -1, np.int32)
        dropped[:n_drop] = drop_slots
        if self._dart_fused is None:
            with global_tracer.span("train/compile_fused"):
                self._dart_fused = self._make_fused_dart()
        st = self._dart
        bins = self._stream_take_bins()
        with global_tracer.span("train/iteration",
                                block=lambda: self.scores):
            out = self._dart_fused(
                bins, tuple(self._valid_bins), self._obj_state(),
                self.scores, self._sample_mask, tuple(self._valid_scores),
                st["leaf_hist"], tuple(st["vhist"]), st["leaf_vals"],
                st["factors"], jnp.asarray(dropped), jnp.int32(n_drop),
                jnp.int32(self._dart_t), jnp.int32(self.iter),
                jnp.float32(self.config.learning_rate))
            self._stream_prefetch_bins()
            if self._health_armed:
                out, self._health_vec = out[:-1], out[-1]
            (self.scores, self._sample_mask, valid, recs, new_obj_state,
             st["leaf_hist"], vhist, st["leaf_vals"],
             st["factors"]) = out
            t0 = _time.perf_counter()
            jax.block_until_ready(self.scores)
            _stats.note_block(_time.perf_counter() - t0)
        st["vhist"] = list(vhist)
        if self.objective is not None:
            self.objective.swap_device_state(new_obj_state)
        self._valid_scores = list(valid)
        self._device_records.append(recs)
        self._dart_t += 1
        self.iter += 1
        _stats.iterations_total += 1
        self._note_stream_meta()
        new_factor, _old = self._dart_factors(n_drop)
        self._update_drop_weights(drop_slots)
        self._tree_weights.append(new_factor)
        self._sum_tree_weight += new_factor
        return False

    def _train_one_iter_stream_orchestrated(self) -> bool:
        import time as _time
        self._boost_from_average()
        self._ensure_dart_state()
        from .io.streaming import global_stream_stats as _stats
        drop_slots = self._select_drop(self._dart_t)
        n_drop = len(drop_slots)
        global_metrics.observe("dart_dropped_trees", n_drop)
        d_cap = max(int(self.config.max_drop), 1)
        dropped = np.full(d_cap, -1, np.int32)
        dropped[:n_drop] = drop_slots
        dropped = jnp.asarray(dropped)
        st = self._dart
        prep = self._stream_prog("dart_prep", self._make_stream_dart_prep)
        with global_tracer.span("train/iteration",
                                block=lambda: self.scores):
            it = jnp.int32(self.iter)
            t_cur = jnp.int32(self._dart_t)
            (sample_mask, scores_adj, delta, deltas_v, grad_all,
             hess_all, new_f, old_f, new_obj_state) = prep(
                self._obj_state(), self.scores, self._sample_mask,
                st["leaf_hist"], tuple(st["vhist"]), st["leaf_vals"],
                st["factors"], dropped, jnp.int32(n_drop), it,
                jnp.float32(self.config.learning_rate))
            self._sample_mask = sample_mask
            if self.objective is not None:
                self.objective.swap_device_state(new_obj_state)
            recs = []
            scores = self.scores
            valid = tuple(self._valid_scores)
            vhists = tuple(st["vhist"])
            leaf_hist, leaf_vals = st["leaf_hist"], st["leaf_vals"]
            for k in range(self.num_tree_per_iteration):
                rec, row_leaf, mask, true_g, true_h = \
                    self._stream_grow_class(k, grad_all[k], hess_all[k],
                                            sample_mask, it)
                post = self._stream_prog(
                    f"dart_post_{k}",
                    lambda k=k: self._make_stream_dart_post(k))
                (rec, scores, valid, vhists, leaf_hist, leaf_vals) = \
                    post(self._obj_state(), rec, row_leaf, scores,
                         scores_adj, delta, valid,
                         tuple(self._valid_bins), vhists, leaf_hist,
                         leaf_vals, new_f, old_f, deltas_v, t_cur,
                         mask, true_g, true_h)
                recs.append(rec)
            fac = self._stream_prog("dart_factors",
                                    self._make_stream_dart_factors)
            st["factors"] = fac(st["factors"], dropped, t_cur, new_f,
                                old_f)
            self.scores = scores
            self._valid_scores = list(valid)
            st["vhist"] = list(vhists)
            st["leaf_hist"], st["leaf_vals"] = leaf_hist, leaf_vals
            if self._health_armed:
                sen = self._stream_prog(
                    "sentinel", lambda: _nonfinite_counts)
                self._health_vec = sen(grad_all, hess_all, self.scores)
            t0 = _time.perf_counter()
            jax.block_until_ready(self.scores)
            _stats.note_block(_time.perf_counter() - t0)
        _stats.iterations_total += 1
        self._device_records.append(_stack_class_records(recs))
        self._dart_t += 1
        self.iter += 1
        self._note_stream_meta()
        new_factor, _old = self._dart_factors(n_drop)
        self._update_drop_weights(drop_slots)
        self._tree_weights.append(new_factor)
        self._sum_tree_weight += new_factor
        return False

    def _train_one_iter_fast(self) -> bool:
        """Fused DART iteration (the DART twin of the GBDT fast path)."""
        if self._stream is not None:
            return self._train_one_iter_stream()
        self._boost_from_average()
        self._ensure_dart_state()
        drop_slots = self._select_drop(self._dart_t)
        n_drop = len(drop_slots)
        global_metrics.observe("dart_dropped_trees", n_drop)
        d_cap = max(int(self.config.max_drop), 1)
        dropped = np.full(d_cap, -1, np.int32)
        dropped[:n_drop] = drop_slots
        if self._dart_fused is None:
            with global_tracer.span("train/compile_fused"):
                self._dart_fused = self._make_fused_dart()
        st = self._dart
        with global_tracer.span("train/iteration",
                                block=lambda: self.scores):
            out = self._dart_fused(
                self.bins_fm, tuple(self._valid_bins), self._obj_state(),
                self.scores, self._sample_mask, tuple(self._valid_scores),
                st["leaf_hist"], tuple(st["vhist"]), st["leaf_vals"],
                st["factors"], jnp.asarray(dropped), jnp.int32(n_drop),
                jnp.int32(self._dart_t), jnp.int32(self.iter),
                jnp.float32(self.config.learning_rate))
            if self._health_armed:
                out, self._health_vec = out[:-1], out[-1]
            (self.scores, self._sample_mask, valid, recs, new_obj_state,
             st["leaf_hist"], vhist, st["leaf_vals"],
             st["factors"]) = out
        st["vhist"] = list(vhist)
        if self.objective is not None:
            self.objective.swap_device_state(new_obj_state)
        self._valid_scores = list(valid)
        self._device_records.append(recs)
        self._dart_t += 1
        self.iter += 1
        # host weight bookkeeping — uses only host-known values (drop
        # count), so no device sync happens
        new_factor, _old = self._dart_factors(n_drop)
        self._update_drop_weights(drop_slots)
        self._tree_weights.append(new_factor)
        self._sum_tree_weight += new_factor
        return False

    def _materialize_records_inner(self) -> None:
        if self._dart is None:
            return super()._materialize_records_inner()
        # fused DART: records hold UNSHRUNK leaf values; the applied
        # factors evolve retroactively (Normalize rescales dropped trees),
        # so all fused-born trees are rebuilt from the kept unshrunk
        # records x the factor buffer's current snapshot.
        recs = self._device_records
        self._device_records, self._record_lrs = [], []
        if recs:
            host = _records_to_host(recs)
            for i in range(len(recs)):
                self._dart_unshrunk.append(
                    {f: np.asarray(getattr(host, f)[i])
                     for f in host._fields})
        factors = np.asarray(jax.device_get(self._dart["factors"]))
        # leaf values come from the history buffer (unshrunk + the first
        # iteration's bias/creation_factor term) x current factor — the
        # exact quantity the device drop path subtracts, and the
        # reference's post-Normalize leaf values (dart.hpp:159)
        buf_vals = np.asarray(jax.device_get(self._dart["leaf_vals"]))
        k_per = self.num_tree_per_iteration
        base = self._dart_base
        # incremental rebuild: only trees whose factor changed since the
        # last snapshot (the dropped ones) plus the not-yet-built tail —
        # a per-iteration predict() loop stays O(drops), not O(T^2)
        prev = getattr(self, "_dart_factor_snapshot", None)
        built = len(self._host_models) - base
        for i, rec_all in enumerate(self._dart_unshrunk):
            if i < built and prev is not None and i < len(prev) and \
                    factors[i] == prev[i]:
                continue
            first_iter = (base + i) == 0
            iter_trees = []
            for k in range(k_per):
                rec = {f: rec_all[f][k] for f in rec_all}
                tree = Tree.from_arrays(rec, self.train_set.mappers,
                                        self.train_set.used_features)
                if tree.num_leaves > 1 or first_iter:
                    # constant FIRST-iteration trees rebuild from the
                    # history buffer too: their bias rides it
                    # (init/creation_factor), so factor x buffer
                    # reproduces the reference's post-Normalize value
                    # when the tree has been dropped/rescaled
                    if tree.num_leaves > 1:
                        tree.apply_shrinkage(float(factors[i]))
                    tree.leaf_value[:] = (
                        factors[i] * buf_vals[i][k][:len(tree.leaf_value)]
                    ).astype(tree.leaf_value.dtype)
                else:
                    tree.leaf_value[:] = 0.0
                iter_trees.append(tree)
            if i < built:
                self._host_models[base + i] = iter_trees
            else:
                self._host_models.append(iter_trees)
        self._dart_factor_snapshot = factors.copy()

    def _freeze_dart_fused(self) -> None:
        """Materialize fused-born trees with their final factors and hand
        authority to the host Tree objects (after this, Normalize mutates
        them directly and the records must never be re-applied)."""
        self._materialize_records()
        self._dart_unshrunk = []
        self._dart = None
        self._dart_fused = None

    def add_valid(self, valid_set, raw_data) -> None:
        super().add_valid(valid_set, raw_data)
        if self._dart_t > 0:
            # past trees have no leaf history on the new valid set
            self._freeze_dart_fused()
            self._dart_fast_disabled = True
        else:
            self._dart = None
            self._dart_fused = None

    def rollback_one_iter(self) -> None:
        if self.iter <= 0:
            return
        if self._dart_t > 0 or self._device_records:
            # factor rewind isn't representable in the fused buffers
            self._freeze_dart_fused()
            self._dart_fast_disabled = True
        super().rollback_one_iter()
        if self._tree_weights:
            w = self._tree_weights.pop()
            self._sum_tree_weight -= w

    def _train_one_iter_impl(self, custom_grad=None,
                             custom_hess=None) -> bool:
        if self._fast_path_ok(custom_grad):
            return self._train_one_iter_fast()
        if self._dart_t > 0 or self._device_records:
            self._freeze_dart_fused()
        self._dart_fast_disabled = True
        drop_idx = [self._num_init_iteration + i for i in self._select_drop(
            len(self.models) - self._num_init_iteration)]
        global_metrics.observe("dart_dropped_trees", len(drop_idx))
        # subtract dropped trees from scores (dart.hpp DroppingTrees)
        for di in drop_idx:
            self._add_tree_scores(self.models[di], sign=-1.0)

        new_factor, _old = self._dart_factors(len(drop_idx))
        self._cur_shrinkage = new_factor
        stop = super()._train_one_iter_impl(custom_grad, custom_hess)
        if not stop:
            self._normalize(drop_idx)
            # the new tree's weight is its actual applied factor
            # (ref: dart.hpp:68 push_back(shrinkage_rate_) where
            # shrinkage_rate_ was updated by DroppingTrees :139-147)
            self._tree_weights.append(new_factor)
            self._sum_tree_weight += new_factor
        for di in drop_idx:
            self._add_tree_scores(self.models[di], sign=1.0)
        return stop

    def _add_tree_scores(self, trees, sign: float) -> None:
        for k, tree in enumerate(trees):
            leaves = self._predict_leaf_binned_train(tree)
            self.scores = self.scores.at[k].add(jnp.asarray(
                (sign * tree.leaf_value).astype(np.float32))[leaves])
        for i, (vs, raw) in enumerate(self._valid_sets):
            for k, tree in enumerate(trees):
                self._valid_scores[i] = self._valid_scores[i].at[k].add(
                    jnp.asarray(sign * tree.predict(self._valid_raw(i))
                                .astype(np.float32)))

    def _select_drop(self, n_new: int) -> List[int]:
        """Select NEW-tree indices (0-based, init offset excluded) to drop
        (ref: dart.hpp:98 DroppingTrees). Weighted mode drops tree i with
        probability proportional to its current weight (ref:
        dart.hpp:104-116); weights shrink as trees get renormalized away
        (Normalize), so frequently-dropped trees become less likely to be
        dropped again. Host-only inputs (RNG + weight floats), so the
        fused path calls this without any device sync."""
        cfg = self.config
        if n_new == 0:
            return []
        if self._drop_rng.rand() < cfg.skip_drop:
            return []
        drop_rate = cfg.drop_rate
        sel: List[int] = []
        if not cfg.uniform_drop:
            sum_w = max(self._sum_tree_weight, 1e-30)
            inv_avg = n_new / sum_w
            if cfg.max_drop > 0:
                drop_rate = min(drop_rate, cfg.max_drop * inv_avg / sum_w)
            for i in range(n_new):
                if self._drop_rng.rand() < \
                        drop_rate * self._tree_weights[i] * inv_avg:
                    sel.append(i)
                    if cfg.max_drop > 0 and len(sel) >= cfg.max_drop:
                        break
        else:
            if cfg.max_drop > 0:
                drop_rate = min(drop_rate, cfg.max_drop / n_new)
            for i in range(n_new):
                if self._drop_rng.rand() < drop_rate:
                    sel.append(i)
                    if cfg.max_drop > 0 and len(sel) >= cfg.max_drop:
                        break
        return sel

    def _normalize(self, drop_idx: List[int]) -> None:
        """Scale the DROPPED trees to k/(k+1) (or k/(k+lr) in xgboost
        mode) of their old weight (ref: dart.hpp:159 Normalize — the new
        tree was already created at its final factor, like the
        reference's Shrinkage(shrinkage_rate_) at gbdt.cpp:423)."""
        _new_factor, old_factor = self._dart_factors(len(drop_idx))
        for di in drop_idx:
            for tree in self.models[di]:
                tree.apply_shrinkage(old_factor)
        self._update_drop_weights(
            [di - self._num_init_iteration for di in drop_idx])


class RF(GBDT):
    """Random forest mode (ref: src/boosting/rf.hpp:26): bagging required,
    no shrinkage, gradients always computed at the constant init score,
    output averaged over iterations."""

    boosting_type = "rf"

    def __init__(self, config, train_set, objective=None):
        if not (config.bagging_freq > 0 and
                (config.bagging_fraction < 1.0 or
                 config.feature_fraction < 1.0)):
            raise ValueError(
                "RF mode requires bagging (bagging_freq > 0 and "
                "bagging_fraction < 1) or feature_fraction < 1")
        super().__init__(config, train_set, objective)
        self._base_grad = None

    def _tree_shrinkage(self) -> float:
        return 1.0

    def _gradients(self, custom_grad=None, custom_hess=None):
        if custom_grad is not None:
            return super()._gradients(custom_grad, custom_hess)
        if self._base_grad is None:
            self._boost_from_average()
            init = jnp.asarray(
                np.asarray(self.init_scores, np.float32)[:, None])
            base_score = jnp.broadcast_to(
                init, (self.num_tree_per_iteration, self.num_data))
            obj = self.objective
            if hasattr(obj, "get_gradients_multi"):
                g, h = obj.get_gradients_multi(base_score)
            else:
                g0, h0 = obj.get_gradients(base_score[0])
                g, h = g0[None, :], h0[None, :]
            self._base_grad = (g, h)
        return self._base_grad

    def predict_raw(self, data, start_iteration=0, num_iteration=-1,
                    predict_chunk=None):
        out = super().predict_raw(data, start_iteration, num_iteration,
                                  predict_chunk=predict_chunk)
        end = len(self.models) if num_iteration < 0 else \
            min(len(self.models), start_iteration + num_iteration)
        cnt = max(end - start_iteration, 1)
        return out / cnt


def create_boosting(config: Config, train_set: BinnedDataset,
                    objective: Optional[ObjectiveFunction] = None) -> GBDT:
    """Factory (ref: Boosting::CreateBoosting src/boosting/boosting.cpp:42)."""
    cls = {"gbdt": GBDT, "dart": DART, "rf": RF}.get(config.boosting)
    if cls is None:
        raise ValueError(f"Unknown boosting type: {config.boosting}")
    return cls(config, train_set, objective)
