"""Streaming dataset construction (chunked row pushes).

TPU-native analog of the reference's ChunkedArray + streaming C API
(ref: include/LightGBM/utils/chunked_array.hpp, c_api.cpp:1330
LGBM_DatasetPushRows*, tests/cpp_tests/test_stream.cpp:253). Producers
push row blocks (with per-block label/weight/init-score/group slices)
as they arrive; `finalize()` coalesces once and bins — the same
copy-on-finalize contract ChunkedArray gives the reference's
distributed ingestion (Spark/SynapseML streaming)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class DatasetBuilder:
    """Accumulate row chunks, then produce a constructed Dataset.

    Example:
        b = DatasetBuilder(num_features=28, params={"max_bin": 63})
        for X_chunk, y_chunk in producer:
            b.push_rows(X_chunk, label=y_chunk)
        ds = b.finalize()
    """

    def __init__(self, num_features: int,
                 params: Optional[Dict[str, Any]] = None,
                 reference=None):
        self.num_features = int(num_features)
        self.params = dict(params or {})
        self.reference = reference
        self._chunks: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []
        self._weights: List[np.ndarray] = []
        self._init_scores: List[np.ndarray] = []
        self._groups: List[np.ndarray] = []
        self._finalized = False

    @property
    def num_pushed(self) -> int:
        return sum(c.shape[0] for c in self._chunks)

    def push_rows(self, data, label=None, weight=None, init_score=None,
                  group=None) -> "DatasetBuilder":
        """Append a [n, F] block (ref: LGBM_DatasetPushRows c_api.cpp).
        Metadata slices are per-block and optional, but each field must
        be provided either for every block or for none."""
        if self._finalized:
            raise RuntimeError("builder already finalized")
        block = np.atleast_2d(np.asarray(data, np.float64))
        if block.shape[1] != self.num_features:
            raise ValueError(
                f"pushed block has {block.shape[1]} features, expected "
                f"{self.num_features}")
        # validate everything BEFORE mutating, so a rejected push leaves
        # the builder unchanged
        fields = []
        for value, store, name in (
                (label, self._labels, "label"),
                (weight, self._weights, "weight"),
                (init_score, self._init_scores, "init_score"),
                (group, self._groups, "group")):
            if value is not None:
                if self._chunks and not store:
                    raise ValueError(
                        f"{name} was missing for earlier blocks but "
                        "provided for this one (all-or-none per field)")
                arr = np.asarray(value)
                if name != "group" and arr.shape[0] != block.shape[0]:
                    raise ValueError(
                        f"{name} slice has {arr.shape[0]} rows, block has "
                        f"{block.shape[0]}")
                fields.append((store, arr))
            elif store:
                raise ValueError(
                    f"{name} was provided for earlier blocks but missing "
                    "for this one")
        self._chunks.append(block)
        for store, arr in fields:
            store.append(arr)
        return self

    def finalize(self):
        """Coalesce chunks and construct the Dataset (one copy — the
        ChunkedArray coalesce contract)."""
        from ..basic import Dataset
        if self._finalized:
            raise RuntimeError("builder already finalized")
        if not self._chunks:
            raise ValueError("no rows pushed")
        self._finalized = True
        X = (self._chunks[0] if len(self._chunks) == 1
             else np.concatenate(self._chunks, axis=0))

        def _cat(parts):
            if not parts:
                return None
            return parts[0] if len(parts) == 1 else np.concatenate(parts)

        ds = Dataset(X, label=_cat(self._labels),
                     weight=_cat(self._weights),
                     init_score=_cat(self._init_scores),
                     group=_cat(self._groups),
                     reference=self.reference,
                     params=self.params)
        self._chunks.clear()
        self._labels.clear()
        self._weights.clear()
        self._init_scores.clear()
        self._groups.clear()
        return ds.construct()
