"""Training-health observability (obs/health.py; ISSUE 10): cross-shard
drift sentinels under injected divergence, NaN/Inf sentinels on poisoned
gradients, runtime-attributed collective counters (the PR-1 trace-time
counters' steady-state fix), straggler-skew math, the eval-loss anomaly
detector, bit-identity of trained models with health on vs off, and the
check_health / perf-gate wiring."""

import json
import os
import sys

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.health import (DriftError, NonFiniteError,
                                     global_health, tree_depths)
from lightgbm_tpu.parallel import mesh as mesh_lib

from conftest import make_binary, make_regression

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _fresh_health():
    global_health.reset()
    was = global_health.enabled
    yield
    global_health.enabled = was
    global_health.reset()


def _diverged_replicated(mesh, host, bad_shard, delta=1.0):
    """A fully-replicated array whose copy on `bad_shard` is perturbed —
    the physical state of a silently-diverged replica."""
    copies = []
    for i, dev in enumerate(mesh.devices.flat):
        h = host.copy()
        if i == bad_shard:
            h.flat[0] += delta
        copies.append(jax.device_put(h, dev))
    return jax.make_array_from_single_device_arrays(
        host.shape, NamedSharding(mesh, P()), copies)


# ---------------------------------------------------------------------------
class TestDriftSentinel:
    def test_injected_divergence_detected_and_attributed(self):
        mesh = mesh_lib.get_mesh(8)
        arr = _diverged_replicated(mesh, np.arange(32, dtype=np.float32), 3)
        mm = global_health.check_drift(mesh, {"scores": arr}, mode="warn")
        assert [(m["name"], m["shards"]) for m in mm] == [("scores", [3])]
        assert global_health.drift_mismatches == 1
        assert global_health.last_drift["mismatches"][0]["shards"] == [3]

    def test_error_mode_raises_drift_error(self):
        mesh = mesh_lib.get_mesh(8)
        arr = _diverged_replicated(mesh, np.ones(16, np.float32), 6)
        with pytest.raises(DriftError, match=r"shard\(s\) \[6\]"):
            global_health.check_drift(mesh, {"state": arr}, mode="error")

    def test_clean_replica_passes_even_with_nans(self):
        """Identical NaN state on every shard is consistent, not drift
        (NaNs are zeroed from the sums and counted separately)."""
        mesh = mesh_lib.get_mesh(8)
        host = np.arange(16, dtype=np.float32)
        host[2] = np.nan
        arr = jax.device_put(host, NamedSharding(mesh, P()))
        assert global_health.check_drift(mesh, {"s": arr},
                                         mode="error") == []

    def test_majority_vote_names_the_bad_shard_even_shard0(self):
        mesh = mesh_lib.get_mesh(8)
        arr = _diverged_replicated(mesh, np.ones(8, np.float32), 0)
        mm = global_health.check_drift(mesh, {"s": arr}, mode="warn")
        assert mm[0]["shards"] == [0]

    def test_two_shard_tie_reports_both_not_an_arbitrary_loser(self):
        """On a diverged 2-shard mesh the replicas are indistinguishable
        — both must be reported, never just the insertion-order loser."""
        mesh = mesh_lib.get_mesh(2)
        try:
            arr = _diverged_replicated(mesh, np.ones(8, np.float32), 0)
            mm = global_health.check_drift(mesh, {"s": arr}, mode="warn")
            assert mm[0]["shards"] == [0, 1]
        finally:
            mesh_lib.get_mesh(8)  # restore the shared 8-device mesh

    def test_booster_drift_detected_at_the_right_iteration(self):
        """tpu_health=error on the feature-parallel learner (replicated
        scores): healthy iterations pass, then one device's replica is
        perturbed and the NEXT iteration's end-of-iteration digest must
        raise DriftError; the warn-mode twin records instead."""
        X, y = make_binary(512)
        params = {"objective": "binary", "tree_learner": "feature",
                  "tpu_num_shards": 8, "num_leaves": 7, "tpu_wave_max": 0,
                  "min_data_in_leaf": 5, "verbosity": -1}
        bst = lgb.Booster({**params, "tpu_health": "error"},
                          lgb.Dataset(X, label=y))
        assert not bst.update()
        assert not bst.update()  # clean replicas: no alarm
        g = bst._gbdt
        g.scores = _diverged_replicated(g.mesh, np.asarray(g.scores), 2)
        with pytest.raises(DriftError, match="iteration 2"):
            bst.update()
        assert global_health.drift_mismatches >= 1

        global_health.reset()
        bst_w = lgb.Booster({**params, "tpu_health": "warn"},
                            lgb.Dataset(X, label=y))
        bst_w.update()
        gw = bst_w._gbdt
        gw.scores = _diverged_replicated(gw.mesh, np.asarray(gw.scores), 5)
        assert not bst_w.update()  # warn keeps training
        assert global_health.drift_mismatches >= 1
        assert global_health.last_drift["where"] == "iteration 1"


# ---------------------------------------------------------------------------
class TestNaNSentinel:
    def test_fast_path_error_raises_within_one_iteration(self):
        X, _ = make_regression(512)
        y = X[:, 0].astype(np.float64).copy()
        y[11] = np.nan  # one poisoned label -> NaN L2 gradient
        with pytest.raises(NonFiniteError, match="iteration 0"):
            lgb.train({"objective": "regression", "verbosity": -1,
                       "tpu_health": "error", "num_leaves": 7},
                      lgb.Dataset(X, label=y), num_boost_round=3)

    def test_warn_mode_records_and_keeps_training(self):
        X, _ = make_regression(512)
        y = X[:, 0].astype(np.float64).copy()
        y[11] = np.nan
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "tpu_health": "warn", "num_leaves": 7},
                        lgb.Dataset(X, label=y), num_boost_round=2)
        assert bst.current_iteration() == 2
        assert global_health.nonfinite.get("grad", 0) >= 1
        assert global_health.nonfinite_iterations == 2
        assert global_health.last_nonfinite["iteration"] == 1

    def test_slow_path_custom_gradients(self):
        """Custom fobj (slow path): the sentinel reads the gradient
        buffers that are already live — NaN custom grads trip it too."""
        X, y = make_binary(256)
        bst = lgb.Booster({"objective": "none", "verbosity": -1,
                           "tpu_health": "warn", "num_leaves": 7},
                          lgb.Dataset(X, label=y))

        def fobj(preds, ds):
            g = preds - y
            g[3] = np.nan
            return g, np.ones_like(g)

        bst.update(fobj=fobj)
        assert global_health.nonfinite.get("grad", 0) >= 1

    def test_health_every_skips_intermediate_iterations(self):
        X, _ = make_regression(300)
        y = X[:, 0].astype(np.float64).copy()
        y[0] = np.nan
        lgb.train({"objective": "regression", "verbosity": -1,
                   "tpu_health": "warn", "tpu_health_every": 2,
                   "num_leaves": 7},
                  lgb.Dataset(X, label=y), num_boost_round=4)
        # checks fire on every 2nd tick only
        assert global_health.nonfinite_iterations == 2


# ---------------------------------------------------------------------------
class TestBitIdentity:
    @staticmethod
    def _strip_params(model_str):
        return "\n".join(l for l in model_str.splitlines()
                         if "tpu_health" not in l)

    def test_model_bytes_identical_health_on_vs_off(self):
        """The sentinel adds pure reductions as extra program outputs;
        the trained trees must be bit-identical (only the echoed
        params line may differ)."""
        X, y = make_binary(512)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 7}
        off = lgb.train(dict(params), lgb.Dataset(X, label=y),
                        num_boost_round=4).model_to_string()
        on = lgb.train({**params, "tpu_health": "error"},
                       lgb.Dataset(X, label=y),
                       num_boost_round=4).model_to_string()
        assert self._strip_params(off) == self._strip_params(on)

    def test_disabled_path_is_guard_check_only(self, monkeypatch):
        """With health off nothing may reach the registry: break every
        recording entry point and train."""
        def boom(*a, **k):
            raise AssertionError("health touched while disabled")
        monkeypatch.setattr(global_health, "note_sentinel", boom)
        monkeypatch.setattr(global_health, "check_drift", boom)
        monkeypatch.setattr(global_health, "note_program_call", boom)
        monkeypatch.setattr(global_health, "straggler_probe", boom)
        global_health.disable()
        X, y = make_binary(256)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 7},
                        lgb.Dataset(X, label=y), num_boost_round=2)
        assert bst.current_iteration() == 2
        assert global_health.summary() == {}

    def test_unknown_health_mode_rejected(self):
        X, y = make_binary(128)
        with pytest.raises(ValueError, match="tpu_health"):
            lgb.Booster({"objective": "binary", "verbosity": -1,
                         "tpu_health": "sometimes"},
                        lgb.Dataset(X, label=y))


# ---------------------------------------------------------------------------
class TestRuntimeCollectives:
    def test_steady_state_counters_match_issued_calls(self):
        """The satellite fix of the PR-1 counters: trace-time counters
        freeze after the first compile, the health runtime counters
        must keep advancing by exactly one manifest per program call."""
        global_health.enable()
        X, y = make_binary(512)
        # pin the full-histogram psum oracle: this test exercises the
        # counter mechanics, not the reduction choice (test_scatter.py
        # covers the scatter tags)
        bst = lgb.Booster({"objective": "binary", "tree_learner": "voting",
                           "top_k": 2, "tpu_num_shards": 8,
                           "num_leaves": 7, "tpu_wave_max": 0,
                           "min_data_in_leaf": 5, "verbosity": -1,
                           "tpu_hist_reduce": "psum"},
                          lgb.Dataset(X, label=y))
        bst.update()
        snap1 = {t: dict(v) for t, v in global_health.runtime.items()}
        assert snap1, "no runtime collective attribution recorded"
        # root vote once + two votes per step, traced once but issued
        # L-1 times via the loop factor
        L = 7
        assert snap1["vote/all_gather"]["calls"] == 1 + 2 * (L - 1)
        assert snap1["vote/psum_hist"]["bytes"] > 0
        bst.update()  # steady state: no retrace, counters must still move
        for tag, ent in snap1.items():
            now = global_health.runtime[tag]
            assert now["calls"] == 2 * ent["calls"], tag
            assert now["bytes"] == 2 * ent["bytes"], tag

    def test_collective_probe_records_timing(self):
        global_health.enable()
        mesh = mesh_lib.get_mesh(8)
        out = global_health.probe_collectives(mesh)
        assert set(out) == {"psum", "all_gather", "psum_scatter"}
        for op in ("psum", "all_gather", "psum_scatter"):
            assert global_health.probe[op]["seconds"] > 0
            assert global_health.probe[op]["bytes"] > 0

    def test_feature_parallel_all_gather_attributed(self):
        global_health.enable()
        X, y = make_binary(512)
        bst = lgb.Booster({"objective": "binary",
                           "tree_learner": "feature",
                           "tpu_num_shards": 8, "num_leaves": 7,
                           "tpu_wave_max": 0, "min_data_in_leaf": 5,
                           "verbosity": -1}, lgb.Dataset(X, label=y))
        bst.update()
        ent = global_health.runtime.get("split/all_gather")
        assert ent and ent["op"] == "all_gather"
        assert ent["calls"] == 1 + 2 * (7 - 1)


# ---------------------------------------------------------------------------
class TestStraggler:
    def test_skew_math_and_worst_ordinal(self):
        s = global_health.straggler_from_matrix(
            ["grow", "update"],
            [[0.1, 0.2], [0.1, 0.2], [0.4, 0.2], [0.1, 0.2]])
        assert s["n_hosts"] == 4
        assert s["phases"]["grow"]["skew"] == pytest.approx(4.0)
        assert s["phases"]["grow"]["worst"] == 2
        assert s["phases"]["update"]["skew"] == pytest.approx(1.0)
        assert s["max_skew"] == pytest.approx(4.0)
        assert s["worst_phase"] == "grow"

    def test_probe_merges_worst_skew_across_probes(self):
        global_health.straggler_probe({"grow": 0.0})  # nothing yet
        global_health.straggler_probe({"grow": 0.5})
        first = global_health.straggler["phases"]["grow"]["skew"]
        # later quiet probe must not erase the recorded phase
        global_health.straggler_probe({"update": 0.1})
        assert "grow" in global_health.straggler["phases"]
        assert global_health.straggler["phases"]["grow"]["skew"] == first
        assert "update" in global_health.straggler["phases"]

    def test_tracer_fed_probe_single_host(self):
        from lightgbm_tpu.obs.trace import global_tracer
        was = global_tracer.enabled
        global_tracer.enable()
        try:
            with global_tracer.span("health_test/phase"):
                pass
            s = global_health.straggler_probe()
            assert s is not None and s["n_hosts"] == 1
        finally:
            if not was:
                global_tracer.disable()


# ---------------------------------------------------------------------------
class TestEvalAnomalies:
    def test_nan_flag(self):
        assert global_health.note_eval(0, "v", "l2", float("nan")) == \
            ["nan"]
        assert global_health.eval_anomalies["nan"] == 1

    def test_spike_flag(self):
        for i in range(6):
            global_health.note_eval(i, "v", "l2", 1.0)
        flags = global_health.note_eval(6, "v", "l2", 2.0)
        assert "spike" in flags
        # higher-is-better metrics spike DOWNWARD
        for i in range(6):
            global_health.note_eval(i, "v", "auc", 0.9, True)
        assert "spike" in global_health.note_eval(6, "v", "auc", 0.3, True)

    def test_plateau_flag(self):
        flags = []
        for i in range(12):
            flags = global_health.note_eval(i, "v", "l2", 0.5)
        assert "plateau" in flags

    def test_engine_feeds_eval_results(self):
        global_health.enable()
        X, y = make_regression(400)
        Xv, yv = make_regression(200, seed=1)
        lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 7},
                  lgb.Dataset(X, label=y), num_boost_round=3,
                  valid_sets=[lgb.Dataset(Xv, label=yv)])
        assert any(k.startswith("valid_0/") for k in
                   global_health._eval_hist)


# ---------------------------------------------------------------------------
class TestDiagnostics:
    def test_tree_depths_chain(self):
        d = tree_depths(np.asarray([0, 1, 2]))
        assert d.tolist() == [1, 2, 3, 3]
        assert tree_depths(np.asarray([-1, -1])).tolist() == [0]

    def test_bin_occupancy_meta_published(self):
        from lightgbm_tpu.obs.metrics import global_metrics
        X, y = make_binary(512)
        lgb.Booster({"objective": "binary", "verbosity": -1,
                     "max_bin": 63}, lgb.Dataset(X, label=y))
        hb = global_metrics.meta.get("health_bins")
        assert hb and hb["features"] == 8
        assert 0 < hb["bin_occupancy"] <= 1.0
        assert hb["trivial_features"] == 0

    def test_telemetry_iteration_carries_distributions(self):
        from lightgbm_tpu.callback import record_telemetry
        X, y = make_binary(512)
        rec = {}
        lgb.train({"objective": "binary", "verbosity": -1,
                   "num_leaves": 15},
                  lgb.Dataset(X, label=y), num_boost_round=2,
                  callbacks=[record_telemetry(rec)])
        last = {k: v[-1] for k, v in rec.items()
                if v and v[-1] is not None}
        assert last["tree_depth_max"] >= 1
        assert last["gain_p50"] <= last["gain_p90"] <= last["best_gain"]
        assert last["leaf_count_min"] <= last["leaf_count_median"] \
            <= last["leaf_count_max"]

    def test_replicated_detector(self):
        mesh = mesh_lib.get_mesh(8)
        rep = jax.device_put(np.ones(8, np.float32),
                             NamedSharding(mesh, P()))
        assert mesh_lib.is_replicated_on(mesh, rep)
        sharded = mesh_lib.shard_data(mesh, np.ones(64, np.float32), 0)
        assert not mesh_lib.is_replicated_on(mesh, sharded)
        assert not mesh_lib.is_replicated_on(mesh, np.ones(4))


# ---------------------------------------------------------------------------
class TestOpenMetricsAndTools:
    def test_health_families_render_and_validate(self):
        from lightgbm_tpu.obs.export import render_openmetrics
        from check_metrics_endpoint import validate_exposition
        mesh = mesh_lib.get_mesh(8)
        global_health.enable()
        global_health.probe_collectives(mesh)
        global_health.straggler_probe({"grow": 0.2})
        arr = _diverged_replicated(mesh, np.ones(8, np.float32), 1)
        global_health.check_drift(mesh, {"s": arr}, mode="warn")
        global_health.note_sentinel(3, {"grad": 2, "hess": 0,
                                        "scores": 0}, mode="warn")
        global_health.note_eval(0, "v", "l2", float("nan"))
        text = render_openmetrics()
        errors, families = validate_exposition(text)
        assert not errors, errors[:5]
        for fam in ("lgbmtpu_health_collective_seconds_total",
                    "lgbmtpu_health_straggler_skew",
                    "lgbmtpu_health_drift_mismatch_total",
                    "lgbmtpu_health_nonfinite_total",
                    "lgbmtpu_health_eval_anomalies_total"):
            assert fam in families, fam

    def test_disabled_summary_empty_and_no_families(self):
        from lightgbm_tpu.obs.export import render_openmetrics
        assert global_health.summary() == {}
        assert "lgbmtpu_health_" not in render_openmetrics()

    @pytest.mark.slow
    def test_check_health_tool(self):
        import check_health
        assert check_health.main() == 0

    def test_bench_health_fold_shape_is_json(self):
        """The summary bench.py folds must be JSON-serializable."""
        mesh = mesh_lib.get_mesh(8)
        global_health.enable()
        global_health.probe_collectives(mesh)
        global_health.straggler_probe({"grow": 0.3})
        json.dumps(global_health.summary())


# ---------------------------------------------------------------------------
class TestPerfGateHealthCheck:
    @staticmethod
    def _rec(health):
        return {"metric": "boosting_iters_per_sec_higgs_shape",
                "value": 50.0, "vs_baseline": 13.0,
                "unit": "iters/sec (N=10500000)",
                "hist_bytes_reduction": 1.35,
                "health": health}

    def test_skew_over_ceiling_fails(self, tmp_path, capsys):
        import check_perf_gate
        cand = tmp_path / "BENCH_candidate.json"
        cand.write_text(json.dumps(self._rec({
            "straggler": {"phases": {"train/grow": {
                "max_s": 1.0, "median_s": 0.1, "skew": 10.0,
                "worst": 3}}, "max_skew": 10.0}})))
        assert check_perf_gate.main([str(cand)]) == 1
        assert "straggler skew" in capsys.readouterr().out

    def test_collective_share_over_ceiling_fails(self, tmp_path, capsys):
        import check_perf_gate
        cand = tmp_path / "BENCH_candidate.json"
        cand.write_text(json.dumps(self._rec({
            "collectives_est": {"est_seconds": 9.0, "train_seconds": 10.0,
                                "time_share": 0.9}})))
        assert check_perf_gate.main([str(cand)]) == 1
        assert "collective time share" in capsys.readouterr().out

    def test_healthy_summary_passes(self, tmp_path, capsys):
        import check_perf_gate
        cand = tmp_path / "BENCH_candidate.json"
        cand.write_text(json.dumps(self._rec({
            "straggler": {"phases": {"train/grow": {
                "max_s": 1.0, "median_s": 0.9, "skew": 1.11,
                "worst": 0}}, "max_skew": 1.11},
            "collectives_est": {"est_seconds": 0.5,
                                "train_seconds": 10.0,
                                "time_share": 0.05}})))
        assert check_perf_gate.main([str(cand)]) == 0
        assert "straggler phase(s) checked" in capsys.readouterr().out

    def test_no_health_summaries_skips(self, capsys):
        import check_perf_gate
        assert check_perf_gate.main([]) == 0
        assert "health check skipped" in capsys.readouterr().out
