"""Training-health observability: collective timing & straggler
attribution, cross-shard drift sentinels, and model-quality diagnostics.

The third always-on-capable obs pillar, alongside ``obs/memory.py``
(capacity) and ``obs/xla.py`` (compiled-program facts). Those two
explain where bytes and compile time go; this module answers whether
training is *healthy* — the detection layer ROADMAP item 5's elastic
fault tolerance needs before it can react to anything. Three parts:

1. **Collective accounting & timing** — the psum/all_gather call sites
   in ``learner.py`` and ``parallel/{voting,feature_parallel}.py`` go
   through the :func:`psum` / :func:`all_gather` wrappers here. Each
   wrapper keeps the PR-1 trace-time counters alive AND registers the
   site (tag, op, payload bytes, loop trip count) into the *manifest*
   of the program being traced; every runtime invocation of an
   instrumented program (``obs/xla.instrumented_jit``) then multiplies
   its manifest into per-tag runtime counters — so steady-state
   iterations report the collectives actually issued, not the zero the
   trace-time-only counters showed after the first compile.
   :meth:`HealthRegistry.probe_collectives` adds device-synchronized
   wall time: a timed psum + all_gather microprobe over the real mesh,
   giving a measured seconds-per-byte rate per op (the first
   driver-visible view of ICI behavior in the multichip dryrun).

2. **Cross-shard drift sentinels** — :meth:`HealthRegistry.check_drift`
   digests replicated device state per shard inside ``shard_map``
   (sum / sum-of-squares / abs-sum / nonfinite-count per array, NaNs
   zeroed so identical-NaN state still matches) and ``all_gather``\\ s
   the digests across the mesh; any shard whose digest differs from
   the majority is a silently-diverged replica. Under the
   ``tpu_health`` knob: ``warn`` records + logs the mismatch,
   ``error`` raises a structured :class:`DriftError` — converting
   ROADMAP item 4's silent parity failures into an alarm.

3. **Model-quality diagnostics** — per-iteration NaN/Inf sentinel
   counts (``isfinite`` reductions folded into the fused training
   programs by ``boosting.py`` — the fused path stays fused),
   host-side straggler skew over per-phase timings (allgathered
   across processes every check period: max/median per phase plus the
   worst-shard ordinal), and an eval-loss anomaly detector
   (spike / NaN / plateau flags fed from ``engine.train``).

Everything flows through :meth:`HealthRegistry.summary` → bench.py's
JSON line / the multichip dryrun's ``MULTICHIP-HEALTH`` line →
``obs/export.render_openmetrics`` (``lgbmtpu_health_*`` families,
validated by ``tools/check_health.py``) → Chrome trace spans
(``health/drift_check`` etc. when the tracer runs).

Disabled cost: with the registry off and ``tpu_health=off`` every
hot-path entry (``note_program_call``, the boosting hooks) is a single
attribute check; manifests are captured at trace time only (compile
cost, never per iteration) and the trained model is bit-identical with
health on or off (asserted by tests/test_health.py).

Enabled via ``LGBM_TPU_HEALTH=1``, ``global_health.enable()``, or
implicitly with the metrics registry (``LGBM_TPU_TELEMETRY`` / the
telemetry callbacks); the ``tpu_health=off/warn/error`` knob arms the
per-booster drift/NaN alarms independently of full telemetry.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .metrics import global_metrics


class HealthError(RuntimeError):
    """Base class of the structured training-health alarms."""


class DriftError(HealthError):
    """Replicated state diverged across mesh shards (tpu_health=error)."""


class NonFiniteError(HealthError):
    """NaN/Inf gradients, hessians or scores detected (tpu_health=error)."""


# eval-anomaly detector tuning: a point is a "spike" when it is worse
# than the rolling median by this fraction of the median's magnitude;
# a "plateau" when the best improvement over the window is below the
# absolute epsilon for a full window
_EVAL_WINDOW = 8
_EVAL_SPIKE_FRAC = 0.5
_EVAL_PLATEAU_EPS = 1e-9


def _tree_bytes(tree) -> int:
    """Total payload bytes of a pytree of (traced or concrete) arrays."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        total += int(size) * int(np.dtype(dtype).itemsize)
    return total


def tree_depths(split_leaf: np.ndarray) -> np.ndarray:
    """Depth of every leaf of a grown tree from its ``split_leaf``
    record (creation order: split s splits leaf ``split_leaf[s]``, the
    right child becomes leaf s+1 — learner.TreeArrays numbering).
    Returns the per-leaf depth array (root-only tree -> [0])."""
    split_leaf = np.asarray(split_leaf).reshape(-1)
    n_leaves = int(np.sum(split_leaf >= 0)) + 1
    depth = np.zeros(max(n_leaves, 1), np.int32)
    nxt = 1
    for s in range(split_leaf.shape[0]):
        leaf = int(split_leaf[s])
        if leaf < 0:
            continue
        d = depth[leaf] + 1
        depth[leaf] = d
        depth[nxt] = d
        nxt += 1
    return depth[:max(n_leaves, 1)]


class HealthRegistry:
    """Global training-health state (see module docstring)."""

    def __init__(self) -> None:
        self.enabled = os.environ.get(
            "LGBM_TPU_HEALTH", "") not in ("", "0")
        self._lock = threading.Lock()
        # --- collective accounting
        # program tag -> tuple of (site_tag, op, nbytes, loop_factor)
        self._manifests: Dict[str, Tuple[Tuple[str, str, int, int], ...]] = {}
        self._trace_stack: List[List[Tuple[str, str, int, int]]] = []
        # site tag -> {"op", "calls", "bytes"} — RUNTIME-attributed
        self.runtime: Dict[str, Dict[str, Any]] = {}
        self.program_calls: Dict[str, int] = {}
        # op -> {"seconds", "bytes", "count"} from the timed microprobe
        self.probe: Dict[str, Dict[str, float]] = {}
        # --- straggler
        self.straggler: Optional[Dict[str, Any]] = None
        self._straggler_base: Dict[str, float] = {}
        # --- drift
        self.drift_checks = 0
        self.drift_mismatches = 0
        self.last_drift: Optional[Dict[str, Any]] = None
        self._digest_cache: Dict[Any, Any] = {}
        # --- NaN/Inf sentinel
        self.nonfinite: Dict[str, int] = {}
        self.nonfinite_iterations = 0
        self.last_nonfinite: Optional[Dict[str, Any]] = None
        # --- eval anomaly detector
        self._eval_hist: Dict[str, List[float]] = {}
        self.eval_anomalies: Dict[str, int] = {}
        self.last_eval_anomaly: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._manifests.clear()
            self._trace_stack.clear()
            self.runtime.clear()
            self.program_calls.clear()
            self.probe.clear()
        self.straggler = None
        self._straggler_base = {}
        self.drift_checks = 0
        self.drift_mismatches = 0
        self.last_drift = None
        self.nonfinite = {}
        self.nonfinite_iterations = 0
        self.last_nonfinite = None
        self._eval_hist.clear()
        self.eval_anomalies = {}
        self.last_eval_anomaly = None

    # ------------------------------------------------------------------
    # collective manifests (trace time) + runtime attribution (per call)
    def begin_program_trace(self, tag: str) -> None:
        """Open a manifest-capture frame: collective wrappers traced
        under this program body register into it. Trace-time only."""
        with self._lock:
            self._trace_stack.append([])

    def end_program_trace(self, tag: str) -> None:
        with self._lock:
            if not self._trace_stack:
                return
            sites = self._trace_stack.pop()
            # nested program traces (rare) attribute to the inner tag;
            # re-traces for new shapes replace the manifest wholesale
            self._manifests[tag] = tuple(sites)

    def register_site(self, site_tag: str, op: str, nbytes: int,
                      loop_factor: int = 1) -> None:
        """Record one traced collective call site into the open
        manifest (no-op outside a program trace). ``loop_factor`` is
        the static trip count when the site sits inside a ``lax.scan``
        body — traced once, issued `loop_factor` times per run."""
        with self._lock:
            if self._trace_stack:
                self._trace_stack[-1].append(
                    (site_tag, op, int(nbytes), max(int(loop_factor), 1)))

    def note_program_call(self, tag: str) -> None:
        """One runtime invocation of an instrumented program: multiply
        its manifest into the per-tag runtime counters. Callers guard
        on ``enabled`` — this is the per-call hot path."""
        manifest = self._manifests.get(tag)
        with self._lock:
            self.program_calls[tag] = self.program_calls.get(tag, 0) + 1
            if not manifest:
                return
            for site_tag, op, nbytes, factor in manifest:
                ent = self.runtime.get(site_tag)
                if ent is None:
                    ent = self.runtime[site_tag] = {
                        "op": op, "calls": 0, "bytes": 0}
                ent["calls"] += factor
                ent["bytes"] += nbytes * factor

    # ------------------------------------------------------------------
    # timed collective microprobe (device-synchronized wall time)
    def probe_collectives(self, mesh, payload_rows: int = 4096,
                          reps: int = 2) -> Optional[Dict[str, Any]]:
        """Run a timed psum + all_gather microprobe over `mesh` and
        record a measured seconds/bytes rate per op. The probe is its
        own tiny shard_map program (raw lax collectives, so it never
        pollutes the runtime site counters); the first rep warms the
        compile, later reps are timed behind ``block_until_ready`` —
        honest device-synchronized wall time, where the in-program
        collectives can never be separately host-timed."""
        if getattr(mesh, "size", 1) <= 1:
            return None
        import time

        import jax
        from .trace import global_tracer

        programs = self._probe_programs(mesh, payload_rows)
        n = payload_rows * mesh.size
        # byte accounting matches the runtime wrappers' convention
        # (_tree_bytes of the per-shard RESULT): psum's per-shard
        # reduced output is the local slice, all_gather's is W x it —
        # so the derived seconds-per-byte rate prices runtime bytes
        # consistently in _estimate_collective_share
        op_bytes = {"psum": payload_rows * 4, "all_gather": n * 4,
                    "psum_scatter": payload_rows * 4 // mesh.size}
        out: Dict[str, Any] = {}
        with global_tracer.span("health/collective_probe"):
            for op, (fn, x) in programs.items():
                try:
                    jax.block_until_ready(fn(x))  # compile/cache + warm
                    t0 = time.perf_counter()
                    for _ in range(max(reps, 1)):
                        r = fn(x)
                    jax.block_until_ready(r)
                    dt = (time.perf_counter() - t0) / max(reps, 1)
                except Exception:  # probes must never take training down
                    continue
                nbytes = op_bytes[op]
                with self._lock:
                    ent = self.probe.setdefault(
                        op, {"seconds": 0.0, "bytes": 0, "count": 0})
                    ent["seconds"] += dt
                    ent["bytes"] += nbytes
                    ent["count"] += 1
                out[op] = {"seconds": round(dt, 6), "bytes": nbytes}
        return out or None

    def _probe_programs(self, mesh, payload_rows: int):
        """Jitted probe programs cached per (mesh, payload) — repeated
        probes (every learner setup + the dryrun emit) must reuse the
        first pair of compiles, like _digest_program below."""
        key = ("probe", mesh.axis_names, tuple(mesh.devices.flat),
               int(payload_rows))
        cached = self._digest_cache.get(key)
        if cached is not None:
            return cached
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import shard_map as _shard_map

        axis = mesh.axis_names[0]
        x = jnp.ones((payload_rows * mesh.size,), jnp.float32)

        def _psum(v):
            return lax.psum(v, axis)

        def _gather(v):
            return lax.all_gather(v, axis)

        def _scatter(v):
            return lax.psum_scatter(v, axis, scatter_dimension=0,
                                    tiled=True)

        cached = {
            "psum": (jax.jit(_shard_map(
                _psum, mesh=mesh, in_specs=P(axis), out_specs=P())), x),
            "all_gather": (jax.jit(_shard_map(
                _gather, mesh=mesh, in_specs=P(axis),
                out_specs=P(axis))), x),
            "psum_scatter": (jax.jit(_shard_map(
                _scatter, mesh=mesh, in_specs=P(axis),
                out_specs=P(axis))), x),
        }
        self._digest_cache[key] = cached
        return cached

    # ------------------------------------------------------------------
    # straggler attribution
    @staticmethod
    def straggler_from_matrix(phase_names: Sequence[str],
                              matrix) -> Dict[str, Any]:
        """Skew stats from a [n_hosts, n_phases] per-phase seconds
        matrix: per phase the max and median across hosts, their ratio
        (the straggler skew), and the worst-shard ordinal. Pure math —
        the allgather plumbing lives in :meth:`straggler_probe`."""
        m = np.asarray(matrix, np.float64)
        if m.ndim == 1:
            m = m[None, :]
        phases: Dict[str, Any] = {}
        max_skew, worst_phase = 0.0, None
        for j, name in enumerate(phase_names):
            col = m[:, j]
            med = float(np.median(col))
            mx = float(np.max(col))
            # epsilon floor keeps the ratio finite (JSON-safe) when a
            # phase ran on a minority of hosts only
            skew = mx / max(med, 1e-9) if mx > 0 else 1.0
            worst = int(np.argmax(col))
            phases[name] = {"max_s": round(mx, 6),
                            "median_s": round(med, 6),
                            "skew": round(skew, 4),
                            "worst": worst}
            if skew > max_skew:
                max_skew, worst_phase = skew, name
        return {"n_hosts": int(m.shape[0]), "phases": phases,
                "max_skew": round(max_skew, 4), "worst_phase": worst_phase}

    def straggler_probe(self, phase_seconds: Optional[Dict[str, float]]
                        = None) -> Optional[Dict[str, Any]]:
        """Gather each host's per-phase self-times accumulated since the
        last probe (from the tracer aggregation) across processes and
        publish the skew summary. Single-process meshes share one host,
        so the matrix degenerates to one row (skew 1.0) — the plumbing
        still runs, which is what the multichip dryrun proves."""
        if phase_seconds is None:
            from .trace import global_tracer
            agg = global_tracer.summary()
            cur = {n: a["self_seconds"] for n, a in agg.items()}
            phase_seconds = {n: cur[n] - self._straggler_base.get(n, 0.0)
                             for n in cur}
            self._straggler_base = cur
        names = sorted(n for n, v in phase_seconds.items() if v > 0)
        if not names:
            return self.straggler
        vec = np.asarray([phase_seconds[n] for n in names], np.float64)
        matrix = vec[None, :]
        try:
            import jax
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils as mh
                # phase sets can differ across hosts (host-0-only driver
                # work, a phase still pending on a straggler); column j
                # must mean the same phase everywhere, so a name-list
                # signature rides along and any disagreement falls back
                # to local-only stats instead of misattributing skew
                import zlib
                sig = float(zlib.crc32("\n".join(names).encode()))
                gathered = np.asarray(mh.process_allgather(
                    np.concatenate([vec, [sig]])))
                if np.all(gathered[:, -1] == sig):
                    matrix = gathered[:, :-1]
        except Exception:
            pass  # a failed gather degrades to local-only stats
        fresh = self.straggler_from_matrix(names, matrix)
        # merge across probes: keep every phase's WORST observed skew —
        # a straggler that showed up once must stay visible in the
        # run-final summary, not be overwritten by a later quiet probe
        prev = self.straggler
        if prev:
            merged = dict(prev["phases"])
            for name, ph in fresh["phases"].items():
                old = merged.get(name)
                if old is None or ph.get("skew", 0) >= old.get("skew", 0):
                    merged[name] = ph
            worst = max(merged, key=lambda n: merged[n].get("skew", 0.0))
            fresh = {"n_hosts": fresh["n_hosts"], "phases": merged,
                     "max_skew": merged[worst].get("skew", 1.0),
                     "worst_phase": worst}
        self.straggler = fresh
        return fresh

    # ------------------------------------------------------------------
    # cross-shard drift sentinels
    def _digest_program(self, mesh, leaves, treedef):
        """Cached jitted shard_map digest: each shard computes a [L, 4]
        digest of its LOCAL copy of every (replicated) leaf — sum,
        sum-of-squares, abs-sum, nonfinite count, with nonfinite values
        zeroed from the sums so identical-NaN state still matches —
        then all_gathers to [W, L, 4] for the host comparison."""
        avals = tuple((tuple(x.shape), str(x.dtype)) for x in leaves)
        key = (mesh.axis_names, tuple(mesh.devices.flat), treedef, avals)
        fn = self._digest_cache.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import shard_map as _shard_map

        axis = mesh.axis_names[0]

        def body(*xs):
            digs = []
            for x in xs:
                xf = jnp.asarray(x).astype(jnp.float32).ravel()
                finite = jnp.isfinite(xf)
                xz = jnp.where(finite, xf, 0.0)
                digs.append(jnp.stack([
                    jnp.sum(xz), jnp.sum(xz * xz), jnp.sum(jnp.abs(xz)),
                    jnp.sum((~finite).astype(jnp.float32))]))
            return lax.all_gather(jnp.stack(digs), axis)  # [W, L, 4]

        fn = jax.jit(_shard_map(
            body, mesh=mesh, in_specs=tuple(P() for _ in leaves),
            out_specs=P()))
        self._digest_cache[key] = fn
        return fn

    def drift_digests(self, mesh, tree) -> np.ndarray:
        """[W, n_leaves, 4] per-shard digests of a replicated pytree."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        fn = self._digest_program(mesh, leaves, treedef)
        return np.asarray(fn(*leaves))

    def check_drift(self, mesh, arrays: Dict[str, Any], *,
                    mode: str = "warn",
                    where: str = "") -> List[Dict[str, Any]]:
        """Digest every named replicated pytree across the mesh and
        compare shards. Returns the mismatch records; ``mode="warn"``
        logs and counts them, ``mode="error"`` raises
        :class:`DriftError` naming the diverged shard ordinals."""
        from .trace import global_tracer
        mismatches: List[Dict[str, Any]] = []
        with global_tracer.span("health/drift_check"):
            for name, tree in arrays.items():
                digs = self.drift_digests(mesh, tree)
                self.drift_checks += 1
                # majority vote: the modal digest row is "truth", every
                # other shard is divergent — a single bad replica is
                # named even when shard 0 is the bad one (W >= 3). With
                # no strict majority (e.g. a diverged 2-shard mesh) the
                # replicas are indistinguishable: every shard is
                # reported rather than arbitrarily blaming one.
                keys = [digs[w].tobytes() for w in range(digs.shape[0])]
                counts: Dict[bytes, int] = {}
                for k in keys:
                    counts[k] = counts.get(k, 0) + 1
                majority = max(counts, key=lambda k: counts[k])
                if len(counts) > 1 and counts[majority] * 2 <= len(keys):
                    bad = list(range(len(keys)))
                else:
                    bad = [w for w, k in enumerate(keys) if k != majority]
                if bad:
                    mismatches.append({
                        "name": name, "shards": bad,
                        "where": where,
                        "digests": digs.reshape(digs.shape[0], -1)
                        .tolist()})
        if mismatches:
            self.drift_mismatches += len(mismatches)
            self.last_drift = {"where": where,
                               "mismatches": [
                                   {k: m[k] for k in ("name", "shards")}
                                   for m in mismatches]}
            detail = "; ".join(
                f"{m['name']}: shard(s) {m['shards']} diverged"
                for m in mismatches)
            msg = (f"cross-shard drift detected"
                   f"{' at ' + where if where else ''}: {detail} "
                   f"(replicated state is no longer replicated — "
                   f"see obs/health.py)")
            if str(mode).lower() == "error":
                raise DriftError(msg)
            from .. import log
            log.warning(msg)
        return mismatches

    # ------------------------------------------------------------------
    # NaN/Inf sentinel
    def note_sentinel(self, iteration: int, counts: Dict[str, int], *,
                      mode: str = "warn", where: str = "") -> None:
        """Record one iteration's nonfinite counts (grad/hess/scores).
        Zero counts are free book-keeping; any nonzero count flags the
        iteration and, under ``mode="error"``, raises
        :class:`NonFiniteError` — within the iteration that produced
        it, not many evals later."""
        total = 0
        for kind, v in counts.items():
            v = int(v)
            if v:
                self.nonfinite[kind] = self.nonfinite.get(kind, 0) + v
            total += v
        if not total:
            return
        self.nonfinite_iterations += 1
        self.last_nonfinite = {"iteration": int(iteration), **{
            k: int(v) for k, v in counts.items()}}
        detail = ", ".join(f"{k}={int(v)}" for k, v in counts.items() if v)
        msg = (f"non-finite training state at iteration {iteration}"
               f"{' (' + where + ')' if where else ''}: {detail} "
               f"entries are NaN/Inf")
        if str(mode).lower() == "error":
            raise NonFiniteError(msg)
        from .. import log
        log.warning(msg)

    # ------------------------------------------------------------------
    # eval-loss anomaly detector
    def note_eval(self, iteration: int, data_name: str, metric_name: str,
                  value: float, higher_better: bool = False) -> List[str]:
        """Feed one eval result; returns the anomaly flags it raised
        (subset of {"nan", "spike", "plateau"})."""
        key = f"{data_name}/{metric_name}"
        hist = self._eval_hist.setdefault(key, [])
        flags: List[str] = []
        v = float(value) if value is not None else float("nan")
        if not math.isfinite(v):
            flags.append("nan")
        else:
            window = hist[-_EVAL_WINDOW:]
            if len(window) >= 4:
                med = float(np.median(window))
                worse = (med - v) if higher_better else (v - med)
                if math.isfinite(med) and worse > max(
                        abs(med), 1e-12) * _EVAL_SPIKE_FRAC:
                    flags.append("spike")
            if len(window) >= _EVAL_WINDOW:
                vals = window + [v]
                # flat over a full window in either direction
                if (max(vals) - min(vals)) < _EVAL_PLATEAU_EPS:
                    flags.append("plateau")
            hist.append(v)
            if len(hist) > 4 * _EVAL_WINDOW:
                del hist[:-2 * _EVAL_WINDOW]
        for f in flags:
            self.eval_anomalies[f] = self.eval_anomalies.get(f, 0) + 1
        if flags:
            self.last_eval_anomaly = {
                "iteration": int(iteration), "metric": key,
                "value": v if math.isfinite(v) else None, "flags": flags}
        return flags

    def note_evals(self, iteration: int, results) -> None:
        """Feed an engine evaluation_result_list
        ([(data_name, metric, value, higher_better), ...])."""
        for item in results or ():
            try:
                name, metric, value, hib = item[0], item[1], item[2], \
                    bool(item[3])
            except (IndexError, TypeError):
                continue
            self.note_eval(iteration, name, metric, value, hib)

    # ------------------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """The bench/MULTICHIP-JSON shaped health summary; sections with
        nothing recorded are omitted (a disabled run returns {})."""
        out: Dict[str, Any] = {}
        with self._lock:
            runtime = {t: dict(v) for t, v in self.runtime.items()}
            probe = {op: dict(v) for op, v in self.probe.items()}
        if runtime:
            out["collectives"] = runtime
        if probe:
            for op, ent in probe.items():
                secs = ent.get("seconds", 0.0)
                ent["bytes_per_s"] = (round(ent["bytes"] / secs, 1)
                                      if secs > 0 else 0.0)
            out["collective_probe"] = probe
        est = self._estimate_collective_share(runtime, probe)
        if est:
            out["collectives_est"] = est
        if self.straggler:
            out["straggler"] = self.straggler
        if self.drift_checks or self.drift_mismatches:
            out["drift"] = {"checks": self.drift_checks,
                            "mismatches": self.drift_mismatches}
            if self.last_drift:
                out["drift"]["last"] = self.last_drift
        if self.nonfinite or self.nonfinite_iterations:
            out["nonfinite"] = {**self.nonfinite,
                                "flagged_iterations":
                                self.nonfinite_iterations}
            if self.last_nonfinite:
                out["nonfinite"]["last"] = self.last_nonfinite
        if self.eval_anomalies:
            out["eval"] = dict(self.eval_anomalies)
            if self.last_eval_anomaly:
                out["eval"]["last"] = self.last_eval_anomaly
        return out

    @staticmethod
    def _estimate_collective_share(runtime, probe) -> Optional[Dict]:
        """Estimated collective seconds (runtime bytes x the probe's
        measured per-byte rate) as a share of total measured training
        time — the quantity tools/check_perf_gate.py's health check
        holds to a ceiling. None when either side is missing."""
        if not runtime or not probe:
            return None
        est = 0.0
        for ent in runtime.values():
            p = probe.get(ent.get("op"))
            if not p or p.get("bytes", 0) <= 0:
                continue
            rate = p["seconds"] / p["bytes"]  # measured seconds per byte
            est += ent.get("bytes", 0) * rate
        if est <= 0:
            return None
        train_s = sum(r.get("iteration_seconds", 0.0)
                      for r in global_metrics.history)
        out = {"est_seconds": round(est, 6)}
        if train_s > 0:
            out["train_seconds"] = round(train_s, 4)
            out["time_share"] = round(min(est / train_s, 1.0), 4)
        return out


global_health = HealthRegistry()

# env-enabled telemetry (LGBM_TPU_TELEMETRY) arms health too, matching
# obs/memory.py's watermarks and obs/xla.py's introspector
if global_metrics.enabled:
    global_health.enable()


# ---------------------------------------------------------------------------
# collective call-site wrappers (used by learner.py / parallel/*)
def psum(x, axis_name: str, *, tag: str, loop_factor: int = 1):
    """``lax.psum`` with health accounting: keeps the PR-1 trace-time
    counters and registers the site (tag, bytes, scan trip count) into
    the enclosing program's manifest for runtime attribution."""
    from jax import lax
    out = lax.psum(x, axis_name)
    nbytes = _tree_bytes(out)
    global_metrics.note_collective("psum", nbytes)
    global_health.register_site(tag, "psum", nbytes, loop_factor)
    return out


def all_gather(x, axis_name: str, *, tag: str, loop_factor: int = 1):
    """``lax.all_gather`` (pytree-mapped) with health accounting; byte
    counts are of the GATHERED result (W x the local payload)."""
    import jax
    from jax import lax
    out = jax.tree_util.tree_map(
        lambda a: lax.all_gather(a, axis_name), x)
    nbytes = _tree_bytes(out)
    global_metrics.note_collective("all_gather", nbytes)
    global_health.register_site(tag, "all_gather", nbytes, loop_factor)
    return out


def psum_scatter(x, axis_name: str, *, tag: str, loop_factor: int = 1,
                 scatter_dimension: int = 0):
    """``lax.psum_scatter`` (tiled) with health accounting: each shard
    receives only its owned 1/W slice of the reduced tensor — the
    ReduceScatter of data_parallel_tree_learner.cpp:287. Byte counts
    are of the per-shard RESULT slice (the wrapper convention), which
    is what makes the psum->psum_scatter reduction visible as a W-fold
    drop in the runtime counters."""
    from jax import lax
    out = lax.psum_scatter(x, axis_name,
                           scatter_dimension=scatter_dimension, tiled=True)
    nbytes = _tree_bytes(out)
    global_metrics.note_collective("psum_scatter", nbytes)
    global_health.register_site(tag, "psum_scatter", nbytes, loop_factor)
    return out


def note_gspmd_collective(op: str, nbytes: int, *, tag: str,
                          loop_factor: int = 1) -> None:
    """Account a collective the XLA GSPMD partitioner inserts on its own
    (no lax call site to wrap — e.g. the reduce-scatter materializing a
    feature-sharded histogram constraint). Called at trace time from
    inside the instrumented program so the modeled bytes land in the
    same manifest/runtime counters as the explicit wrappers."""
    global_metrics.note_collective(op, int(nbytes))
    global_health.register_site(tag, op, int(nbytes), loop_factor)
