"""EFB feature-bundling tests (ref: src/io/dataset.cpp:112 FindGroups,
:251 FastFeatureBundling; tests/python_package_test coverage of
enable_bundle)."""

import numpy as np
import pytest

from conftest import make_binary

import lightgbm_tpu as lgb
from lightgbm_tpu.bundling import (build_bundled_matrix, find_bundles,
                                   expand_bundle_hist)


def _one_hot_data(n=1200, cats=10, dense=2, seed=0):
    """dense informative features + a strict one-hot block (bundleable)."""
    r = np.random.RandomState(seed)
    labels = r.randint(0, cats, n)
    X = np.zeros((n, dense + cats))
    X[:, :dense] = r.randn(n, dense)
    X[np.arange(n), dense + labels] = 1.0
    logit = X[:, 0] + 2.0 * (labels % 3 == 0) - 1.0
    y = (logit + 0.3 * r.randn(n) > 0).astype(np.float32)
    return X, y


def test_find_bundles_one_hot():
    r = np.random.RandomState(0)
    labels = r.randint(0, 6, 500)
    masks = np.zeros((6, 500), bool)
    masks[labels, np.arange(500)] = True
    nb = np.full(6, 3)
    bundles = find_bundles(masks, nb, max_conflict_rate=0.0)
    assert len(bundles) == 1
    assert sorted(bundles[0]) == list(range(6))


def test_find_bundles_conflicting_stay_apart():
    masks = np.ones((3, 100), bool)  # all features always nonzero
    nb = np.full(3, 8)
    bundles = find_bundles(masks, nb, max_conflict_rate=0.0)
    assert len(bundles) == 3


def test_bundled_matrix_roundtrip_decode():
    """Encode + logical decode must reproduce the original bins."""
    r = np.random.RandomState(1)
    f, n = 5, 300
    nb = np.array([4, 6, 3, 5, 4], np.int64)
    labels = r.randint(0, f, n)
    bins = np.zeros((f, n), np.uint8)
    for i in range(n):  # one nonzero feature per row -> exclusive
        bins[labels[i], i] = r.randint(1, nb[labels[i]])
    bundled, info = build_bundled_matrix(bins, nb, [list(range(f))])
    assert bundled.shape[0] == 1
    # decode each feature column
    for feat in range(f):
        col = bundled[0].astype(np.int64)
        off = info.offset_of[feat]
        width = nb[feat] - 1
        logical = np.where((col >= off) & (col < off + width),
                           col - off + 1, 0)
        np.testing.assert_array_equal(logical, bins[feat])


def test_expand_bundle_hist_matches_unbundled():
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import build_histogram
    r = np.random.RandomState(2)
    f, n, B = 4, 400, 8
    nb_arr = np.array([5, 7, 4, 8], np.int64)
    labels = r.randint(0, f, n)
    bins = np.zeros((f, n), np.uint8)
    for i in range(n):
        bins[labels[i], i] = r.randint(1, nb_arr[labels[i]])
    grad = r.randn(n).astype(np.float32)
    hess = np.abs(r.randn(n)).astype(np.float32)
    mask = (r.rand(n) < 0.9).astype(np.float32)

    ref = build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                          jnp.asarray(hess), jnp.asarray(mask),
                          max_bins=B, impl="xla")
    bundled, info = build_bundled_matrix(bins, nb_arr, [list(range(f))])
    hg = build_histogram(jnp.asarray(bundled), jnp.asarray(grad),
                         jnp.asarray(hess), jnp.asarray(mask),
                         max_bins=info.num_bundle_bins, impl="xla")
    totals = jnp.sum(hg[0], axis=0)
    out = expand_bundle_hist(hg, jnp.asarray(info.group_of),
                             jnp.asarray(info.offset_of),
                             jnp.asarray(nb_arr.astype(np.int32)), B, totals)
    # compare only each feature's own valid bins (beyond-range rows hold
    # neighbors' bins by design and are masked downstream)
    ref_np, out_np = np.asarray(ref), np.asarray(out)
    for feat in range(f):
        valid = int(nb_arr[feat])
        np.testing.assert_allclose(out_np[feat, :valid], ref_np[feat, :valid],
                                   rtol=1e-5, atol=1e-5)


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty(len(s))
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0.5
    return (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) / (
        pos.sum() * (~pos).sum())


def test_bundled_training_matches_unbundled():
    """Same data trained with and without EFB: storage shrinks, model
    quality and predictions agree (splits are on the same logical
    histograms; only the bin-0 row arrives via subtraction)."""
    X, y = _one_hot_data()
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "min_data_in_bin": 1}

    ds_b = lgb.Dataset(X, label=y, params=dict(params))
    ds_b.construct()
    assert ds_b._binned.bundle_info is not None
    assert ds_b._binned.bins_fm.shape[0] < ds_b._binned.num_features

    ds_u = lgb.Dataset(X, label=y,
                       params=dict(params, enable_bundle=False))
    ds_u.construct()
    assert ds_u._binned.bundle_info is None

    bst_b = lgb.train(dict(params), ds_b, num_boost_round=15)
    bst_u = lgb.train(dict(params, enable_bundle=False), ds_u,
                      num_boost_round=15)
    pb, pu = bst_b.predict(X), bst_u.predict(X)
    assert _auc(y, pb) > 0.8
    np.testing.assert_allclose(pb, pu, rtol=2e-2, atol=2e-3)


def test_bundled_valid_set_and_exact_grower():
    """Valid sets bin through the train bundles; the exact (tpu_wave_max=0)
    grower shares the decode path."""
    X, y = _one_hot_data(seed=3)
    Xv, yv = _one_hot_data(seed=4)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "min_data_in_bin": 1,
              "tpu_wave_max": 0}
    ds = lgb.Dataset(X, label=y, params=dict(params))
    dv = lgb.Dataset(Xv, label=yv, reference=ds, params=dict(params))
    record = {}
    lgb.train(dict(params), ds, num_boost_round=10, valid_sets=[dv],
              valid_names=["v"],
              callbacks=[lgb.record_evaluation(record)])
    logloss = record["v"]["binary_logloss"]
    assert logloss[-1] < logloss[0]


def test_bundled_binary_roundtrip(tmp_path):
    X, y = _one_hot_data(seed=5)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1,
                                         "min_data_in_bin": 1})
    ds.construct()
    assert ds._binned.bundle_info is not None
    path = tmp_path / "b.bin"
    ds.save_binary(path)
    loaded = lgb.Dataset(str(path), params={"verbosity": -1})
    loaded.construct()
    lb = loaded._binned
    assert lb.bundle_info is not None
    np.testing.assert_array_equal(lb.bins_fm, ds._binned.bins_fm)
    np.testing.assert_array_equal(lb.bundle_info.group_of,
                                  ds._binned.bundle_info.group_of)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "min_data_in_leaf": 5}, loaded, num_boost_round=5)
    assert bst.num_trees() == 5
