"""Span tracer: nested named spans with self-time attribution.

The structured successor of the flat wall-clock ``timer.Timer``
(ref: Common::Timer / FunctionTimer, include/LightGBM/utils/common.h:
980,1044). Spans nest via a real stack, so a parent's *self* time —
total minus time spent inside child spans — is attributable, the way
the reference's ``FunctionTimer`` frames nest inside each other.

jax device work is asynchronous: a span that must charge dispatched
device work to itself passes ``block=`` a pytree of jax arrays (or a
zero-arg callable returning one) which is waited on before the clock
stops.

Export formats:
- ``summary()``   — aggregated {name: {seconds, self_seconds, count}}.
- ``export_chrome(path)`` — Chrome trace-event JSON (load in
  chrome://tracing or Perfetto); validated by ``tools/check_trace.py``.

Enabling:
- ``LGBM_TPU_TRACE=/path.json`` in the environment (or the
  ``trace_output`` train param) enables the tracer and writes the
  Chrome trace at interpreter exit.
- ``LGBM_TPU_TIMETAG=1`` (or ``enable()``) prints the aggregated
  summary at exit, exactly like the reference's atexit dump.

When disabled, ``span()`` returns a shared no-op context manager —
no allocation, one attribute check.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class _NullSpan:
    """Shared do-nothing context manager for the disabled fast path."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanFrame:
    """One live span (context manager); exists only while enabled."""
    __slots__ = ("tracer", "name", "block", "args", "t0", "child_ns")

    def __init__(self, tracer: "Tracer", name: str, block,
                 args=None) -> None:
        self.tracer = tracer
        self.name = name
        self.block = block
        self.args = args
        self.child_ns = 0

    def __enter__(self) -> "_SpanFrame":
        self.tracer._stack.append(self)
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        if self.block is not None and exc_type is None:
            # skip the device wait when the body raised (the timing is
            # garbage then, and block= lambdas commonly reference names
            # bound inside the span body); never let telemetry mask the
            # user's exception
            try:
                import jax
                b = self.block
                jax.block_until_ready(b() if callable(b) else b)
            except Exception:
                pass
        t1 = time.perf_counter_ns()
        tracer = self.tracer
        stack = tracer._stack
        # tolerate a mispaired exit (exception unwound past frames)
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        dur = t1 - self.t0
        if stack:
            stack[-1].child_ns += dur
        tracer._record(self.name, self.t0, dur, dur - self.child_ns,
                       len(stack), self.args)
        return False


class Tracer:
    """Nested named spans, aggregation, and Chrome trace export."""

    # raw-event cap: aggregation (summary/report) is unbounded either
    # way; past the cap only the per-span Chrome events stop growing
    # (~50 B each -> ~50 MB ceiling), with the drop count reported in
    # the export. Keeps week-long LGBM_TPU_TIMETAG runs flat in memory.
    MAX_EVENTS = 1_000_000

    def __init__(self) -> None:
        self.enabled = False
        self.print_summary_at_exit = False
        self.trace_path: Optional[str] = None
        self._tls = threading.local()  # per-thread span stack
        self._lock = threading.Lock()  # guards _events/_agg/sinks
        self._dropped_events = 0
        # completed spans:
        # (name, start_ns, dur_ns, self_ns, depth, tid, args-or-None)
        self._events: List[tuple] = []
        self._thread_names: Dict[int, str] = {}  # tid -> thread name
        self._agg: Dict[str, List[float]] = {}  # name -> [total, self, count]
        self._sinks: List[Any] = []  # callables(name, dur_s, self_s)
        self._exported = False
        self._printed = False

        env_path = os.environ.get("LGBM_TPU_TRACE", "")
        if env_path:
            self.enable(path=env_path)
        if os.environ.get("LGBM_TPU_TIMETAG", "") not in ("", "0"):
            self.enable(print_at_exit=True)

    @property
    def _stack(self) -> List["_SpanFrame"]:
        """This thread's open-span stack — spans on one thread must never
        pop frames opened by another (e.g. a predict worker thread while
        the main thread trains)."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # ------------------------------------------------------------------
    def enable(self, path: Optional[str] = None,
               print_at_exit: bool = False) -> None:
        self.enabled = True
        if path:
            self.trace_path = path
        if print_at_exit:
            self.print_summary_at_exit = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        self._stack.clear()
        with self._lock:
            self._events.clear()
            self._thread_names.clear()
            self._agg.clear()
            self._dropped_events = 0
        self._exported = False
        self._printed = False

    def add_sink(self, sink) -> None:
        """Register a callable(name, dur_seconds, self_seconds) invoked on
        every completed span (the metrics registry hooks phase times here)."""
        if sink not in self._sinks:
            self._sinks.append(sink)

    # ------------------------------------------------------------------
    def span(self, name: str, block: Optional[Any] = None,
             args: Optional[Dict[str, Any]] = None):
        """Time a nested phase. Disabled mode returns a shared no-op
        context manager (no allocation). `args` (a small dict) rides
        into the Chrome event's ``args`` — the request-tracing link
        fields (trace_id, batch_id, ...) travel this way."""
        if not self.enabled:
            return _NULL_SPAN
        return _SpanFrame(self, name, block, args)

    def add_complete_span(self, name: str, start_ns: int, dur_ns: int,
                          args: Optional[Dict[str, Any]] = None,
                          tid: Optional[int] = None) -> None:
        """Record an already-timed span retroactively (the serve path
        emits per-request and per-batch attribution spans after the
        fact, once queue-wait and device time are known). Does not
        touch the live span stack and does not fire sinks — these are
        attribution records, not training phases."""
        if not self.enabled:
            return
        self._record(name, int(start_ns), int(dur_ns), int(dur_ns), 0,
                     args, tid=tid, fire_sinks=False)

    def _record(self, name: str, start_ns: int, dur_ns: int, self_ns: int,
                depth: int, args: Optional[Dict[str, Any]] = None,
                tid: Optional[int] = None, fire_sinks: bool = True) -> None:
        if tid is None:
            tid = threading.get_ident()
        with self._lock:
            if tid not in self._thread_names:
                # for the thread_name metadata events in chrome_events
                self._thread_names[tid] = threading.current_thread().name
            if len(self._events) < self.MAX_EVENTS:
                self._events.append((name, start_ns, dur_ns, self_ns,
                                     depth, tid, args))
            else:
                self._dropped_events += 1
            agg = self._agg.get(name)
            if agg is None:
                agg = self._agg[name] = [0.0, 0.0, 0]
            agg[0] += dur_ns * 1e-9
            agg[1] += self_ns * 1e-9
            agg[2] += 1
        if fire_sinks:
            for sink in self._sinks:
                sink(name, dur_ns * 1e-9, self_ns * 1e-9)

    # ------------------------------------------------------------------
    def summary(self) -> Dict[str, Dict[str, float]]:
        """Aggregated per-phase totals, reference-dump shaped."""
        with self._lock:
            items = [(n, list(a)) for n, a in self._agg.items()]
        return {name: {"seconds": agg[0], "self_seconds": agg[1],
                       "count": agg[2]}
                for name, agg in sorted(items)}

    def report(self) -> str:
        s = self.summary()
        lines = ["LightGBM-TPU phase timers:"]
        for name in sorted(s, key=lambda n: s[n]["seconds"], reverse=True):
            lines.append(f"  {name:32s} {s[name]['seconds']:10.3f}s "
                         f"(self {s[name]['self_seconds']:8.3f}s) "
                         f"x{int(s[name]['count'])}")
        return "\n".join(lines)

    def _metadata_events(self, pid: int, tids,
                         thread_names: Dict[int, str]) -> List[Dict[str, Any]]:
        """Chrome ``ph: "M"`` metadata: process_name / process_labels
        (host + shard identity from hostenv / the metrics meta) and a
        thread_name per recorded thread — without these, multi-thread
        and multi-process traces are anonymous pid/tid soup in
        Perfetto."""
        from ..hostenv import host_labels
        labels = host_labels()
        proc = "lightgbm_tpu"
        if "process_index" in labels:
            proc += (f" host{labels['process_index']}"
                     f"/{labels.get('num_processes', '?')}")
        try:  # shard labels (set by parallel learner setup)
            from .metrics import global_metrics
            mesh = global_metrics.meta.get("mesh_size")
            if mesh:
                labels["mesh_size"] = str(mesh)
            learner = global_metrics.meta.get("tree_learner")
            if learner:
                labels["tree_learner"] = str(learner)
        except Exception:
            pass
        events = [
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": proc}},
            {"name": "process_labels", "ph": "M", "pid": pid,
             "args": {"labels": ",".join(
                 f"{k}={v}" for k, v in sorted(labels.items()))}},
        ]
        for tid in sorted(tids):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": thread_names.get(tid, f"thread-{tid}")}})
        return events

    def chrome_events(self) -> List[Dict[str, Any]]:
        """Completed spans as Chrome trace-event dicts (phase "X",
        microsecond timestamps), sorted by start time — prefixed with
        the ``ph: "M"`` process/thread metadata events. Device slices
        captured by obs/profile.py ride along on their own pid so host
        spans and device programs render on one Perfetto timeline."""
        pid = os.getpid()
        with self._lock:
            snapshot = list(self._events)
            names = dict(self._thread_names)
        events = self._metadata_events(pid, {e[5] for e in snapshot}
                                       | set(names), names)
        for name, start_ns, dur_ns, self_ns, depth, tid, extra in sorted(
                snapshot, key=lambda e: e[1]):
            args: Dict[str, Any] = {"self_us": self_ns / 1000.0,
                                    "depth": depth}
            if extra:
                args.update(extra)
            events.append({
                "name": name,
                "ph": "X",
                "ts": start_ns / 1000.0,
                "dur": dur_ns / 1000.0,
                "pid": pid,
                "tid": tid,
                "args": args,
            })
        try:
            # device lane (same perf_counter_ns clock as the spans; the
            # profile registry rebases profiler-sourced slices onto it)
            from .profile import global_profile
            events.extend(global_profile.device_lane_events(pid + 1))
        except Exception:
            pass  # the host trace must export even if the lane cannot
        return events

    def export_chrome(self, path: str) -> None:
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "lightgbm_tpu.obs.trace",
                          "dropped_events": self._dropped_events},
        }
        with open(path, "w") as fh:
            json.dump(doc, fh)

    def print_summary_once(self) -> None:
        """Print the aggregated report (at most once) — the USE_TIMETAG
        dump. Does NOT export the trace file; that stays an exit-time
        (or explicit export_chrome) action so a mid-run summary print
        cannot truncate the trace."""
        if self.print_summary_at_exit and self._agg and not self._printed:
            self._printed = True
            print(self.report(), flush=True)

    # ------------------------------------------------------------------
    def _at_exit(self) -> None:
        if self.trace_path and self._events and not self._exported:
            self._exported = True
            try:
                self.export_chrome(self.trace_path)
            except OSError as exc:
                print(f"[LightGBM-TPU] trace export to "
                      f"{self.trace_path} failed: {exc}", flush=True)
        self.print_summary_once()


global_tracer = Tracer()
atexit.register(global_tracer._at_exit)
