"""Model refit: keep tree structures, refresh leaf values on new data.

(ref: GBDT::RefitTree gbdt.cpp:267, Booster.refit basic.py,
refit_decay_rate in config.h.)
"""

from __future__ import annotations

import numpy as np


def refit_booster(booster, data, label, decay_rate: float = 0.9,
                  weight=None):
    """Returns a new Booster whose leaf values are
    decay * old + (1 - decay) * new_leaf_optimum on `data`."""
    from .basic import Booster, Dataset

    data = np.asarray(data, np.float64)
    label = np.asarray(label, np.float32)

    new_booster = Booster(model_str=booster.model_to_string())
    if any(getattr(t, "is_linear", False)
           for t in new_booster._loaded.trees):
        raise ValueError(
            "refit is not supported for linear trees "
            "(ref: the reference refuses RefitTree on linear models)")
    gbdt = booster._gbdt
    if gbdt is not None:
        cfg = gbdt.config
    else:
        # booster was loaded from file: rebuild config from the model's
        # stored parameters block (ref: task=refit loads input_model)
        from .config import Config
        loaded = booster._loaded
        params = dict(loaded.params)
        params["objective"] = loaded.objective_str.split()[0]
        if loaded.num_class > 1:
            params["num_class"] = loaded.num_class
        cfg = Config.from_params(params)

    # leaf assignments of new data under existing structures
    leaf_preds = booster.predict(data, pred_leaf=True)  # [N, T]
    if leaf_preds.ndim == 1:
        leaf_preds = leaf_preds[:, None]

    # fresh objective on the new labels
    from .dataset import Metadata
    from .objectives import create_objective
    meta = Metadata(len(label))
    meta.set_label(label)
    if weight is not None:
        meta.set_weight(weight)
    obj = create_objective(cfg)
    obj.init(meta, len(label))

    import jax.numpy as jnp
    k = (gbdt.num_tree_per_iteration if gbdt is not None
         else max(new_booster._loaded.num_tree_per_iteration, 1))
    scores = np.zeros((k, len(label)), np.float32)
    t = 0
    loaded = new_booster._loaded
    for it in range(loaded.num_iterations):
        for ki in range(k):
            tree = loaded.trees[it * k + ki]
            if hasattr(obj, "get_gradients_multi"):
                g_all, h_all = obj.get_gradients_multi(jnp.asarray(scores))
                grad = np.asarray(g_all[ki], np.float64)
                hess = np.asarray(h_all[ki], np.float64)
            else:
                g, h = obj.get_gradients(jnp.asarray(scores[ki]))
                grad, hess = np.asarray(g, np.float64), np.asarray(h, np.float64)
            leaves = leaf_preds[:, t]
            lam = cfg.lambda_l2
            for leaf in range(tree.num_leaves):
                m = leaves == leaf
                if not m.any():
                    continue
                gsum, hsum = grad[m].sum(), hess[m].sum()
                new_out = -gsum / (hsum + lam) * tree.shrinkage
                tree.leaf_value[leaf] = (decay_rate * tree.leaf_value[leaf]
                                         + (1.0 - decay_rate) * new_out)
            tree.pack_version += 1  # leaf edits invalidate packed slots
            scores[ki] += tree.leaf_value[leaves]
            t += 1
    return new_booster
